"""Functional neural-network layers for evolvable policies.

Parity: reference ``neuroevolution/net/layers.py`` (568 LoC) — utility layers
``Clip, Bin, Slice, Round, Apply`` (``layers.py:24-159``), **single-step**
RNN/LSTM cells with explicit hidden state (``layers.py:161-281``),
``FeedForwardNet`` (``layers.py:283-374``), ``StructuredControlNet``
(``layers.py:377-467``), ``LocomotorNet`` (``layers.py:470-568``).

TPU-first design: instead of torch ``nn.Module`` objects with implicit
parameter storage, every layer here is a lightweight *combinator* with three
pure methods::

    params = layer.init(key)          # parameter pytree
    state  = layer.initial_state()    # recurrent-state pytree (None if stateless)
    y, new_state = layer.apply(params, x, state)

Composition uses ``>>`` exactly like the reference's ``str_to_net`` DSL.
Because apply is pure, policies vmap over both population (batched params) and
environments (batched observations) natively — what the reference builds from
``torch.func.functional_call`` + vmap (``net/functional.py:46-259``).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "Module",
    "Sequential",
    "Linear",
    "Bias",
    "Apply",
    "Tanh",
    "ReLU",
    "Sigmoid",
    "Softmax",
    "Clip",
    "Bin",
    "Slice",
    "Round",
    "RNN",
    "LSTM",
    "FeedForwardNet",
    "StructuredControlNet",
    "LocomotorNet",
]


class Module:
    """Base combinator."""

    def init(self, key) -> Any:
        return ()

    def initial_state(self) -> Any:
        return None

    def apply(self, params, x, state=None) -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError

    @property
    def is_stateful(self) -> bool:
        return self.initial_state() is not None

    def __rshift__(self, other: "Module") -> "Sequential":
        mine = list(self.modules) if isinstance(self, Sequential) else [self]
        theirs = list(other.modules) if isinstance(other, Sequential) else [other]
        return Sequential(mine + theirs)

    def __call__(self, params, x, state=None):
        return self.apply(params, x, state)


class Sequential(Module):
    """Sequence of layers threading hidden state through the stateful ones —
    the analog of the reference's ``net/multilayered.py`` container."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)

    def init(self, key):
        keys = jax.random.split(key, max(len(self.modules), 1))
        return tuple(m.init(k) for m, k in zip(self.modules, keys))

    def initial_state(self):
        states = tuple(m.initial_state() for m in self.modules)
        if all(s is None for s in states):
            return None
        return states

    def apply(self, params, x, state=None):
        if state is None:
            state = tuple(m.initial_state() for m in self.modules)
        new_states = []
        for m, p, s in zip(self.modules, params, state):
            x, ns = m.apply(p, x, s)
            new_states.append(ns)
        out_state = tuple(new_states)
        if all(s is None for s in out_state):
            out_state = None
        return x, out_state

    def __repr__(self):
        return " >> ".join(repr(m) for m in self.modules)


class FrozenModule(Module):
    """A module with its parameters baked in: ``init`` returns an empty
    parameter pytree and ``apply`` ignores the params argument. Used by
    ``to_policy`` exports so a deployable policy carries its evolved weights
    (the analog of the reference's parameterized-net wrappers,
    ``gymne.py:646-672``)."""

    def __init__(self, module: Module, params):
        self._module = module
        self._params = params

    def init(self, key):
        return ()

    def initial_state(self):
        return self._module.initial_state()

    def apply(self, params, x, state=None):
        return self._module.apply(self._params, x, state)

    @property
    def wrapped_module(self) -> Module:
        return self._module

    @property
    def wrapped_params(self):
        return self._params

    def __repr__(self):
        return f"FrozenModule({self._module!r})"


class Linear(Module):
    """Dense layer; initialization mirrors torch's ``nn.Linear`` default
    (uniform +-1/sqrt(fan_in)), keeping evolved-policy scales comparable to
    the reference."""

    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.bias = bool(bias)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        bound = 1.0 / math.sqrt(self.in_features)
        W = jax.random.uniform(
            k1, (self.out_features, self.in_features), minval=-bound, maxval=bound
        )
        if self.bias:
            b = jax.random.uniform(k2, (self.out_features,), minval=-bound, maxval=bound)
            return {"weight": W, "bias": b}
        return {"weight": W}

    def apply(self, params, x, state=None):
        y = x @ params["weight"].T
        if self.bias:
            y = y + params["bias"]
        return y, state

    def __repr__(self):
        return f"Linear({self.in_features}, {self.out_features}, bias={self.bias})"


class Bias(Module):
    """Learnable additive bias vector."""

    def __init__(self, num_features: int):
        self.num_features = int(num_features)

    def init(self, key):
        return {"bias": jnp.zeros(self.num_features)}

    def apply(self, params, x, state=None):
        return x + params["bias"], state

    def __repr__(self):
        return f"Bias({self.num_features})"


class Apply(Module):
    """Apply an arbitrary elementwise function, optionally with kwargs
    (reference ``layers.py:129-159``)."""

    def __init__(self, fn: Callable, **kwargs):
        self._fn = fn
        self._kwargs = kwargs

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return self._fn(x, **self._kwargs), state

    def __repr__(self):
        name = getattr(self._fn, "__name__", repr(self._fn))
        return f"Apply({name})"


class Tanh(Apply):
    def __init__(self):
        super().__init__(jnp.tanh)

    def __reduce__(self):
        # the stored jax ufunc object does not pickle by qualified name on
        # this jax; rebuilding from the (argless) constructor does — keeps
        # whole-searcher checkpoints (resilience.RunCheckpointer) working
        return (Tanh, ())

    def __repr__(self):
        return "Tanh()"


class ReLU(Apply):
    def __init__(self):
        super().__init__(jax.nn.relu)

    def __reduce__(self):
        return (ReLU, ())

    def __repr__(self):
        return "ReLU()"


class Sigmoid(Apply):
    def __init__(self):
        super().__init__(jax.nn.sigmoid)

    def __reduce__(self):
        return (Sigmoid, ())

    def __repr__(self):
        return "Sigmoid()"


class Softmax(Apply):
    def __init__(self, axis: int = -1):
        super().__init__(jax.nn.softmax, axis=axis)

    def __reduce__(self):
        return (Softmax, (self._kwargs.get("axis", -1),))

    def __repr__(self):
        return "Softmax()"


class Clip(Module):
    """Clip into [lb, ub] (reference ``layers.py:24-52``)."""

    def __init__(self, lb: float, ub: float):
        self.lb = float(lb)
        self.ub = float(ub)

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return jnp.clip(x, self.lb, self.ub), state

    def __repr__(self):
        return f"Clip({self.lb}, {self.ub})"


class Bin(Module):
    """Binarize: values map to lb or ub by sign (reference ``layers.py:55-88``)."""

    def __init__(self, lb: float, ub: float):
        self.lb = float(lb)
        self.ub = float(ub)

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return jnp.where(x <= 0, self.lb, self.ub), state

    def __repr__(self):
        return f"Bin({self.lb}, {self.ub})"


class Slice(Module):
    """Take ``x[..., from_index:to_index]`` (reference ``layers.py:91-121``)."""

    def __init__(self, from_index: int, to_index: int):
        self.from_index = int(from_index)
        self.to_index = int(to_index)

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return x[..., self.from_index : self.to_index], state

    def __repr__(self):
        return f"Slice({self.from_index}, {self.to_index})"


class Round(Module):
    """Round to n decimal digits (reference ``layers.py:124-126``)."""

    def __init__(self, ndigits: int = 0):
        self.ndigits = int(ndigits)
        self._scale = 10.0**self.ndigits

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return jnp.round(x * self._scale) / self._scale, state

    def __repr__(self):
        return f"Round({self.ndigits})"


class RNN(Module):
    """Single-step Elman RNN cell with explicit hidden state in/out
    (reference ``layers.py:161-218``)."""

    def __init__(self, input_size: int, hidden_size: int, nonlinearity: str = "tanh"):
        self.input_size = int(input_size)
        self.hidden_size = int(hidden_size)
        if nonlinearity not in ("tanh", "relu"):
            raise ValueError(f"Unsupported nonlinearity: {nonlinearity}")
        self.nonlinearity = nonlinearity

    def init(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        bound = 1.0 / math.sqrt(self.hidden_size)
        u = lambda k, shape: jax.random.uniform(k, shape, minval=-bound, maxval=bound)  # noqa: E731
        return {
            "W_ih": u(k1, (self.hidden_size, self.input_size)),
            "W_hh": u(k2, (self.hidden_size, self.hidden_size)),
            "b_ih": u(k3, (self.hidden_size,)),
            "b_hh": u(k4, (self.hidden_size,)),
        }

    def initial_state(self):
        return jnp.zeros(self.hidden_size)

    def apply(self, params, x, state=None):
        if state is None:
            state = jnp.zeros(x.shape[:-1] + (self.hidden_size,), dtype=x.dtype)
        pre = (
            x @ params["W_ih"].T
            + params["b_ih"]
            + state @ params["W_hh"].T
            + params["b_hh"]
        )
        h = jnp.tanh(pre) if self.nonlinearity == "tanh" else jax.nn.relu(pre)
        return h, h

    def __repr__(self):
        return f"RNN({self.input_size}, {self.hidden_size})"


class LSTM(Module):
    """Single-step LSTM cell with explicit (h, c) state
    (reference ``layers.py:221-281``)."""

    def __init__(self, input_size: int, hidden_size: int):
        self.input_size = int(input_size)
        self.hidden_size = int(hidden_size)

    def init(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        bound = 1.0 / math.sqrt(self.hidden_size)
        u = lambda k, shape: jax.random.uniform(k, shape, minval=-bound, maxval=bound)  # noqa: E731
        return {
            "W_ih": u(k1, (4 * self.hidden_size, self.input_size)),
            "W_hh": u(k2, (4 * self.hidden_size, self.hidden_size)),
            "b_ih": u(k3, (4 * self.hidden_size,)),
            "b_hh": u(k4, (4 * self.hidden_size,)),
        }

    def initial_state(self):
        return (jnp.zeros(self.hidden_size), jnp.zeros(self.hidden_size))

    def apply(self, params, x, state=None):
        if state is None:
            h = jnp.zeros(x.shape[:-1] + (self.hidden_size,), dtype=x.dtype)
            c = jnp.zeros(x.shape[:-1] + (self.hidden_size,), dtype=x.dtype)
        else:
            h, c = state
        gates = x @ params["W_ih"].T + params["b_ih"] + h @ params["W_hh"].T + params["b_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return h, (h, c)

    def __repr__(self):
        return f"LSTM({self.input_size}, {self.hidden_size})"


class FeedForwardNet(Module):
    """MLP from ``(size, activation)`` layer specs
    (reference ``layers.py:283-374``)."""

    LengthActTuple = Tuple[int, Callable]

    def __init__(self, input_size: int, layers: Sequence):
        self.input_size = int(input_size)
        modules = []
        in_size = self.input_size
        for layer in layers:
            if isinstance(layer, (tuple, list)):
                size, act = (layer[0], layer[1]) if len(layer) >= 2 else (layer[0], None)
            else:
                size, act = layer, None
            modules.append(Linear(in_size, int(size)))
            if act is not None:
                modules.append(act if isinstance(act, Module) else Apply(act))
            in_size = int(size)
        self._seq = Sequential(modules)

    def init(self, key):
        return self._seq.init(key)

    def apply(self, params, x, state=None):
        return self._seq.apply(params, x, state)

    def __repr__(self):
        return f"FeedForwardNet({self._seq!r})"


def tanh_mlp(input_size: int, output_size: int, hidden: Sequence) -> Module:
    """The ``Linear >> Tanh >> ... >> Linear`` policy stack every benchmark
    surface shares (bench_common's BENCH_HIDDEN policies, the program
    ledger's gate-shape programs) — ONE builder, so the architecture the
    perf gate measures cannot drift from the one bench.py benchmarks."""
    sizes = [int(h) for h in hidden]
    if not sizes:
        return Linear(int(input_size), int(output_size))
    net = Linear(int(input_size), sizes[0])
    for a, b in zip(sizes, sizes[1:] + [None]):
        net = net >> Tanh()
        net = net >> Linear(a, b if b is not None else int(output_size))
    return net


class StructuredControlNet(Module):
    """Structured Control Net (Srouji, Zhang, Salakhutdinov 2018): the sum of
    a linear module and a nonlinear MLP module
    (reference ``layers.py:377-467``)."""

    def __init__(
        self,
        *,
        in_features: int,
        out_features: int,
        num_layers: int,
        hidden_size: int,
        bias: bool = True,
        nonlinearity: Callable = jnp.tanh,
    ):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self._linear = Linear(self.in_features, self.out_features, bias=bias)
        modules = []
        in_size = self.in_features
        for _ in range(int(num_layers)):
            modules.append(Linear(in_size, int(hidden_size), bias=bias))
            modules.append(Apply(nonlinearity))
            in_size = int(hidden_size)
        modules.append(Linear(in_size, self.out_features, bias=bias))
        self._nonlinear = Sequential(modules)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {"linear": self._linear.init(k1), "nonlinear": self._nonlinear.init(k2)}

    def apply(self, params, x, state=None):
        y1, _ = self._linear.apply(params["linear"], x)
        y2, _ = self._nonlinear.apply(params["nonlinear"], x)
        return y1 + y2, state

    def __repr__(self):
        return f"StructuredControlNet(in={self.in_features}, out={self.out_features})"


class LocomotorNet(Module):
    """Locomotor Net (Liu, Ostrow, Srouji et al.): linear module plus a
    sinusoidal nonlinear module ``sum_i sin(Wx + b) * amplitude``
    (reference ``layers.py:470-568``)."""

    def __init__(self, *, in_features: int, out_features: int, bias: bool = True, num_sinusoids: int = 16):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.num_sinusoids = int(num_sinusoids)
        self._linear = Linear(self.in_features, self.out_features, bias=bias)
        self._sinusoids = [
            Linear(self.in_features, self.out_features, bias=bias)
            for _ in range(self.num_sinusoids)
        ]

    def init(self, key):
        keys = jax.random.split(key, self.num_sinusoids + 2)
        return {
            "linear": self._linear.init(keys[0]),
            "sinusoids": tuple(m.init(k) for m, k in zip(self._sinusoids, keys[1:])),
            "amplitudes": jax.random.normal(keys[-1], (self.num_sinusoids,)) * 0.1,
        }

    def apply(self, params, x, state=None):
        y, _ = self._linear.apply(params["linear"], x)
        for i, m in enumerate(self._sinusoids):
            s, _ = m.apply(params["sinusoids"][i], x)
            y = y + jnp.sin(s) * params["amplitudes"][i]
        return y, state

    def __repr__(self):
        return f"LocomotorNet(in={self.in_features}, out={self.out_features}, S={self.num_sinusoids})"
