"""Network misc helpers (reference ``net/misc.py:26-116``)."""

from __future__ import annotations

from .functional import count_parameters, fill_parameters, parameter_vector

__all__ = ["count_parameters", "fill_parameters", "parameter_vector", "device_of_module"]


def device_of_module(params) -> str:
    """Device of a parameter pytree (reference ``net/misc.py:104``); in JAX
    this is informational only — placement is controlled by shardings."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    for leaf in leaves:
        if hasattr(leaf, "devices"):
            devices = leaf.devices()
            return str(next(iter(devices)))
    return "cpu"
