"""``str_to_net``: the network-specification mini-DSL.

Parity: reference ``net/parser.py:218-344`` (parser internals 88-216): a
string like ``"Linear(obs_length, 16) >> Tanh() >> Linear(16, act_length)"``
is parsed via Python ``ast`` into a network. Names are resolved against the
layer registry (``net/layers.py``); free variables are substituted from
keyword arguments (the reference's constants mechanism, e.g. ``obs_length`` /
``act_length`` / ``obs_space`` provided by GymNE-style problems).
"""

from __future__ import annotations

import ast
from typing import Any, Dict

from . import layers as _layers
from .layers import Module

__all__ = ["str_to_net", "NetParsingError"]


class NetParsingError(Exception):
    """Parse/eval failure with source context (reference ``parser.py:31-85``)."""

    def __init__(self, message: str, source: str = ""):
        super().__init__(f"{message}\n  while parsing: {source}" if source else message)


_SAFE_FUNCS: Dict[str, Any] = {
    name: getattr(_layers, name)
    for name in _layers.__all__
    if isinstance(getattr(_layers, name), type) and issubclass(getattr(_layers, name), Module)
}
# math helpers allowed inside layer arguments
_SAFE_CONSTS: Dict[str, Any] = {
    "True": True,
    "False": False,
    "None": None,
    "inf": float("inf"),
    "nan": float("nan"),
    "pi": 3.141592653589793,
}


def _eval_node(node: ast.AST, names: Dict[str, Any], source: str) -> Any:
    if isinstance(node, ast.Expression):
        return _eval_node(node.body, names, source)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.RShift):
        left = _eval_node(node.left, names, source)
        right = _eval_node(node.right, names, source)
        if not isinstance(left, Module) or not isinstance(right, Module):
            raise NetParsingError(">> expects layers on both sides", source)
        return left >> right
    if isinstance(node, ast.BinOp):
        left = _eval_node(node.left, names, source)
        right = _eval_node(node.right, names, source)
        ops = {
            ast.Add: lambda a, b: a + b,
            ast.Sub: lambda a, b: a - b,
            ast.Mult: lambda a, b: a * b,
            ast.Div: lambda a, b: a / b,
            ast.FloorDiv: lambda a, b: a // b,
            ast.Pow: lambda a, b: a**b,
            ast.Mod: lambda a, b: a % b,
        }
        for op_type, fn in ops.items():
            if isinstance(node.op, op_type):
                return fn(left, right)
        raise NetParsingError(f"Unsupported operator: {ast.dump(node.op)}", source)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_eval_node(node.operand, names, source)
    if isinstance(node, ast.Call):
        if not isinstance(node.func, ast.Name):
            raise NetParsingError("Only simple layer names may be called", source)
        func_name = node.func.id
        if func_name not in _SAFE_FUNCS:
            raise NetParsingError(
                f"Unknown layer type: {func_name!r} (known: {sorted(_SAFE_FUNCS)})", source
            )
        func = _SAFE_FUNCS[func_name]
        args = [_eval_node(a, names, source) for a in node.args]
        kwargs = {kw.arg: _eval_node(kw.value, names, source) for kw in node.keywords}
        return func(*args, **kwargs)
    if isinstance(node, ast.Name):
        if node.id in names:
            return names[node.id]
        if node.id in _SAFE_CONSTS:
            return _SAFE_CONSTS[node.id]
        raise NetParsingError(f"Unknown name: {node.id!r}", source)
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        return [_eval_node(e, names, source) for e in node.elts]
    raise NetParsingError(f"Unsupported syntax: {ast.dump(node)}", source)


def str_to_net(s: str, **constants) -> Module:
    """Parse a network string into a Module (reference ``parser.py:218``).

    Example::

        net = str_to_net(
            "Linear(obs_length, 16) >> Tanh() >> Linear(16, act_length)",
            obs_length=4,
            act_length=2,
        )
    """
    try:
        tree = ast.parse(s.strip(), mode="eval")
    except SyntaxError as e:
        raise NetParsingError(f"Invalid network string: {e}", s) from e
    result = _eval_node(tree, dict(constants), s)
    if not isinstance(result, Module):
        raise NetParsingError(
            f"Network string evaluated to {type(result).__name__}, not a layer", s
        )
    return result
