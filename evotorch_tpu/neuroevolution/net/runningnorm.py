"""Online observation normalization with mergeable statistics.

Parity: reference ``net/runningnorm.py:47-621`` (device-aware running
mean/stdev with masked updates and ``to_layer()``) and
``net/runningstat.py:25-152`` (the numpy Welford-style counterpart used for
actor-delta sync).

TPU-first design: the statistics are a *pytree* ``(count, sum, sum_of_squares)``
— a ``CollectedStats`` dataclass — so they can

- ride inside a jitted ``lax.scan`` rollout (the reference updates stats
  statefully in Python between env steps; here they are part of the scan
  carry, SURVEY.md §7 hard-parts),
- merge across mesh shards with a single ``psum`` (the reference's
  main<->actor delta-sync protocol, ``gymne.py:524-573``, collapses to a
  collective).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...tools.pytree import pytree_dataclass

__all__ = [
    "CollectedStats",
    "RunningNorm",
    "RunningStat",
    "group_stats_init",
    "group_stats_normalize",
    "group_stats_update",
    "stats_slot",
]


@pytree_dataclass
class CollectedStats:
    count: jnp.ndarray  # scalar
    sum: jnp.ndarray  # (n,)
    sum_of_squares: jnp.ndarray  # (n,)

    @property
    def mean(self) -> jnp.ndarray:
        return self.sum / jnp.maximum(self.count, 1.0)

    @property
    def stdev(self) -> jnp.ndarray:
        c = jnp.maximum(self.count, 2.0)
        var = (self.sum_of_squares - (self.sum**2) / c) / (c - 1.0)
        return jnp.sqrt(jnp.maximum(var, 1e-8))


def _stats_init(n: int, dtype=jnp.float32) -> CollectedStats:
    return CollectedStats(
        count=jnp.zeros((), dtype=dtype),
        sum=jnp.zeros(n, dtype=dtype),
        sum_of_squares=jnp.zeros(n, dtype=dtype),
    )


def stats_update(stats: CollectedStats, obs: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> CollectedStats:
    """Accumulate a batch of observations ``(B, n)``; rows where ``mask`` is
    False are ignored (reference masked update, ``runningnorm.py:300-380``).
    Pure function — usable inside jit/scan."""
    obs = jnp.atleast_2d(obs)
    if mask is not None:
        m = mask[:, None].astype(obs.dtype)
        obs = obs * m
        n_new = jnp.sum(mask.astype(obs.dtype))
    else:
        n_new = jnp.asarray(obs.shape[0], dtype=obs.dtype)
    return CollectedStats(
        count=stats.count + n_new,
        sum=stats.sum + jnp.sum(obs, axis=0),
        sum_of_squares=stats.sum_of_squares + jnp.sum(obs**2, axis=0),
    )


def stats_merge(a: CollectedStats, b: CollectedStats) -> CollectedStats:
    """Merge two stats (the reference's ``update(other)``,
    ``runningstat.py:76``); equals elementwise addition, which is why a psum
    across shards is the distributed merge."""
    return CollectedStats(
        count=a.count + b.count,
        sum=a.sum + b.sum,
        sum_of_squares=a.sum_of_squares + b.sum_of_squares,
    )


def stats_psum(stats: CollectedStats, axis_name: str) -> CollectedStats:
    """All-reduce the stats across a mesh axis (inside shard_map)."""
    return jax.tree_util.tree_map(lambda x: jax.lax.psum(x, axis_name), stats)


def stats_normalize(stats: CollectedStats, obs: jnp.ndarray, *, clip: Optional[Tuple[float, float]] = None) -> jnp.ndarray:
    """Normalize observations by the collected stats; identity while count<2."""
    safe = stats.count >= 2
    normalized = (obs - stats.mean) / stats.stdev
    if clip is not None:
        lo, hi = clip
        normalized = jnp.clip(normalized, lo, hi)
    return jnp.where(safe, normalized, obs)


# -------------------- per-group (stacked) statistics ------------------------
# A STACKED CollectedStats — count (G,), sum (G, n), sum_of_squares (G, n) —
# holds one independent observation-normalization slot per accounting group
# (tenant, island, ...). The refill rollout engine detects the stacked form
# by the count's rank and switches every stat touch to these helpers, so N
# tenants sharing one compiled program each normalize by THEIR OWN history
# (per-tenant obs-norm isolation, docs/serving.md). The leaves stay plain
# arrays, so psum/merge/checkpoint plumbing lifts unchanged.


def group_stats_init(num_groups: int, n: int, dtype=jnp.float32) -> CollectedStats:
    """A stacked stats pytree with ``num_groups`` independent zero slots."""
    return CollectedStats(
        count=jnp.zeros(int(num_groups), dtype=dtype),
        sum=jnp.zeros((int(num_groups), n), dtype=dtype),
        sum_of_squares=jnp.zeros((int(num_groups), n), dtype=dtype),
    )


def group_stats_update(
    stats: CollectedStats,
    obs: jnp.ndarray,
    groups: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    num_groups: int,
) -> CollectedStats:
    """Accumulate a batch of observations ``(B, n)`` into stacked stats,
    crediting row ``i`` to slot ``groups[i]`` (masked rows contribute
    nothing — the same masking contract as :func:`stats_update`). Pure;
    usable inside jit/scan."""
    obs = jnp.atleast_2d(obs)
    groups = jnp.asarray(groups, dtype=jnp.int32)
    if mask is not None:
        m = mask.astype(obs.dtype)
    else:
        m = jnp.ones(obs.shape[0], dtype=obs.dtype)
    obs_m = obs * m[:, None]
    return CollectedStats(
        count=stats.count
        + jax.ops.segment_sum(m, groups, num_segments=int(num_groups)),
        sum=stats.sum
        + jax.ops.segment_sum(obs_m, groups, num_segments=int(num_groups)),
        sum_of_squares=stats.sum_of_squares
        + jax.ops.segment_sum(obs_m**2, groups, num_segments=int(num_groups)),
    )


def group_stats_normalize(
    stats: CollectedStats, obs: jnp.ndarray, groups: jnp.ndarray
) -> jnp.ndarray:
    """Normalize each observation row by ITS group's slot (identity while
    that slot's count < 2) — the per-lane gather form of
    :func:`stats_normalize` over stacked stats."""
    groups = jnp.asarray(groups, dtype=jnp.int32)
    cnt = jnp.maximum(stats.count, 1.0)[:, None]
    mean = stats.sum / cnt
    c2 = jnp.maximum(stats.count, 2.0)[:, None]
    var = (stats.sum_of_squares - (stats.sum**2) / c2) / (c2 - 1.0)
    stdev = jnp.sqrt(jnp.maximum(var, 1e-8))
    safe = (stats.count >= 2.0)[groups]
    normalized = (obs - mean[groups]) / stdev[groups]
    return jnp.where(safe[:, None], normalized, obs)


def stats_slot(stats: CollectedStats, g: int) -> CollectedStats:
    """One group's slot of a stacked stats pytree as a plain (unstacked)
    :class:`CollectedStats` — what a tenant sees as "its" statistics."""
    return CollectedStats(
        count=stats.count[g],
        sum=stats.sum[g],
        sum_of_squares=stats.sum_of_squares[g],
    )


class RunningNorm:
    """Stateful convenience wrapper over the pure stats functions
    (reference ``net/runningnorm.py:47``)."""

    def __init__(self, shape, dtype=jnp.float32, *, min_variance: float = 1e-8, clip: Optional[Tuple[float, float]] = None):
        if isinstance(shape, int):
            shape = (shape,)
        (self._n,) = tuple(shape)
        self._dtype = dtype
        self._min_variance = float(min_variance)
        self._clip = clip
        self.stats = _stats_init(self._n, dtype)

    @property
    def shape(self):
        return (self._n,)

    @property
    def count(self) -> float:
        return float(self.stats.count)

    @property
    def mean(self) -> jnp.ndarray:
        return self.stats.mean

    @property
    def stdev(self) -> jnp.ndarray:
        return self.stats.stdev

    def update(self, x, mask=None):
        """Accumulate an observation (1-D) or a batch (2-D); or merge another
        RunningNorm/RunningStat/CollectedStats."""
        if isinstance(x, RunningNorm):
            self.stats = stats_merge(self.stats, x.stats)
        elif isinstance(x, CollectedStats):
            self.stats = stats_merge(self.stats, x)
        elif isinstance(x, RunningStat):
            other = CollectedStats(
                count=jnp.asarray(float(x.count), dtype=self._dtype),
                sum=jnp.asarray(x.sum, dtype=self._dtype),
                sum_of_squares=jnp.asarray(x.sum_of_squares, dtype=self._dtype),
            )
            self.stats = stats_merge(self.stats, other)
        else:
            x = jnp.asarray(x, dtype=self._dtype)
            if x.ndim == 1:
                x = x[None, :]
            self.stats = stats_update(self.stats, x, mask)

    def normalize(self, x) -> jnp.ndarray:
        return stats_normalize(self.stats, jnp.asarray(x, dtype=self._dtype), clip=self._clip)

    def __call__(self, x) -> jnp.ndarray:
        return self.normalize(x)

    def update_and_normalize(self, x, mask=None) -> jnp.ndarray:
        self.update(x, mask)
        return self.normalize(x)

    def to_layer(self):
        """Freeze into an ObsNormLayer-style module (reference
        ``runningnorm.py:580-621``)."""
        from .rl import ObsNormLayer

        return ObsNormLayer(mean=self.mean, stdev=self.stdev, clip=self._clip)

    def reset(self):
        self.stats = _stats_init(self._n, self._dtype)

    def __repr__(self):
        return f"RunningNorm(shape={self.shape}, count={self.count})"


class RunningStat:
    """Host-side numpy counterpart (reference ``net/runningstat.py:25-152``),
    kept for non-jitted (classic gym) rollouts. Mergeable via ``update``."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._count = 0
        self._sum: Optional[np.ndarray] = None
        self._sum_of_squares: Optional[np.ndarray] = None

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> np.ndarray:
        return self._sum

    @property
    def sum_of_squares(self) -> np.ndarray:
        return self._sum_of_squares

    @property
    def mean(self) -> np.ndarray:
        return self._sum / self._count

    @property
    def stdev(self) -> np.ndarray:
        c = max(self._count, 2)
        var = (self._sum_of_squares - (self._sum**2) / c) / (c - 1)
        return np.sqrt(np.maximum(var, 1e-8))

    def update(self, x):
        if isinstance(x, RunningStat):
            if x._count == 0:
                return
            if self._count == 0:
                self._count = x._count
                self._sum = x._sum.copy()
                self._sum_of_squares = x._sum_of_squares.copy()
            else:
                self._count += x._count
                self._sum = self._sum + x._sum
                self._sum_of_squares = self._sum_of_squares + x._sum_of_squares
            return
        x = np.asarray(x, dtype=np.float64)
        if x.ndim == 1:
            x = x[None, :]
        if self._count == 0:
            self._sum = np.zeros(x.shape[-1], dtype=np.float64)
            self._sum_of_squares = np.zeros(x.shape[-1], dtype=np.float64)
        self._count += x.shape[0]
        self._sum += x.sum(axis=0)
        self._sum_of_squares += (x**2).sum(axis=0)

    def normalize(self, x) -> np.ndarray:
        if self._count < 2:
            return np.asarray(x)
        return (np.asarray(x) - self.mean) / self.stdev

    def to_delta(self, since: "RunningStat") -> "RunningStat":
        """Stats collected since ``since`` (the actor-delta of the reference's
        sync protocol, ``gymne.py:548-573``)."""
        delta = RunningStat()
        if self._count > since._count:
            delta._count = self._count - since._count
            delta._sum = self._sum - (since._sum if since._sum is not None else 0.0)
            delta._sum_of_squares = self._sum_of_squares - (
                since._sum_of_squares if since._sum_of_squares is not None else 0.0
            )
        return delta

    def __repr__(self):
        return f"RunningStat(count={self._count})"
