"""Stateful-module shims.

Parity: reference ``net/statefulmodule.py:21-107`` (``StatefulModule`` /
``ensure_stateful`` hide the recurrent h in/out pair) and
``net/multilayered.py:21-74`` (sequential container threading hidden state).

In this framework every layer already follows the explicit
``apply(params, x, state) -> (y, state)`` protocol and ``Sequential`` threads
states natively, so these are thin aliases kept for API familiarity.
"""

from __future__ import annotations

from .layers import Module, Sequential

__all__ = ["StatefulModule", "ensure_stateful", "MultiLayered"]

StatefulModule = Module
MultiLayered = Sequential


def ensure_stateful(module: Module) -> Module:
    """All modules are stateful-protocol already; returns the module
    (reference ``statefulmodule.py:95-107``)."""
    if not isinstance(module, Module):
        raise TypeError(f"Expected a Module, got {type(module)}")
    return module
