"""RL policy-wrapping helpers.

Parity: reference ``net/rl.py`` — ``ActClipWrapperModule`` (``rl.py:130``),
``ObsNormWrapperModule`` (``rl.py:166``), ``AliveBonusScheduleWrapper``
(``rl.py:199``), plus the env-step shims ``reset_env``/``take_step_in_env``
(``rl.py:63-128``) for host-side gymnasium loops.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .layers import Module

__all__ = [
    "ObsNormLayer",
    "ActClipLayer",
    "ObsNormWrapperModule",
    "ActClipWrapperModule",
    "alive_bonus_for_step",
    "alive_bonus_for_step_host",
    "reset_env",
    "take_step_in_env",
]


class ObsNormLayer(Module):
    """Frozen observation normalization (reference ``runningnorm.py:to_layer``
    and ``rl.py:166``)."""

    def __init__(self, *, mean, stdev, clip: Optional[Tuple[float, float]] = None):
        self.mean = jnp.asarray(mean)
        self.stdev = jnp.asarray(stdev)
        self.clip = clip

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        y = (x - self.mean) / self.stdev
        if self.clip is not None:
            y = jnp.clip(y, self.clip[0], self.clip[1])
        return y, state

    def __repr__(self):
        return f"ObsNormLayer(n={self.mean.shape[-1]})"


class ActClipLayer(Module):
    """Clip actions into the action space bounds (reference ``rl.py:130``)."""

    def __init__(self, lb, ub):
        self.lb = jnp.asarray(lb)
        self.ub = jnp.asarray(ub)

    def init(self, key):
        return ()

    def apply(self, params, x, state=None):
        return jnp.clip(x, self.lb, self.ub), state

    def __repr__(self):
        return "ActClipLayer()"


def ObsNormWrapperModule(module: Module, obs_norm) -> Module:
    """Prepend frozen obs normalization to a policy (reference ``rl.py:166``)."""
    layer = obs_norm.to_layer() if hasattr(obs_norm, "to_layer") else obs_norm
    return layer >> module


def ActClipWrapperModule(module: Module, lb, ub) -> Module:
    """Append action clipping to a policy (reference ``rl.py:130``)."""
    return module >> ActClipLayer(lb, ub)


def alive_bonus_for_step(t, alive_bonus_schedule) -> float:
    """Scheduled alive bonus (reference ``rl.py:199`` and
    ``vecgymne.py:801-878``): ``(t0, b)`` gives bonus b from timestep t0 on;
    ``(t0, t1, b)`` ramps linearly from 0 at t0 to b at t1. Works with traced
    ``t`` inside jit."""
    if alive_bonus_schedule is None:
        return 0.0
    if len(alive_bonus_schedule) == 2:
        t0, bonus = alive_bonus_schedule
        return jnp.where(t >= t0, bonus, 0.0)
    t0, t1, bonus = alive_bonus_schedule
    ramp = bonus * (t - t0) / max(t1 - t0, 1)
    return jnp.clip(ramp, 0.0, bonus) * (t >= t0)


def alive_bonus_for_step_host(t: int, alive_bonus_schedule) -> float:
    """Pure-Python :func:`alive_bonus_for_step` for host gym/vector loops:
    the jnp form dispatches a device computation whose scalar result the
    host loop would then sync back EVERY step (graftlint ``host-sync``) —
    for a host-side ``t`` the schedule is plain float math."""
    if alive_bonus_schedule is None:
        return 0.0
    if len(alive_bonus_schedule) == 2:
        t0, bonus = alive_bonus_schedule
        return float(bonus) if t >= t0 else 0.0
    t0, t1, bonus = alive_bonus_schedule
    if t < t0:
        return 0.0
    ramp = float(bonus) * (t - t0) / max(t1 - t0, 1)
    return min(max(ramp, 0.0), float(bonus))


# --------------------------------------------------------------------------
# host-side gymnasium shims (classic, non-vectorized API)
# --------------------------------------------------------------------------


def reset_env(env) -> np.ndarray:
    """Reset a gym(nasium) env under either API generation
    (reference ``rl.py:63-92``)."""
    result = env.reset()
    if isinstance(result, tuple) and len(result) == 2:
        obs, _info = result
        return np.asarray(obs)
    return np.asarray(result)


def take_step_in_env(env, action) -> Tuple[np.ndarray, float, bool]:
    """Step a gym(nasium) env under either API generation; returns
    ``(obs, reward, done)`` (reference ``rl.py:94-128``)."""
    result = env.step(np.asarray(action))
    if len(result) == 5:
        obs, reward, terminated, truncated, _info = result
        done = bool(terminated) or bool(truncated)
    else:
        obs, reward, done, _info = result
        done = bool(done)
    return np.asarray(obs), float(reward), done
