"""Vectorized-RL plumbing: batched policies and the jitted rollout engine.

Parity: reference ``net/vecrl.py`` (1912 LoC). What the reference assembles
from dlpack converters (``vecrl.py:53-82``), ``TorchWrapper``
(``vecrl.py:362-613``), a stateful ``Policy`` with auto-vmap forward and
per-env reset (``vecrl.py:1019-1361``), ``reset_tensors``
(``vecrl.py:866-1016``) and eager Python stepping (``vecgymne.py:837-904``)
becomes here ONE jitted program: ``run_vectorized_rollout`` compiles the
entire population x envs x time loop — masked activity, auto-reset,
episode/interaction accounting, obs-norm statistics in the carry — into a
single ``lax.while_loop`` (SURVEY.md §3.4 and §5 long-context note).

``run_vectorized_rollout_compacting`` is the TPU answer to the idle-lane
problem of the reference's evaluation contract (each lane runs its episodes
then idles until the whole population finishes): the loop runs in chunks,
and between chunks the still-active lanes are sorted to the front and the
working width shrinks to the smallest allowed power-of-two that holds them —
so once most of the population has finished, the machine stops paying for
the dead lanes.

``eval_mode="episodes_refill"`` is the work-conserving alternative
(continuous batching for rollouts, after the Podracer always-on device
loops, arXiv:2104.06272): a FIXED lane width ``W <= popsize * num_episodes``,
a pending-work queue carried in the ``lax.while_loop`` state, and an
on-device refill step that reloads a finishing lane with the next pending
(solution, episode) item — fresh env reset from the item's own PRNG seed,
policy parameters gathered into the lane slot, episode return credited to
the right solution by segment reduction. No host round-trip, no re-trace,
no padding to the longest survivor.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ...observability.devicemetrics import (
    QUEUE_WAIT_BUCKETS,
    TELEMETRY_WIDTH,
    append_health_block,
    compute_health_block,
    pack_eval_telemetry,
    pack_group_telemetry,
    queue_wait_bucket_index,
)
from ...tools.lowrank import is_factored
from ..net.functional import FlatParamsPolicy
from ..net.lowrank import (
    LowRankParamsBatch,
    TrunkDeltaParamsBatch,
    lowrank_forward,
    prepare_lowrank,
    prepare_trunk_delta,
    trunk_delta_forward,
)
from ..net.rl import alive_bonus_for_step
from ..net.runningnorm import (
    CollectedStats,
    group_stats_normalize,
    group_stats_update,
    stats_normalize,
    stats_update,
)

__all__ = [
    "Policy",
    "reset_tensors",
    "run_vectorized_rollout",
    "run_vectorized_rollout_compacting",
    "run_vectorized_rollout_compacting_sharded",
    "global_lane_ids",
    "RolloutResult",
]


# ------------------- population-parameter representations -------------------
# The engine accepts a population as a dense (N, L) matrix, a
# LowRankParamsBatch (center + shared basis + per-lane coefficients — the
# augmented-matmul MXU path, net/lowrank.py), or a TrunkDeltaParamsBatch
# (shared trunk + rank-1-per-block deltas — the shared-trunk MXU path,
# docs/policies.md). These helpers are the only places that care which one
# it is; per-lane state lives ONLY in coeffs for both factored forms
# (tools.lowrank.is_factored), so take/popsize generalize.


def _params_popsize(params_batch) -> int:
    if is_factored(params_batch):
        return params_batch.popsize
    return params_batch.shape[0]


def _params_cast(params_batch, dtype):
    if dtype is None:
        return params_batch
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), params_batch)


def _params_take(params_batch, idx):
    if is_factored(params_batch):
        return params_batch.take(idx)
    return params_batch[idx]


def _forward_ctx(policy, params_batch, trunk_block: int = 0):
    """Precompute the loop-invariant forward context (per-layer center/basis
    or trunk/factor trees for the factored paths); call inside jit, OUTSIDE
    stepping loops. ``trunk_block`` is the static lane-block size of the
    trunk-delta forward (0 = single block; ignored by the other forms)."""
    if isinstance(params_batch, TrunkDeltaParamsBatch):
        return prepare_trunk_delta(policy, params_batch, trunk_block=trunk_block)
    if isinstance(params_batch, LowRankParamsBatch):
        return prepare_lowrank(policy, params_batch)
    return None


def _batched_forward(policy, params_batch, ctx, obs, states):
    """Whole-population policy forward for any representation."""
    if isinstance(params_batch, TrunkDeltaParamsBatch):
        return trunk_delta_forward(policy, params_batch, ctx, obs, states)
    if isinstance(params_batch, LowRankParamsBatch):
        return lowrank_forward(policy, params_batch, ctx, obs, states)
    if states is None:
        out, _ = jax.vmap(lambda p, o: policy(p, o))(params_batch, obs)
        return out, None
    return jax.vmap(policy)(params_batch, obs, states)


def reset_tensors(tree: Any, mask: jnp.ndarray) -> Any:
    """Zero the rows of every leaf where ``mask`` is True (the reference's
    nested-state resetter, ``vecrl.py:866-1016``), as a pure function."""

    def zero_rows(leaf):
        m = mask.reshape(mask.shape + (1,) * (leaf.ndim - mask.ndim))
        return jnp.where(m, jnp.zeros_like(leaf), leaf)

    return jax.tree_util.tree_map(zero_rows, tree)


class Policy:
    """Stateful convenience wrapper over a flat-params policy
    (reference ``Policy``, ``vecrl.py:1019-1361``): give it parameters for one
    solution or a batch of solutions, call it on observations, and it manages
    the recurrent state — including per-env ``reset(indices)``."""

    def __init__(self, net, *, key=None):
        from .functional import FlatParamsPolicy
        from .layers import Module

        if isinstance(net, FlatParamsPolicy):
            self._flat = net
        elif isinstance(net, Module):
            self._flat = FlatParamsPolicy(net, key=key)
        else:
            raise TypeError(f"Policy expects a Module or FlatParamsPolicy, got {type(net)}")
        self._params: Optional[jnp.ndarray] = None
        self._state = None
        self._batched = False

    @property
    def parameter_count(self) -> int:
        return self._flat.parameter_count

    def set_parameters(self, parameters, *, reset: bool = True):
        """Accepts ``(L,)`` for one policy or ``(N, L)`` for a batch of
        policies (reference ``vecrl.py:1191``)."""
        parameters = jnp.asarray(parameters)
        self._params = parameters
        self._batched = parameters.ndim == 2
        if reset:
            self._state = None

    def _fresh_state(self, batch_size: Optional[int]):
        proto = self._flat.initial_state()
        if proto is None:
            return None
        if batch_size is None:
            return proto
        return jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(leaf, (batch_size,) + leaf.shape), proto
        )

    def __call__(self, obs) -> jnp.ndarray:
        if self._params is None:
            raise RuntimeError("Call set_parameters(...) before using the Policy")
        obs = jnp.asarray(obs)
        if self._batched:
            n = self._params.shape[0]
            if self._state is None:
                self._state = self._fresh_state(n)
            if self._state is None:
                out, _ = jax.vmap(lambda p, o: self._flat(p, o))(self._params, obs)
                return out
            out, self._state = jax.vmap(lambda p, o, s: self._flat(p, o, s))(
                self._params, obs, self._state
            )
            return out
        if self._state is None:
            self._state = self._fresh_state(None)
        out, self._state = self._flat(self._params, obs, self._state)
        return out

    def reset(self, indices=None):
        """Reset recurrent state — fully, or only the rows given by a boolean
        mask / index array (reference ``vecrl.py:1281``)."""
        if self._state is None or indices is None:
            self._state = None
            return
        mask = jnp.asarray(indices)
        if mask.dtype != jnp.bool_:
            n = self._params.shape[0]
            mask = jnp.zeros(n, dtype=bool).at[mask].set(True)
        self._state = reset_tensors(self._state, mask)

    @property
    def h(self):
        return self._state


# telemetry matrix column indices (devicemetrics._SLOTS order)
(
    _COL_ENV_STEPS,
    _COL_EPISODES,
    _COL_CAPACITY,
    _COL_LANE_WIDTH,
    _COL_REFILL,
    _COL_WAIT,
    _COL_NONFINITE,
) = range(TELEMETRY_WIDTH)


def _empty_lane_groups():
    """The lane_groups sentinel when per-group accounting is off: a (0,)
    int32 array (shape-stable, costs nothing in the carry)."""
    return jnp.zeros((0,), dtype=jnp.int32)


def _empty_group_counts():
    """The group_counts sentinel when per-group accounting is off."""
    return jnp.zeros((0, TELEMETRY_WIDTH), dtype=jnp.int32)


def _init_group_counts(lane_groups, num_groups: int):
    """A fresh (G, TELEMETRY_WIDTH) counter block with the lane_width column
    set from the initial lane->group assignment (every other column
    accumulates in the stepping loop)."""
    widths = jax.ops.segment_sum(
        jnp.ones(lane_groups.shape[0], dtype=jnp.int32),
        lane_groups,
        num_segments=num_groups,
    )
    return (
        jnp.zeros((num_groups, TELEMETRY_WIDTH), dtype=jnp.int32)
        .at[:, _COL_LANE_WIDTH]
        .add(widths)
    )


def _fold_lane_counts(
    group_counts, lane_steps, lane_episodes, lane_groups, t_global, num_groups, mask=None
):
    """Fold the per-lane step/episode accumulators into the per-group counter
    block: one segment_sum at a loop boundary instead of one per loop
    iteration. A lane's capacity charge is ``t_global`` — every lane still in
    the carry has been present since t=0 (compaction only ever drops lanes),
    so ``width x iterations`` decomposes into ``t_global`` per present lane.
    ``mask`` (int-castable, per lane) restricts the fold to a subset — the
    lanes being dropped at a compaction boundary; the survivors keep
    accumulating and fold at the next boundary."""
    width = lane_steps.shape[0]
    per_lane = jnp.stack(
        [lane_steps, lane_episodes, jnp.broadcast_to(t_global, (width,))], axis=1
    )
    if mask is not None:
        per_lane = per_lane * mask.astype(jnp.int32)[:, None]
    return group_counts.at[:, :_COL_LANE_WIDTH].add(
        jax.ops.segment_sum(per_lane, lane_groups, num_segments=num_groups)
    )


def _quarantine_nonfinite(scores, *, valid_mask=None, penalty=None, sync_axis=None):
    """Non-finite score quarantine (docs/resilience.md): replace NaN/Inf
    entries of a final per-solution score vector with the WORST finite score
    in the batch (or a fixed ``penalty``) and return the replacement mask.

    Runs once at the very end of an engine, on the ``(N,)`` mean scores —
    one ``isfinite`` plus a select, so the quarantined program is the
    unquarantined one plus a handful of elementwise ops. ``valid_mask``
    excludes padding rows from the worst-finite reduction (their synthetic
    scores are not evidence) and from the returned COUNT mask — but their
    values are still scrubbed finite, so no NaN survives in the full-width
    vector whatever a caller reduces over before slicing. ``sync_axis``
    (shard_map callers) pmins the worst-finite value over the mesh so
    sharded replacement scores stay bit-identical to unsharded; the counts
    are additive and psum with the rest of the telemetry.
    """
    finite = jnp.isfinite(scores)
    bad = ~finite  # replacement mask: every non-finite entry is scrubbed
    consider = finite
    counted = bad
    if valid_mask is not None:
        counted = bad & valid_mask
        consider = consider & valid_mask
    if penalty is not None:
        repl = jnp.asarray(penalty, dtype=scores.dtype)
    else:
        big = jnp.asarray(jnp.finfo(scores.dtype).max, dtype=scores.dtype)
        worst = jnp.min(jnp.where(consider, scores, big))
        if sync_axis is not None:
            worst = jax.lax.pmin(worst, sync_axis)
        # an all-non-finite (or all-padding) batch leaves no worst finite
        # score to charge: quarantine to 0.0 rather than float-max
        repl = jnp.where(worst >= big, jnp.zeros((), scores.dtype), worst)
    return jnp.where(bad, repl, scores), counted


def _nonfinite_group_counts(group_counts, bad, groups, num_groups: int):
    """Fold a quarantine mask into the ``nonfinite`` telemetry column, one
    count per quarantined SOLUTION, charged to the solution's group."""
    return group_counts.at[:, _COL_NONFINITE].add(
        jax.ops.segment_sum(
            bad.astype(jnp.int32), groups, num_segments=int(num_groups)
        )
    )


def _health_telemetry(telemetry, scores, groups, num_groups, num_valid):
    """Append the v4 search-health block to a packed telemetry matrix,
    computed from the final post-quarantine per-solution mean scores. The
    scores (and group ids) are sliced to the static ``num_valid`` BEFORE
    the reductions so padded and unpadded programs reduce over identical
    shapes — the bit-identity contract of docs/observability.md "Search
    health"."""
    if num_valid is not None:
        scores = scores[:num_valid]
        if groups is not None:
            groups = groups[:num_valid]
    return append_health_block(
        telemetry, compute_health_block(scores, groups, num_groups)
    )


class RolloutResult(NamedTuple):
    scores: jnp.ndarray  # (N,) mean episodic return per solution
    stats: CollectedStats  # obs-norm statistics collected during the rollout
    total_steps: jnp.ndarray  # scalar: total env interactions
    total_episodes: jnp.ndarray  # scalar: episodes finished
    # packed on-device eval telemetry (observability.devicemetrics): one
    # (G, GROUP_TELEMETRY_WIDTH) int32 matrix (G=1 without per-group
    # accounting) — or (G, HEALTH_TELEMETRY_WIDTH) with the health plane
    # on — computed inside the same jitted program as the scores; fetching
    # it is part of the same transfer, never a new dispatch. None when the
    # engine ran with telemetry=False.
    telemetry: Any = None


class RolloutCarry(NamedTuple):
    """Loop state of the rollout engine. Per-lane leaves are batch-leading
    except ``env_states`` (whose layout belongs to the env; see
    ``Env.batched_native``); ``key`` is the ``(n,)`` array of per-lane PRNG
    chains (randomness is a per-lane property — see ``_rollout_init``);
    ``stats``/counters are global."""

    env_states: Any
    obs: jnp.ndarray
    policy_states: Any
    scores: jnp.ndarray
    episodes_done: jnp.ndarray
    steps_in_episode: jnp.ndarray
    active: jnp.ndarray
    stats: CollectedStats
    key: Any
    total_steps: jnp.ndarray
    t_global: jnp.ndarray
    # lane-step slots executed (working width summed over iterations): the
    # occupancy denominator (observability.devicemetrics); frozen at its
    # initial zero when the engine runs with telemetry off
    capacity: jnp.ndarray
    # per-group accounting (ISSUE 15): lane_groups is the (n,) group id each
    # lane charges its counters to, group_counts the (G, TELEMETRY_WIDTH)
    # per-group counter block. The hot loop only bumps the per-lane
    # accumulators lane_steps/lane_episodes (two elementwise adds); the
    # segment_sum fold into group_counts happens ONCE at a loop boundary
    # (_fold_lane_counts) — lane->group ids never change inside these
    # engines, so the fold commutes with the loop and the per-step cost is
    # G-independent. All four are empty (0-row) sentinels when
    # num_groups == 1 or telemetry is off, so the single-group program
    # carries no group state at all.
    lane_groups: jnp.ndarray
    group_counts: jnp.ndarray
    lane_steps: jnp.ndarray
    lane_episodes: jnp.ndarray


def _policy_to_action(raw, action_space, noise, clip: bool):
    if action_space.is_discrete:
        return jnp.argmax(raw, axis=-1)
    act = raw if noise is None else raw + noise
    if clip and action_space.lb is not None:
        act = jnp.clip(act, action_space.lb, action_space.ub)
    return act


def _env_reset(env, keys):
    if getattr(env, "batched_native", False):
        return env.batch_reset(keys)
    return jax.vmap(env.reset)(keys)


def _env_state_select(env, mask, a, b):
    """Per-lane env-state select: lane i takes ``a`` where ``mask[i]``."""
    if getattr(env, "batched_native", False):
        return env.batch_where(mask, a, b)

    def select(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(select, a, b)


def _lane_select(mask, new, old):
    """Per-lane row select with ``mask`` broadcast over trailing dims."""
    m = mask.reshape(mask.shape + (1,) * (new.ndim - 1))
    return jnp.where(m, new, old)


def _initial_policy_states(policy: FlatParamsPolicy, n: int, compute_dtype):
    """The width-``n`` batch of initial recurrent states (``None`` for a
    stateless policy), in the compute dtype (recurrent state lives in compute
    dtype) — the one definition of a lane's fresh policy state, shared by
    rollout init and the refill engine."""
    proto = policy.initial_state()
    if proto is None:
        return None
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(
            leaf if compute_dtype is None else leaf.astype(compute_dtype),
            (n,) + leaf.shape,
        ),
        proto,
    )


def _env_state_take(env, states, idx):
    """Gather lanes ``idx`` out of a batched env state (lane compaction)."""
    if getattr(env, "batched_native", False):
        take = getattr(env, "batch_take", None)
        if take is None:
            raise NotImplementedError(
                f"{type(env).__name__} is batched_native but does not implement"
                " batch_take(states, idx); lane compaction needs it"
            )
        return take(states, idx)
    return jax.tree_util.tree_map(lambda x: x[idx], states)


def _stats_psum_merge(old: CollectedStats, new: CollectedStats, axis_name: str):
    """Every shard absorbs every shard's stat delta: the per-step form of the
    end-of-rollout delta merge (the accumulators are linear, so delta-psum
    composes exactly)."""
    delta = jax.tree_util.tree_map(lambda n, o: n - o, new, old)
    return jax.tree_util.tree_map(
        lambda o, d: o + jax.lax.psum(d, axis_name), old, delta
    )


def _rollout_init(
    env,
    policy: FlatParamsPolicy,
    params_batch: jnp.ndarray,
    key,
    stats: CollectedStats,
    *,
    observation_normalization: bool,
    compute_dtype,
    lane_ids=None,
    stats_sync_axis=None,
    num_valid=None,
    pad_episodes_done: int = 0,
    groups=None,
    num_groups: int = 1,
):
    """Build the initial carry (full width) and the compute-dtype params.

    Each lane carries its OWN PRNG chain, seeded by ``fold_in(key,
    lane_id)`` — realized randomness is therefore a per-lane property,
    independent of the working width (compaction), the batch composition,
    and the mesh topology (a sharded evaluation passing global ``lane_ids``
    reproduces the unsharded one bit-for-bit).

    ``num_valid`` marks lanes with ``lane_ids >= num_valid`` as PADDING
    (``parallel.make_sharded_rollout_evaluator`` pads an indivisible
    popsize to the next mesh multiple): they start inactive with
    ``episodes_done = pad_episodes_done`` (``num_episodes`` in episodes
    mode, so the exit condition sees them as finished) and are excluded
    from the initial statistics mask — padding never earns score credit
    or counter/telemetry credit."""
    n = _params_popsize(params_batch)
    params_batch = _params_cast(params_batch, compute_dtype)

    if lane_ids is None:
        lane_ids = jnp.arange(n, dtype=jnp.int32)
    valid = (
        jnp.ones(n, dtype=bool)
        if num_valid is None
        else lane_ids < jnp.int32(num_valid)
    )
    lane_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(lane_ids)
    pair = jax.vmap(lambda k: jax.random.split(k, 2))(lane_keys)
    lane_keys, reset_keys = pair[:, 0], pair[:, 1]
    env_states, obs = _env_reset(env, reset_keys)
    if observation_normalization:
        # the initial reset observations are fed to the policy at t=0, so
        # they belong in the normalization statistics (the reference updates
        # stats on every observation the policy consumes)
        new_stats = stats_update(stats, obs, mask=valid)
        if stats_sync_axis is not None:
            new_stats = _stats_psum_merge(stats, new_stats, stats_sync_axis)
        stats = new_stats

    policy_states = _initial_policy_states(policy, n, compute_dtype)

    if groups is not None and num_groups > 1:
        # lane i charges group groups[i]; the lane_width column is set once
        # here (physical lanes per group — padding lanes included, matching
        # the v1 global's physical lane_width), everything else accumulates
        # per lane in the stepping loop and folds at the boundary
        lane_groups = jnp.asarray(groups, dtype=jnp.int32)
        group_counts = _init_group_counts(lane_groups, num_groups)
        lane_steps0 = jnp.zeros(n, dtype=jnp.int32)
        lane_episodes0 = jnp.zeros(n, dtype=jnp.int32)
    else:
        lane_groups = _empty_lane_groups()
        group_counts = _empty_group_counts()
        lane_steps0 = _empty_lane_groups()
        lane_episodes0 = _empty_lane_groups()

    episodes_done0 = (
        jnp.zeros(n, dtype=jnp.int32)
        if num_valid is None
        else jnp.where(valid, 0, jnp.int32(pad_episodes_done))
    )
    carry = RolloutCarry(
        env_states=env_states,
        obs=obs,
        policy_states=policy_states,
        scores=jnp.zeros(n),
        episodes_done=episodes_done0,
        steps_in_episode=jnp.zeros(n, dtype=jnp.int32),
        active=valid,
        stats=stats,
        key=lane_keys,  # (n,) per-lane PRNG chains
        total_steps=jnp.zeros((), dtype=jnp.int32),
        t_global=jnp.zeros((), dtype=jnp.int32),
        capacity=jnp.zeros((), dtype=jnp.int32),
        lane_groups=lane_groups,
        group_counts=group_counts,
        lane_steps=lane_steps0,
        lane_episodes=lane_episodes0,
    )
    return carry, params_batch


# Bounded caches (ADVICE r3): these are keyed on env/policy INSTANCES, so an
# unbounded cache would pin every env/policy ever used (plus their jitted
# closures) for the process lifetime — and unlike jit caches they are not
# freed by jax.clear_caches(). 64 entries comfortably covers the handful of
# long-lived env/policy/config combos a training process realistically holds;
# eviction merely costs a retrace on the next use of an evicted combo.
_ENGINE_CACHE_SIZE = 64


@functools.lru_cache(maxsize=_ENGINE_CACHE_SIZE)
def _make_step(
    env,
    policy: FlatParamsPolicy,
    *,
    num_episodes: int,
    max_t: int,
    observation_normalization: bool,
    alive_bonus_schedule,
    decrease_rewards_by,
    action_noise_stdev,
    compute_dtype,
    budget_mode: bool,
    stats_sync_axis=None,
    collect_telemetry: bool = True,
    masked_width: bool = False,
    num_groups: int = 1,
):
    """One masked control step of the whole population, as a pure function
    ``step(params_batch, carry) -> carry``. Width is taken from the carry, so
    the same step serves the monolithic loop and every compacted width.

    ``collect_telemetry``: accumulate the observability counters (one extra
    int32 scalar add per step — the ``capacity`` carry); False freezes the
    telemetry fields so an A/B against a telemetry-free program is possible.

    ``num_groups > 1``: additionally ``segment_sum`` the per-lane
    env-step/episode/capacity increments into the carry's per-group counter
    block every step (ISSUE 15) — one tiny (n -> G) reduction, still zero
    host syncs.

    ``stats_sync_axis``: inside a ``shard_map`` over that axis, psum-merge
    the per-step observation-statistic deltas so every shard normalizes by
    the MESH-GLOBAL cohort — ``obs_norm_sync="step"`` semantics. The caller
    must guarantee every shard runs the same number of steps (mesh-global
    loop conditions), or the collective deadlocks.

    When no lane can ever need a mid-rollout reset (episodes mode with
    ``num_episodes == 1``), the per-step fresh ``env_reset`` — a per-lane key
    split, reset noise and a full observation build — is skipped entirely and
    finished lanes are *frozen* at their last pre-terminal state instead.
    Frozen lanes keep stepping (masked) from a bounded, healthy state, so no
    numerical blow-up can leak NaN into the masked statistics.
    """
    auto_reset = budget_mode or num_episodes > 1

    def step(params_batch, ctx, c: RolloutCarry) -> RolloutCarry:
        n = c.active.shape[0]
        # advance each lane's own PRNG chain (only when this config consumes
        # randomness — otherwise the chains stay untouched and XLA drops the
        # splits entirely)
        if auto_reset or action_noise_stdev is not None:
            triple = jax.vmap(lambda k: jax.random.split(k, 3))(c.key)
            lane_keys, noise_keys, reset_keys = triple[:, 0], triple[:, 1], triple[:, 2]
        else:
            lane_keys, noise_keys, reset_keys = c.key, None, None

        policy_in = (
            stats_normalize(c.stats, c.obs) if observation_normalization else c.obs
        )
        if compute_dtype is not None:
            policy_in = policy_in.astype(compute_dtype)
        raw, new_policy_states = _batched_forward(
            policy, params_batch, ctx, policy_in, c.policy_states
        )
        if compute_dtype is not None:
            raw = raw.astype(jnp.float32)

        noise = None
        if action_noise_stdev is not None:
            # per-lane noise from each lane's own chain: the draw is
            # independent of the working width / batch composition
            noise = action_noise_stdev * jax.vmap(
                lambda k: jax.random.normal(k, raw.shape[1:])
            )(noise_keys)
        actions = _policy_to_action(raw, env.action_space, noise, clip=True)

        if getattr(env, "batched_native", False):
            new_env_states, new_obs, rewards, dones = env.batch_step(
                c.env_states, actions
            )
        else:
            new_env_states, new_obs, rewards, dones = jax.vmap(env.step)(
                c.env_states, actions
            )

        steps_in_episode = c.steps_in_episode + 1
        # guaranteed truncation at max_t (gym TimeLimit semantics): even an
        # env that never emits done internally ends its episode here, so
        # per-episode score averaging stays well-defined
        dones = dones | (steps_in_episode >= max_t)

        if decrease_rewards_by is not None:
            rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            rewards = rewards + alive_bonus_for_step(
                steps_in_episode, alive_bonus_schedule
            ) * (~dones)

        active_f = c.active
        scores = c.scores + jnp.where(active_f, rewards, 0.0)

        finished = dones & active_f
        episodes_done = c.episodes_done + finished.astype(jnp.int32)

        if auto_reset:
            # auto-reset the envs that finished an episode (reset keys come
            # from the per-lane chains: width-independent)
            fresh_states, fresh_obs = _env_reset(env, reset_keys)
            env_states_next = _env_state_select(
                env, finished, fresh_states, new_env_states
            )
            obs_next = _lane_select(finished, fresh_obs, new_obs)
            steps_in_episode = jnp.where(finished, 0, steps_in_episode)
            if new_policy_states is not None:
                new_policy_states = reset_tensors(new_policy_states, finished)
            if budget_mode:
                active = active_f  # every lane runs its full budget
            else:
                active = episodes_done < num_episodes
        else:
            # freeze finished lanes at their last pre-terminal state: they
            # never run another episode, so no fresh reset is ever needed
            active = episodes_done < num_episodes
            env_states_next = _env_state_select(
                env, active, new_env_states, c.env_states
            )
            obs_next = _lane_select(active, new_obs, c.obs)
            steps_in_episode = jnp.where(active, steps_in_episode, 0)

        if budget_mode and not masked_width:
            total_steps = c.total_steps + n
        else:
            # episodes modes, and budget under padding (``masked_width``:
            # some lanes are permanently-inactive pad rows whose slots must
            # not count as genuine interactions)
            total_steps = c.total_steps + jnp.sum(active_f.astype(jnp.int32))
        # normalization statistics come from the observations the policy will
        # actually consume next step: post-reset-selection obs, masked by the
        # envs still running (ADVICE r1: not the pre-reset terminal obs)
        new_stats = (
            stats_update(c.stats, obs_next, mask=active)
            if observation_normalization
            else c.stats
        )
        if observation_normalization and stats_sync_axis is not None:
            new_stats = _stats_psum_merge(c.stats, new_stats, stats_sync_axis)

        if collect_telemetry and num_groups > 1:
            # per-group accounting: lane i charges its env-step (if active)
            # and episode completion (if it fired this step) to PER-LANE
            # accumulators — two fused elementwise adds; the segment_sum into
            # group_counts happens once at the loop boundary
            # (_fold_lane_counts), so the per-step cost is G-independent.
            # Padding lanes never activate or fire, so their only charge is
            # capacity (t_global at fold time) — the same semantics as the
            # v1 global scalars.
            lane_steps = c.lane_steps + active_f.astype(jnp.int32)
            lane_episodes = c.lane_episodes + finished.astype(jnp.int32)
        else:
            lane_steps = c.lane_steps
            lane_episodes = c.lane_episodes

        return RolloutCarry(
            env_states=env_states_next,
            obs=obs_next,
            policy_states=new_policy_states,
            scores=scores,
            episodes_done=episodes_done,
            steps_in_episode=steps_in_episode,
            active=active,
            stats=new_stats,
            key=lane_keys,
            total_steps=total_steps,
            t_global=c.t_global + 1,
            # telemetry: every iteration executes `n` lane-step slots,
            # whether the lanes are live or idling masked
            capacity=(c.capacity + n) if collect_telemetry else c.capacity,
            lane_groups=c.lane_groups,
            group_counts=c.group_counts,
            lane_steps=lane_steps,
            lane_episodes=lane_episodes,
        )

    return step


@partial(
    jax.jit,
    static_argnames=(
        "env",
        "policy",
        "num_episodes",
        "episode_length",
        "observation_normalization",
        "alive_bonus_schedule",
        "decrease_rewards_by",
        "action_noise_stdev",
        "compute_dtype",
        "eval_mode",
        "stats_sync_axis",
        "refill_width",
        "refill_period",
        "seed_stride",
        "telemetry",
        "health",
        "num_valid",
        "num_groups",
        "trunk_block",
        "nonfinite_quarantine",
        "nonfinite_penalty",
        "nonfinite_sync_axis",
    ),
)
def run_vectorized_rollout(
    env,
    policy: FlatParamsPolicy,
    params_batch: jnp.ndarray,
    key,
    stats: CollectedStats,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    observation_normalization: bool = False,
    alive_bonus_schedule: Optional[tuple] = None,
    decrease_rewards_by: Optional[float] = None,
    action_noise_stdev: Optional[float] = None,
    compute_dtype=None,
    eval_mode: str = "episodes",
    lane_ids=None,
    solution_keys=None,
    stats_sync_axis: Optional[str] = None,
    refill_width: Optional[int] = None,
    refill_period: int = 1,
    seed_stride: Optional[int] = None,
    telemetry: bool = True,
    health: bool = True,
    num_valid: Optional[int] = None,
    groups=None,
    num_groups: int = 1,
    trunk_block: int = 0,
    nonfinite_quarantine: bool = False,
    nonfinite_penalty: Optional[float] = None,
    nonfinite_sync_axis: Optional[str] = None,
) -> RolloutResult:
    """Evaluate ``N`` policies on ``N`` environments, fully on-device.

    ``nonfinite_quarantine`` (default off at this primitive layer; ``VecNE``
    turns it on) replaces non-finite final scores with the batch's worst
    FINITE score — or the fixed ``nonfinite_penalty`` when given — inside
    the same jitted program, and counts the quarantined solutions in the
    telemetry's ``nonfinite`` slot (per group at G > 1), so one diverged
    rollout cannot NaN-poison ranking (docs/resilience.md).
    ``nonfinite_sync_axis`` is for explicit shard_map callers: the
    worst-finite reduction pmins over that axis so the sharded replacement
    equals the unsharded one (the GSPMD path needs nothing — its reduction
    is global by construction).

    ``trunk_block`` (trunk-delta populations only): static lane-block size
    of the shared-trunk forward — the population batch is chunked into
    blocks of that many lanes per trunk GEMM (``lax.map``), bounding the
    activation working set. 0 (default) runs one full-width GEMM. Tuned by
    the autotuner's ``policy`` knob group; a no-op for dense/low-rank
    populations.

    ``telemetry`` (default on): accumulate the zero-sync observability
    counters in the loop carry and return them packed in
    ``RolloutResult.telemetry`` — a ``(num_groups,
    GROUP_TELEMETRY_WIDTH)`` int32 matrix produced by the same jitted
    program as the scores (zero extra dispatches; see
    ``observability.devicemetrics``). ``telemetry=False`` compiles the
    accumulator-free program — the A/B baseline for measuring that the
    accumulators cost nothing.

    ``health`` (default on, only meaningful with ``telemetry``): append the
    float32 search-health plane — per-group ``count, sum, sumsq, min, max``
    of the final per-solution mean scores, bit-cast into ``HEALTH_WIDTH``
    extra int32 columns — computed ONCE at program end from the
    post-quarantine scores (no loop-carry cost). ``health=False`` keeps
    the pre-v4 ``(G, GROUP_TELEMETRY_WIDTH)`` wire byte-compatible (the
    ``BENCH_HEALTH=0`` escape hatch). Explicit shard_map callers should
    pass ``health=False`` and append a mesh-global block themselves (see
    ``parallel/evaluate.py``) — a per-shard block would be garbled by the
    telemetry psum.

    ``groups`` / ``num_groups`` (ISSUE 15): per-group telemetry. ``groups``
    is an ``(N,)`` int32 array of group ids in ``[0, num_groups)`` — one per
    SOLUTION — and every telemetry slot is ``segment_sum``-accumulated per
    group inside the same loop carry (the substrate for multi-tenant
    occupancy/fairness accounting and per-island counters). The column sums
    of the per-group matrix equal the single-group global numbers exactly.
    With ``num_groups == 1`` (default) no group state is carried at all. In
    ``episodes_refill`` mode the telemetry additionally carries per-group
    queue-wait histograms (log-spaced buckets; see
    ``devicemetrics.QUEUE_WAIT_BUCKET_EDGES``) fed by each refilled item's
    idle-to-refill wait.

    ``solution_keys`` (``episodes_refill`` only): an optional TRACED ``(N,)``
    typed-key array of per-solution BASE keys. When given, the (solution,
    episode) item seeds fold into ``solution_keys[s]`` instead of the global
    ``key`` — so solutions owned by different requests/tenants packed into
    one program each reproduce the realized randomness of their owner's own
    standalone evaluation (``fold_in(solution_keys[s], lane_ids[s])``
    equals the standalone engine's ``fold_in(key_s, i)`` when the packer
    sets ``lane_ids`` to owner-local indices). Being traced, per-dispatch
    key/owner churn never retraces (the multi-tenant serving substrate,
    docs/serving.md).

    Per-group observation normalization (``episodes_refill`` +
    ``groups``/``num_groups`` only): passing a STACKED stats pytree —
    ``count (G,)``, ``sum (G, n)``, ``sum_of_squares (G, n)``, e.g.
    ``runningnorm.group_stats_init`` — switches every stat touch to the
    per-group form: each lane normalizes by ITS group's slot and updates
    only that slot (per-tenant obs-norm isolation). The stacked form is
    detected by the count's rank, so the same traced signature serves both.

    Randomness is a PER-LANE property: lane ``i``'s PRNG chain is seeded by
    ``fold_in(key, lane_ids[i])`` (default ``lane_ids = arange(N)``) and
    advances with that lane, so realized randomness does not depend on the
    working width, the batch composition, or the mesh topology. A sharded
    caller passing each shard's GLOBAL lane ids (and the same ``key``)
    reproduces the unsharded evaluation bit-for-bit — except under online
    observation normalization, where each lane is normalized by its
    cohort's running statistics and sharding changes the cohort (cohort
    semantics, like the reference's per-actor stats). A sharded caller that
    additionally passes ``stats_sync_axis`` (its shard_map axis name)
    psum-merges the stat deltas EVERY STEP, so all shards normalize by the
    mesh-global cohort and the cohort divergence disappears (at the cost of
    one tiny collective per control step; ``VecNE(obs_norm_sync="step")``).

    The logic mirrors ``VecGymNE._evaluate_subbatch``
    (``vecgymne.py:744-916``): one sub-environment per solution, lockstep
    stepping with an activity mask, auto-reset until each env has finished
    ``num_episodes`` episodes, masked running-norm updates, alive-bonus and
    reward adjustments — but compiled into a single ``lax.while_loop``.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) casts the policy parameters and
    its inputs for the forward pass — the MXU fast path; ES is robust to
    low-precision fitness since ranking is scale-free. Env dynamics, rewards
    and statistics stay in f32.

    ``eval_mode`` selects the evaluation contract:

    - ``"episodes"`` (the reference's ``VecGymNE`` semantics): each lane runs
      exactly ``num_episodes`` episodes, then idles (masked) until every lane
      is finished. The ``lax.while_loop`` exits as soon as all lanes are done,
      but in the worst case the whole population waits on its longest
      survivor — finished lanes burn compute producing nothing. For the
      host-orchestrated variant that reclaims that compute, see
      ``run_vectorized_rollout_compacting``.
    - ``"budget"``: each lane consumes a fixed interaction budget of
      ``num_episodes * max_episode_steps`` steps, auto-resetting whenever an
      episode ends; the score is the average episodic return over the budget
      (completed episodes plus the fractional trailing episode). Every lane
      is active on every step, so the whole program is one fixed-length
      ``lax.fori_loop`` and 100% of computed env steps are genuine, counted
      interactions — on accelerators this is the throughput-optimal contract
      (it also gives low-variance fitness: constant compute per solution, no
      survivorship skew). This is the flagship benchmark path.
    - ``"episodes_refill"``: the same contract as ``"episodes"`` (each
      solution's score is the mean return of exactly ``num_episodes``
      episodes) evaluated by the work-conserving lane-refill scheduler: a
      fixed width ``refill_width`` of lanes is kept saturated by refilling
      each finishing lane with the next pending (solution, episode) item
      from an on-device queue — continuous batching for rollouts. One jitted
      program (usable inside jit/shard_map, unlike the compacting runner),
      no padding to the longest survivor. ``refill_period`` refills only
      every that-many steps (finished lanes wait masked in between),
      amortizing the refill gather/reset; ``seed_stride`` must be the GLOBAL
      popsize on a sharded caller so (solution, episode) seeds stay unique
      across shards. At ``num_episodes=1`` without observation
      normalization the scores are bit-identical to
      ``eval_mode="episodes"`` for the same ``key`` (matched per-lane
      seeding); at ``num_episodes > 1`` each episode runs on its own PRNG
      chain, so scores are distribution-equivalent, not bit-equal. With
      observation normalization ON the refill schedule itself changes the
      running statistics each lane sees mid-rollout (a lane refilled late
      is normalized by more history than its monolithic counterpart), so
      scores differ semantically from ``"episodes"`` — schedule-dependent
      cohort statistics, exactly like sharding under
      ``obs_norm_sync="cohort"``.
    """
    if eval_mode not in ("episodes", "budget", "episodes_refill"):
        raise ValueError(
            "eval_mode must be 'episodes', 'budget' or 'episodes_refill',"
            f" got {eval_mode!r}"
        )
    n_total = _params_popsize(params_batch)
    num_groups = int(num_groups)
    if num_groups > 1 and groups is None:
        raise ValueError("num_groups > 1 requires a groups array of per-solution ids")
    collect_groups = telemetry and num_groups > 1
    if not collect_groups:
        groups, num_groups = None, 1
    if num_valid is not None:
        num_valid = int(num_valid)
        if not (1 <= num_valid <= n_total):
            raise ValueError(
                f"num_valid={num_valid} must be in [1, popsize={n_total}]"
            )
        if num_valid == n_total:
            num_valid = None  # no padding: compile the unmasked program
    max_t = env.max_episode_steps if env.max_episode_steps is not None else 1000
    if episode_length is not None:
        max_t = min(max_t, int(episode_length))
    stacked_stats = stats is not None and getattr(stats.count, "ndim", 0) == 1
    if (solution_keys is not None or stacked_stats) and eval_mode != "episodes_refill":
        raise ValueError(
            "solution_keys and stacked (per-group) stats are"
            " episodes_refill-only features (the serving substrate),"
            f" got eval_mode={eval_mode!r}"
        )
    if eval_mode == "episodes_refill":
        return _run_refill(
            env,
            policy,
            params_batch,
            key,
            stats,
            num_episodes=int(num_episodes),
            max_t=max_t,
            observation_normalization=observation_normalization,
            alive_bonus_schedule=alive_bonus_schedule,
            decrease_rewards_by=decrease_rewards_by,
            action_noise_stdev=action_noise_stdev,
            compute_dtype=compute_dtype,
            lane_ids=lane_ids,
            solution_keys=solution_keys,
            stats_sync_axis=stats_sync_axis,
            refill_width=refill_width,
            refill_period=refill_period,
            seed_stride=seed_stride,
            telemetry=telemetry,
            health=health,
            num_valid=num_valid,
            groups=groups,
            num_groups=num_groups,
            trunk_block=trunk_block,
            nonfinite_quarantine=nonfinite_quarantine,
            nonfinite_penalty=nonfinite_penalty,
            nonfinite_sync_axis=nonfinite_sync_axis,
        )
    hard_cap = max_t * int(num_episodes) + 1
    budget_mode = eval_mode == "budget"

    carry, params_batch = _rollout_init(
        env,
        policy,
        params_batch,
        key,
        stats,
        observation_normalization=observation_normalization,
        compute_dtype=compute_dtype,
        lane_ids=lane_ids,
        stats_sync_axis=stats_sync_axis,
        num_valid=num_valid,
        # episodes-mode padding lanes must look already-finished to the
        # exit condition; budget-mode lanes never finish (masked inactive),
        # so their episodes_done stays 0 and total_episodes needs no fixup
        pad_episodes_done=0 if budget_mode else int(num_episodes),
        groups=groups,
        num_groups=num_groups,
    )
    step = _make_step(
        env,
        policy,
        num_episodes=int(num_episodes),
        max_t=max_t,
        observation_normalization=observation_normalization,
        alive_bonus_schedule=alive_bonus_schedule,
        decrease_rewards_by=decrease_rewards_by,
        action_noise_stdev=action_noise_stdev,
        compute_dtype=compute_dtype,
        budget_mode=budget_mode,
        stats_sync_axis=stats_sync_axis,
        collect_telemetry=telemetry,
        masked_width=num_valid is not None,
        num_groups=num_groups,
    )

    ctx = _forward_ctx(policy, params_batch, trunk_block=int(trunk_block))
    if budget_mode:
        budget = max_t * int(num_episodes)
        final = jax.lax.fori_loop(
            0, budget, lambda _, c: step(params_batch, ctx, c), carry
        )
        # average episodic return over the budget: completed episodes plus
        # the fractional trailing one (exactly the episodic mean whenever the
        # budget lands on an episode boundary)
        episodes_frac = (
            final.episodes_done + final.steps_in_episode.astype(jnp.float32) / max_t
        )
        mean_scores = final.scores / jnp.maximum(episodes_frac, 1.0 / max_t)
    else:

        def cond(c: RolloutCarry):
            any_active = jnp.any(c.active)
            if stats_sync_axis is not None:
                # per-step collectives in the body require every shard to run
                # the same number of iterations: keep looping while ANY shard
                # still has an active lane
                any_active = (
                    jax.lax.psum(any_active.astype(jnp.int32), stats_sync_axis) > 0
                )
            return any_active & (c.t_global < hard_cap)

        final = jax.lax.while_loop(cond, lambda c: step(params_batch, ctx, c), carry)
        mean_scores = final.scores / jnp.maximum(final.episodes_done, 1)
    nf_bad = None
    if nonfinite_quarantine:
        mean_scores, nf_bad = _quarantine_nonfinite(
            mean_scores,
            valid_mask=(
                None
                if num_valid is None
                else jnp.arange(n_total, dtype=jnp.int32) < num_valid
            ),
            penalty=nonfinite_penalty,
            sync_axis=nonfinite_sync_axis,
        )
    total_episodes = jnp.sum(final.episodes_done)
    if num_valid is not None and not budget_mode:
        # padding lanes were initialized as already-finished; subtract their
        # synthetic episodes_done so counters/telemetry report genuine work
        total_episodes = total_episodes - jnp.int32(
            (n_total - num_valid) * int(num_episodes)
        )
    if not telemetry:
        eval_telemetry = None
    elif collect_groups:
        # the per-group counter block IS the telemetry (no histograms in the
        # non-refill engines: nothing queues, nothing waits); the per-lane
        # accumulators fold here, once, after the loop
        group_counts = _fold_lane_counts(
            final.group_counts,
            final.lane_steps,
            final.lane_episodes,
            final.lane_groups,
            final.t_global,
            num_groups,
        )
        if nf_bad is not None:
            # lanes == solutions in these engines, so the per-lane group ids
            # charge the quarantine counts to the right rows
            group_counts = _nonfinite_group_counts(
                group_counts, nf_bad, final.lane_groups, num_groups
            )
        eval_telemetry = pack_group_telemetry(group_counts)
    else:
        eval_telemetry = pack_group_telemetry(
            pack_eval_telemetry(
                env_steps=final.total_steps,
                episodes=total_episodes,
                capacity=final.capacity,
                lane_width=final.active.shape[0],
                nonfinite=(
                    0 if nf_bad is None else jnp.sum(nf_bad.astype(jnp.int32))
                ),
            )[None]
        )
    if eval_telemetry is not None and health:
        eval_telemetry = _health_telemetry(
            eval_telemetry,
            mean_scores,
            final.lane_groups if collect_groups else None,
            num_groups,
            num_valid,
        )
    return RolloutResult(
        scores=mean_scores,
        stats=final.stats,
        total_steps=final.total_steps,
        total_episodes=total_episodes,
        telemetry=eval_telemetry,
    )


# --------------------------- lane-compacting runner ---------------------------


def _pow2_at_least(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


# ---------------------- work-conserving lane-refill engine ----------------------
# Continuous batching for the episodes contract: the whole evaluation is ONE
# lax.while_loop over a fixed width W, kept saturated by refilling finished
# lanes from an on-device pending-work queue. Unlike the compacting runner
# (host-orchestrated chunks, per-width re-traces) this is a single jitted
# program, usable inside jit/shard_map, and never pads a batch to its
# longest survivor — the large-win regime is exactly the flagship
# popsize-10k shape with skewed episode-death times.


class RefillCarry(NamedTuple):
    """Loop state of the refill engine. ``lane_*`` leaves are per-lane
    (width ``W``); ``scores_buf``/``eps_buf`` are per-SOLUTION buffers
    (length ``N``) fed by segment reduction; ``next_item`` is the head of the
    pending-work queue (items are (solution, episode) pairs, encoded
    ``item = episode * N + solution``)."""

    env_states: Any
    obs: jnp.ndarray
    policy_states: Any
    lane_params: Any  # (W, L) dense rows or (W, k) low-rank coefficients
    lane_sol: jnp.ndarray  # (W,) local solution index each lane is running
    lane_score: jnp.ndarray  # (W,) return of the lane's CURRENT episode
    steps_in_episode: jnp.ndarray
    active: jnp.ndarray
    scores_buf: jnp.ndarray  # (N,) summed episodic returns per solution
    eps_buf: jnp.ndarray  # (N,) episodes credited per solution
    next_item: jnp.ndarray  # scalar int32 queue head
    stats: CollectedStats
    key: Any  # (W,) per-lane PRNG chains
    total_steps: jnp.ndarray
    t_global: jnp.ndarray
    # telemetry accumulators (observability.devicemetrics): lane-step slots
    # executed, and lane-steps spent idle while pending work existed (the
    # refill-period / drain-ordering wait — starvation accounting). Frozen at
    # zero when the engine runs with telemetry off.
    capacity: jnp.ndarray
    wait_sum: jnp.ndarray
    # queue-wait histogramming (ISSUE 15): idle_since stamps the loop step
    # at which each lane's episode finished; when a refill reuses the lane,
    # (now - stamp) is the item's wait, bucketed into the (G, B) log-spaced
    # histogram `hist`. lane_groups/group_counts mirror RolloutCarry's
    # per-group accounting (empty sentinels at num_groups == 1); with
    # telemetry off idle_since/hist are empty sentinels too.
    idle_since: jnp.ndarray
    hist: jnp.ndarray
    lane_groups: jnp.ndarray
    group_counts: jnp.ndarray


def _default_refill_width(total_items: int) -> int:
    """W defaults to ~1/8 of the work-list (pow2, floor 128): small enough
    that the queue keeps lanes saturated until near the end, large enough to
    amortize per-step fixed costs."""
    return min(total_items, max(128, _pow2_at_least(max(1, total_items // 8))))


def _refill_forward_setup(policy, params_batch, trunk_block: int = 0):
    """Per-lane parameter storage + forward for the refill engine.

    The loop carries only the PER-LANE slice of the population (dense rows,
    or factored coefficients — the shared center/basis/factors stay
    loop-invariant closures), so a refill gathers O(W x row), never the
    whole population. Returns ``(store, forward)``: ``store`` is the
    (N, row) gather source and ``forward(lane_params, obs, states)`` runs
    the policy at width W."""
    if isinstance(params_batch, TrunkDeltaParamsBatch):
        from .lowrank import (
            _apply_trunk_delta,
            _apply_trunk_delta_blocked,
            prepare_trunk_delta,
            trunk_delta_supported,
        )

        if trunk_delta_supported(policy.module):
            prepared = prepare_trunk_delta(policy, params_batch)
            blk = int(trunk_block)

            def forward(lane_coeffs, obs, states):
                w = obs.shape[0]
                if blk > 0 and w > blk and w % blk == 0:
                    return _apply_trunk_delta_blocked(
                        policy.module,
                        prepared.center_tree,
                        prepared.factors,
                        lane_coeffs,
                        obs,
                        states,
                        blk,
                    )
                return _apply_trunk_delta(
                    policy.module,
                    prepared.center_tree,
                    prepared.factors,
                    lane_coeffs,
                    obs,
                    states,
                )

        else:
            import warnings

            warnings.warn(
                "trunk-delta refill forward fell back to materializing dense "
                f"per-lane parameter rows (W, {params_batch.center.shape[-1]}) "
                f"every step: {type(policy.module).__name__} has no "
                "structured trunk-delta path (supported: Sequential stacks "
                "of Linear/Bias/RNN/LSTM/parameterless layers)",
                stacklevel=3,
            )

            def forward(lane_coeffs, obs, states):
                dense = params_batch.materialize_rows(lane_coeffs)
                return _batched_forward(policy, dense, None, obs, states)

        return params_batch.coeffs, forward
    if isinstance(params_batch, LowRankParamsBatch):
        from .lowrank import _apply_lowrank, lowrank_supported, prepare_lowrank

        if lowrank_supported(policy.module):
            prepared = prepare_lowrank(policy, params_batch)

            def forward(lane_coeffs, obs, states):
                return _apply_lowrank(
                    policy.module,
                    prepared.center_tree,
                    prepared.basis_tree,
                    lane_coeffs,
                    obs,
                    states,
                )

        else:
            import warnings

            # the same LOUD-fallback contract as net/lowrank.py (VERDICT r3
            # #3): the caller chose the factored representation to avoid
            # dense parameter rows, and here they get rebuilt every step
            warnings.warn(
                "low-rank refill forward fell back to materializing dense "
                f"per-lane parameter rows (W, {params_batch.center.shape[-1]}) "
                f"every step: {type(policy.module).__name__} has no "
                "structured low-rank path (supported: Sequential stacks of "
                "Linear/Bias/RNN/LSTM/parameterless layers)",
                stacklevel=3,
            )

            def forward(lane_coeffs, obs, states):
                dense = params_batch.materialize_rows(lane_coeffs)
                return _batched_forward(policy, dense, None, obs, states)

        return params_batch.coeffs, forward

    def forward(lane_params, obs, states):
        return _batched_forward(policy, lane_params, None, obs, states)

    return params_batch, forward


def _run_refill(
    env,
    policy: FlatParamsPolicy,
    params_batch,
    key,
    stats: CollectedStats,
    *,
    num_episodes: int,
    max_t: int,
    observation_normalization: bool,
    alive_bonus_schedule,
    decrease_rewards_by,
    action_noise_stdev,
    compute_dtype,
    lane_ids,
    solution_keys,
    stats_sync_axis,
    refill_width,
    refill_period,
    seed_stride,
    telemetry=True,
    health=True,
    num_valid=None,
    groups=None,
    num_groups=1,
    trunk_block=0,
    nonfinite_quarantine=False,
    nonfinite_penalty=None,
    nonfinite_sync_axis=None,
) -> RolloutResult:
    """The ``episodes_refill`` evaluation: exact ``episodes`` semantics (each
    solution is scored by the mean return of exactly ``num_episodes``
    episodes), evaluated work-conservingly at fixed width. Called inside the
    ``run_vectorized_rollout`` trace."""
    if not jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        # legacy raw uint32 keys (jax.random.PRNGKey): wrap into a typed key
        # array so the per-lane chains stay rank-1 and the refill step's
        # jnp.where lane-selects work on them. The monolithic engine only
        # ever vmaps fold_in/split over its keys, so it accepts either form
        # — and wrapping preserves the key bits, so matched-seed
        # bit-identity to it holds for legacy keys too.
        key = jax.random.wrap_key_data(key)
    if solution_keys is not None and not jnp.issubdtype(
        solution_keys.dtype, jax.dtypes.prng_key
    ):
        solution_keys = jax.random.wrap_key_data(solution_keys)
    n = _params_popsize(params_batch)
    # under width padding (num_valid < n) the work queue only enumerates the
    # genuine solutions: padding rows never receive items, so their eps_buf
    # stays 0 and their mean score is an exact 0.0
    nv = int(num_valid) if num_valid is not None else n
    total_items = nv * int(num_episodes)
    width = refill_width if refill_width is not None else _default_refill_width(total_items)
    width = int(min(max(1, int(width)), total_items))
    period = max(1, int(refill_period))
    stride = int(seed_stride) if seed_stride is not None else nv

    params_batch = _params_cast(params_batch, compute_dtype)
    if lane_ids is None:
        lane_ids = jnp.arange(n, dtype=jnp.int32)
    store, forward = _refill_forward_setup(
        policy, params_batch, trunk_block=int(trunk_block)
    )

    collect_groups = bool(telemetry) and int(num_groups) > 1 and groups is not None
    groups_arr = (
        jnp.asarray(groups, dtype=jnp.int32) if collect_groups else None
    )

    # stacked (per-group) observation-normalization slots: detected by the
    # count's rank so the traced signature is the discriminator (an aval
    # rank change is a different program anyway — no new static argument)
    stacked_stats = stats is not None and getattr(stats.count, "ndim", 0) == 1
    if stacked_stats:
        if not collect_groups:
            raise ValueError(
                "stacked (per-group) stats require telemetry plus a groups"
                " array with num_groups > 1 — each slot needs lane->group"
                " bindings to credit"
            )
        if stats.count.shape[0] != int(num_groups):
            raise ValueError(
                f"stacked stats carry {stats.count.shape[0]} slots but"
                f" num_groups={num_groups}"
            )

    def item_keys(items):
        """(chain, reset) PRNG keys + solution index of queue items. Episode
        ``e`` of solution ``s`` is seeded ``fold_in(key, lane_ids[s] +
        e * seed_stride)`` — at e=0 exactly the monolithic runner's per-lane
        seeding, so matched-seed refill reproduces plain ``episodes``
        bit-for-bit at ``num_episodes=1`` (observation normalization off —
        see the ``run_vectorized_rollout`` docstring), for ANY width,
        sharded or not (``seed_stride`` must be the GLOBAL popsize on a
        sharded caller). With ``solution_keys``, each item folds its seed
        into ITS solution's base key instead of the shared ``key`` — the
        per-tenant isolation form (see ``run_vectorized_rollout``)."""
        sol = items % nv
        ep = items // nv
        seeds = lane_ids[sol] + ep * jnp.int32(stride)
        if solution_keys is not None:
            ik = jax.vmap(jax.random.fold_in)(solution_keys[sol], seeds)
        else:
            ik = jax.vmap(lambda s: jax.random.fold_in(key, s))(seeds)
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(ik)
        return pair[:, 0], pair[:, 1], sol

    items0 = jnp.arange(width, dtype=jnp.int32)
    chain0, reset0, sol0 = item_keys(items0)
    env_states0, obs0 = _env_reset(env, reset0)
    if observation_normalization:
        if stacked_stats:
            new_stats = group_stats_update(
                stats, obs0, groups_arr[sol0], None, int(num_groups)
            )
        else:
            new_stats = stats_update(
                stats, obs0, mask=jnp.ones(width, dtype=bool)
            )
        if stats_sync_axis is not None:
            new_stats = _stats_psum_merge(stats, new_stats, stats_sync_axis)
        stats = new_stats

    policy_states0 = _initial_policy_states(policy, width, compute_dtype)

    if telemetry:
        # the histogram is carried even at G=1 (one row): tail queue wait is
        # a property of the refill schedule, not of multi-tenancy
        hist_groups = int(num_groups) if collect_groups else 1
        hist0 = jnp.zeros((hist_groups, QUEUE_WAIT_BUCKETS), dtype=jnp.int32)
        idle_since0 = jnp.zeros(width, dtype=jnp.int32)
    else:
        hist0 = jnp.zeros((0, QUEUE_WAIT_BUCKETS), dtype=jnp.int32)
        idle_since0 = jnp.zeros((0,), dtype=jnp.int32)
    if collect_groups:
        lane_groups0 = groups_arr[sol0]
        group_counts0 = _init_group_counts(lane_groups0, int(num_groups))
    else:
        lane_groups0 = _empty_lane_groups()
        group_counts0 = _empty_group_counts()

    carry = RefillCarry(
        env_states=env_states0,
        obs=obs0,
        policy_states=policy_states0,
        lane_params=store[sol0],
        lane_sol=sol0,
        lane_score=jnp.zeros(width),
        steps_in_episode=jnp.zeros(width, dtype=jnp.int32),
        active=jnp.ones(width, dtype=bool),
        scores_buf=jnp.zeros(n, dtype=jnp.float32),
        eps_buf=jnp.zeros(n, dtype=jnp.int32),
        next_item=jnp.asarray(width, dtype=jnp.int32),
        stats=stats,
        key=chain0,
        total_steps=jnp.zeros((), dtype=jnp.int32),
        t_global=jnp.zeros((), dtype=jnp.int32),
        capacity=jnp.zeros((), dtype=jnp.int32),
        wait_sum=jnp.zeros((), dtype=jnp.int32),
        idle_since=idle_since0,
        hist=hist0,
        lane_groups=lane_groups0,
        group_counts=group_counts0,
    )

    def step(c: RefillCarry) -> RefillCarry:
        # the per-lane chains advance ONLY when this config draws action
        # noise (refill resets use the item's own key, not the lane chain) —
        # the same 3-way split discipline as the monolithic engine, so the
        # realized noise matches it draw-for-draw
        if action_noise_stdev is not None:
            triple = jax.vmap(lambda k: jax.random.split(k, 3))(c.key)
            lane_keys, noise_keys = triple[:, 0], triple[:, 1]
        else:
            lane_keys, noise_keys = c.key, None

        if not observation_normalization:
            policy_in = c.obs
        elif stacked_stats:
            # per-group slots: each lane is normalized by ITS group's
            # running statistics (tenant isolation)
            policy_in = group_stats_normalize(c.stats, c.obs, c.lane_groups)
        else:
            policy_in = stats_normalize(c.stats, c.obs)
        if compute_dtype is not None:
            policy_in = policy_in.astype(compute_dtype)
        raw, new_policy_states = forward(c.lane_params, policy_in, c.policy_states)
        if compute_dtype is not None:
            raw = raw.astype(jnp.float32)

        noise = None
        if action_noise_stdev is not None:
            noise = action_noise_stdev * jax.vmap(
                lambda k: jax.random.normal(k, raw.shape[1:])
            )(noise_keys)
        actions = _policy_to_action(raw, env.action_space, noise, clip=True)

        if getattr(env, "batched_native", False):
            new_env_states, new_obs, rewards, dones = env.batch_step(
                c.env_states, actions
            )
        else:
            new_env_states, new_obs, rewards, dones = jax.vmap(env.step)(
                c.env_states, actions
            )

        steps_in_episode = c.steps_in_episode + 1
        dones = dones | (steps_in_episode >= max_t)
        if decrease_rewards_by is not None:
            rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            rewards = rewards + alive_bonus_for_step(
                steps_in_episode, alive_bonus_schedule
            ) * (~dones)

        active_f = c.active
        lane_score = c.lane_score + jnp.where(active_f, rewards, 0.0)
        finished = dones & active_f
        # segment reduction: credit finished episodes to their solutions
        # (idle lanes contribute an exact 0.0 to whatever row they last ran)
        scores_buf = c.scores_buf.at[c.lane_sol].add(
            jnp.where(finished, lane_score, 0.0)
        )
        eps_buf = c.eps_buf.at[c.lane_sol].add(finished.astype(jnp.int32))
        total_steps = c.total_steps + jnp.sum(active_f.astype(jnp.int32))

        running = active_f & ~finished
        # freeze non-running lanes at their pre-step state (the monolithic
        # engine's no-reset trick: bounded states, no NaN leakage) and reset
        # their per-episode bookkeeping so a later refill starts clean.
        # Policy states return to the policy's INITIAL state — not zeros —
        # so a refilled episode starts exactly like _rollout_init's (the
        # bit-identity contract must hold for stateful policies whose
        # initial_state() is nonzero, not just the built-in RNN/LSTM zeros)
        env_states_base = _env_state_select(env, running, new_env_states, c.env_states)
        obs_base = _lane_select(running, new_obs, c.obs)
        steps_base = jnp.where(running, steps_in_episode, 0)
        lane_score = jnp.where(running, lane_score, 0.0)
        policy_states_base = (
            None
            if new_policy_states is None
            else jax.tree_util.tree_map(
                lambda s, init: _lane_select(running, s, init),
                new_policy_states,
                policy_states0,
            )
        )

        idle = ~running
        gate = jnp.any(idle) & (c.next_item < total_items)
        if period > 1:
            gate = gate & (((c.t_global + 1) % period) == 0)
        # ranks among idle lanes -> candidate queue items; lanes beyond the
        # queue end stay idle (drained). Computed outside the cond so both
        # branches agree on `take`'s provenance.
        offs = jnp.cumsum(idle.astype(jnp.int32)) - 1
        cand = c.next_item + offs
        take = idle & (cand < total_items) & gate

        def do_refill(op):
            env_states, obs_cur, lane_params, lane_sol, keys = op
            chain, reset_k, sol = item_keys(jnp.where(take, cand, 0))
            fresh_states, fresh_obs = _env_reset(env, reset_k)
            env_states = _env_state_select(env, take, fresh_states, env_states)
            obs_cur = _lane_select(take, fresh_obs, obs_cur)
            lane_sol = jnp.where(take, sol, lane_sol)
            lane_params = _lane_select(take, store[sol], lane_params)
            keys = jnp.where(take, chain, keys)
            return env_states, obs_cur, lane_params, lane_sol, keys

        def skip_refill(op):
            return op

        env_states_next, obs_next, lane_params_next, lane_sol_next, keys_next = (
            jax.lax.cond(
                gate,
                do_refill,
                skip_refill,
                (env_states_base, obs_base, c.lane_params, c.lane_sol, lane_keys),
            )
        )
        active = running | take
        next_item = c.next_item + jnp.sum(take.astype(jnp.int32))

        if telemetry:
            # telemetry: each iteration executes W lane-step slots; lanes
            # idle AFTER this step's refill while the queue still holds work
            # are waiting on the refill gate / drain order (the
            # starvation-accounting numerator)
            capacity = c.capacity + jnp.int32(width)
            wait_sum = c.wait_sum + jnp.where(
                next_item < total_items,
                jnp.sum((~active).astype(jnp.int32)),
                0,
            )
            # queue-wait histogram: a lane's wait is refill step minus the
            # step its previous episode finished (same-step refill = 0 →
            # bucket 0). `take` is all-False when the cond gate is closed,
            # so updating outside the cond adds zeros — no divergence.
            # Lanes drained at queue end never refill → never counted.
            tcur = c.t_global + 1
            idle_since = jnp.where(finished, tcur, c.idle_since)
            waits = jnp.where(take, tcur - idle_since, 0)
            buckets = queue_wait_bucket_index(waits)
            take_i = take.astype(jnp.int32)
            if collect_groups:
                sol_in = jnp.where(take, cand, 0) % nv
                g_in = groups_arr[sol_in]
                hist = c.hist.at[g_in, buckets].add(take_i)
                lane_groups = jnp.where(take, g_in, c.lane_groups)
                per_lane = jnp.stack(
                    [
                        active_f.astype(jnp.int32),
                        finished.astype(jnp.int32),
                        jnp.ones(width, dtype=jnp.int32),
                    ],
                    axis=1,
                )
                group_counts = c.group_counts.at[:, : _COL_LANE_WIDTH].add(
                    jax.ops.segment_sum(
                        per_lane, c.lane_groups, num_segments=num_groups
                    )
                )
                group_counts = group_counts.at[:, _COL_REFILL].add(
                    jax.ops.segment_sum(
                        take_i, g_in, num_segments=num_groups
                    )
                )
                # per-step gating matches the scalar wait_sum above (the
                # UPDATED next_item), so the column sum equals it exactly
                wait_lane = jnp.where(
                    next_item < total_items, (~active).astype(jnp.int32), 0
                )
                group_counts = group_counts.at[:, _COL_WAIT].add(
                    jax.ops.segment_sum(
                        wait_lane, lane_groups, num_segments=num_groups
                    )
                )
            else:
                hist = c.hist.at[0, buckets].add(take_i)
                lane_groups = c.lane_groups
                group_counts = c.group_counts
        else:
            capacity, wait_sum = c.capacity, c.wait_sum
            idle_since, hist = c.idle_since, c.hist
            lane_groups, group_counts = c.lane_groups, c.group_counts

        # obs-norm statistics count ONLY live-lane observations: the
        # post-refill obs each still-active lane will consume next step
        # (idle/drained lanes are masked out entirely). Stacked slots
        # credit the POST-refill lane groups: a fresh reset observation
        # belongs to the incoming item's group, not the departed one's.
        if not observation_normalization:
            new_stats = c.stats
        elif stacked_stats:
            new_stats = group_stats_update(
                c.stats, obs_next, lane_groups, active, num_groups
            )
        else:
            new_stats = stats_update(c.stats, obs_next, mask=active)
        if observation_normalization and stats_sync_axis is not None:
            new_stats = _stats_psum_merge(c.stats, new_stats, stats_sync_axis)

        return RefillCarry(
            env_states=env_states_next,
            obs=obs_next,
            policy_states=policy_states_base,
            lane_params=lane_params_next,
            lane_sol=lane_sol_next,
            lane_score=lane_score,
            steps_in_episode=steps_base,
            active=active,
            scores_buf=scores_buf,
            eps_buf=eps_buf,
            next_item=next_item,
            stats=new_stats,
            key=keys_next,
            total_steps=total_steps,
            t_global=c.t_global + 1,
            capacity=capacity,
            wait_sum=wait_sum,
            idle_since=idle_since,
            hist=hist,
            lane_groups=lane_groups,
            group_counts=group_counts,
        )

    # greedy-scheduling makespan bound (total work / W + longest item) plus
    # the refill-period waiting slack — a safety net, not the exit condition
    hard_cap = (
        (total_items * max_t) // width
        + max_t
        + period * (total_items // width + 1)
        + 2
    )

    def cond(c: RefillCarry):
        # pending queue items keep the loop alive even when every lane is
        # momentarily idle (all lanes can finish on a step whose refill gate
        # is closed by refill_period)
        any_work = jnp.any(c.active) | (c.next_item < total_items)
        if stats_sync_axis is not None:
            # per-step collectives in the body require every shard to run the
            # same number of iterations (see _make_step)
            any_work = (
                jax.lax.psum(any_work.astype(jnp.int32), stats_sync_axis) > 0
            )
        return any_work & (c.t_global < hard_cap)

    final = jax.lax.while_loop(cond, step, carry)
    mean_scores = final.scores_buf / jnp.maximum(final.eps_buf, 1).astype(jnp.float32)
    nf_bad = None
    if nonfinite_quarantine:
        mean_scores, nf_bad = _quarantine_nonfinite(
            mean_scores,
            valid_mask=(
                None
                if num_valid is None
                else jnp.arange(n, dtype=jnp.int32) < nv
            ),
            penalty=nonfinite_penalty,
            sync_axis=nonfinite_sync_axis,
        )
    total_episodes = jnp.sum(final.eps_buf)
    if not telemetry:
        eval_telemetry = None
    elif collect_groups:
        group_counts = final.group_counts
        if nf_bad is not None:
            # scores_buf is per SOLUTION here: charge each quarantined
            # solution's group directly off the per-solution id array
            group_counts = _nonfinite_group_counts(
                group_counts, nf_bad, groups_arr, num_groups
            )
        eval_telemetry = pack_group_telemetry(group_counts, final.hist)
    else:
        eval_telemetry = pack_group_telemetry(
            pack_eval_telemetry(
                env_steps=final.total_steps,
                episodes=total_episodes,
                capacity=final.capacity,
                lane_width=width,
                # items 0..width-1 seeded the lanes; everything past
                # that entered through the refill gather
                refill_events=final.next_item - jnp.int32(width),
                queue_wait=final.wait_sum,
                nonfinite=(
                    0 if nf_bad is None else jnp.sum(nf_bad.astype(jnp.int32))
                ),
            )[None],
            final.hist,
        )
    if eval_telemetry is not None and health:
        eval_telemetry = _health_telemetry(
            eval_telemetry,
            mean_scores,
            groups_arr if collect_groups else None,
            num_groups,
            num_valid,
        )
    return RolloutResult(
        scores=mean_scores,
        stats=final.stats,
        total_steps=final.total_steps,
        total_episodes=total_episodes,
        telemetry=eval_telemetry,
    )


@functools.lru_cache(maxsize=_ENGINE_CACHE_SIZE)
def _compacting_fns(
    env,
    policy: FlatParamsPolicy,
    num_episodes: int,
    max_t: int,
    hard_cap: int,
    observation_normalization: bool,
    alive_bonus_schedule,
    decrease_rewards_by,
    action_noise_stdev,
    compute_dtype,
    stats_sync_axis=None,
    collect_telemetry=True,
    health=True,
    num_groups=1,
    nonfinite_quarantine=False,
    nonfinite_penalty=None,
    nonfinite_sync_axis=None,
):
    """Jitted building blocks of the compacting runner, cached per config so
    repeated calls (every generation) hit XLA's compile cache. ``health``
    appends the v4 search-health block in ``finalize_fn``; the sharded
    wrapper passes ``health=False`` and appends a mesh-global block itself
    (``_compacting_sharded_fns``) so the telemetry psum stays exact."""
    num_groups = int(num_groups)
    step = _make_step(
        env,
        policy,
        num_episodes=num_episodes,
        max_t=max_t,
        observation_normalization=observation_normalization,
        alive_bonus_schedule=alive_bonus_schedule,
        decrease_rewards_by=decrease_rewards_by,
        action_noise_stdev=action_noise_stdev,
        compute_dtype=compute_dtype,
        budget_mode=False,
        stats_sync_axis=stats_sync_axis,
        collect_telemetry=collect_telemetry,
        num_groups=num_groups,
    )

    @jax.jit
    def init_fn(params_batch, key, stats, lane_ids=None, groups=None):
        return _rollout_init(
            env,
            policy,
            params_batch,
            key,
            stats,
            observation_normalization=observation_normalization,
            compute_dtype=compute_dtype,
            lane_ids=lane_ids,
            stats_sync_axis=stats_sync_axis,
            groups=groups,
            num_groups=num_groups,
        )

    @partial(jax.jit, static_argnames=("num_steps",))
    def chunk_fn(params_batch, carry, num_steps: int):
        ctx = _forward_ctx(policy, params_batch)  # loop-invariant, per chunk

        def cond(s):
            i, c = s
            any_active = jnp.any(c.active)
            if stats_sync_axis is not None:
                # per-step collectives: every shard must run the same number
                # of iterations (see _make_step)
                any_active = (
                    jax.lax.psum(any_active.astype(jnp.int32), stats_sync_axis) > 0
                )
            return (i < num_steps) & any_active & (c.t_global < hard_cap)

        def body(s):
            i, c = s
            return i + 1, step(params_batch, ctx, c)

        _, out = jax.lax.while_loop(cond, body, (jnp.zeros((), jnp.int32), carry))
        return out, jnp.sum(out.active.astype(jnp.int32))

    @partial(jax.jit, static_argnames=("new_width",))
    def compact_fn(carry, params_batch, lane_ids, scores_buf, eps_buf, new_width: int):
        # flush every current lane's (final-so-far) score to the full-width
        # buffers, then gather the still-active lanes to the front
        scores_buf = scores_buf.at[lane_ids].set(carry.scores)
        eps_buf = eps_buf.at[lane_ids].set(carry.episodes_done)
        order = jnp.argsort(jnp.logical_not(carry.active))  # stable: active first
        sel = order[:new_width]
        if num_groups > 1:
            # the lanes dropped here leave the carry for good: fold their
            # per-lane accumulators into the group block now (their capacity
            # charge is t_global — present since t=0); survivors keep
            # accumulating and fold at finalize
            width = carry.active.shape[0]
            dropped = jnp.ones(width, bool).at[sel].set(False)
            group_counts = _fold_lane_counts(
                carry.group_counts,
                carry.lane_steps,
                carry.lane_episodes,
                carry.lane_groups,
                carry.t_global,
                num_groups,
                mask=dropped,
            )
        else:
            group_counts = carry.group_counts
        new_carry = RolloutCarry(
            env_states=_env_state_take(env, carry.env_states, sel),
            obs=carry.obs[sel],
            policy_states=(
                None
                if carry.policy_states is None
                else jax.tree_util.tree_map(lambda x: x[sel], carry.policy_states)
            ),
            scores=carry.scores[sel],
            episodes_done=carry.episodes_done[sel],
            steps_in_episode=carry.steps_in_episode[sel],
            active=carry.active[sel],
            stats=carry.stats,
            key=carry.key[sel],  # per-lane chains travel with their lanes
            total_steps=carry.total_steps,
            t_global=carry.t_global,
            capacity=carry.capacity,  # capacity already paid at prior widths
            # the folded group block survives compaction whole; lane group
            # ids and per-lane accumulators travel with their lanes like the
            # PRNG chains
            lane_groups=(
                carry.lane_groups[sel] if num_groups > 1 else carry.lane_groups
            ),
            group_counts=group_counts,
            lane_steps=(
                carry.lane_steps[sel] if num_groups > 1 else carry.lane_steps
            ),
            lane_episodes=(
                carry.lane_episodes[sel] if num_groups > 1 else carry.lane_episodes
            ),
        )
        return new_carry, _params_take(params_batch, sel), lane_ids[sel], scores_buf, eps_buf

    @jax.jit
    def finalize_fn(carry, lane_ids, scores_buf, eps_buf, groups_full=None):
        scores_buf = scores_buf.at[lane_ids].set(carry.scores)
        eps_buf = eps_buf.at[lane_ids].set(carry.episodes_done)
        mean_scores = scores_buf / jnp.maximum(eps_buf, 1)
        nf_bad = None
        if nonfinite_quarantine:
            mean_scores, nf_bad = _quarantine_nonfinite(
                mean_scores,
                penalty=nonfinite_penalty,
                sync_axis=nonfinite_sync_axis,
            )
        total_episodes = jnp.sum(eps_buf)
        if not collect_telemetry:
            telemetry = None
        elif num_groups > 1:
            # fold the surviving lanes' accumulators (dropped lanes folded at
            # their compaction boundary)
            group_counts = _fold_lane_counts(
                carry.group_counts,
                carry.lane_steps,
                carry.lane_episodes,
                carry.lane_groups,
                carry.t_global,
                num_groups,
            )
            if nf_bad is not None:
                # quarantine is per SOLUTION on the scattered-back buffers:
                # the full-width per-solution group ids do the charging
                group_counts = _nonfinite_group_counts(
                    group_counts, nf_bad, groups_full, num_groups
                )
            telemetry = pack_group_telemetry(group_counts)
        else:
            telemetry = pack_group_telemetry(
                pack_eval_telemetry(
                    env_steps=carry.total_steps,
                    episodes=total_episodes,
                    # carry.capacity summed width x iterations through every
                    # compaction, so occupancy credits the narrowing directly
                    capacity=carry.capacity,
                    lane_width=scores_buf.shape[0],
                    nonfinite=(
                        0 if nf_bad is None else jnp.sum(nf_bad.astype(jnp.int32))
                    ),
                )[None]
            )
        if telemetry is not None and health:
            telemetry = _health_telemetry(
                telemetry,
                mean_scores,
                groups_full if num_groups > 1 else None,
                num_groups,
                None,  # the compacting runner never pads its buffers
            )
        return mean_scores, total_episodes, telemetry

    return init_fn, chunk_fn, compact_fn, finalize_fn


def run_vectorized_rollout_compacting(
    env,
    policy: FlatParamsPolicy,
    params_batch: jnp.ndarray,
    key,
    stats: CollectedStats,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    observation_normalization: bool = False,
    alive_bonus_schedule: Optional[tuple] = None,
    decrease_rewards_by: Optional[float] = None,
    action_noise_stdev: Optional[float] = None,
    compute_dtype=None,
    chunk_size: int = 25,
    min_width: Optional[int] = None,
    allowed_widths: Optional[tuple] = None,
    prewarm: bool = False,
    telemetry: bool = True,
    health: bool = True,
    groups=None,
    num_groups: int = 1,
    nonfinite_quarantine: bool = False,
    nonfinite_penalty: Optional[float] = None,
) -> RolloutResult:
    """Episodes-contract evaluation with **lane compaction** — the
    host-orchestrated fast path for ``eval_mode="episodes"``.

    Semantics are those of ``run_vectorized_rollout(eval_mode="episodes")``
    (the reference's ``VecGymNE`` contract, ``vecgymne.py:837-904``): each
    lane runs exactly ``num_episodes`` episodes and its score is the mean
    episodic return. The difference is purely how the machine spends its
    cycles: the loop runs in ``chunk_size``-step jitted chunks; after each
    chunk the number of still-active lanes is inspected, and when it fits in
    a smaller allowed width the active lanes are sorted to the front,
    gathered, and the loop continues narrow — finished lanes stop consuming
    compute instead of idling masked until the slowest survivor ends.

    Orchestration details:

    - The compaction decision is **pipelined one chunk behind**: the next
      chunk is dispatched before the previous chunk's active-count is read,
      so the device never sits idle waiting on the host round-trip (which
      matters on tunneled TPU links).
    - The working width starts at N and descends through a small fixed menu
      (``allowed_widths``, default: the powers of two in
      ``[max(256, pow2(N/64)), N/2]``), jumping straight to the TIGHTEST
      width that holds the survivors — skewed death-time distributions kill
      most of the population in the first chunks, and stepping one notch per
      chunk would pay several more chunks at wide widths. The expensive
      compilations (the stepping program per width) are bounded by the menu
      size and prewarmed by ``prewarm=True``; a jump adds only a cheap
      (from, to) gather trace.
    - Results are scattered into full-width device buffers keyed by original
      lane id, so scores come back in the caller's order with no host-side
      bookkeeping.

    Scores are numerically identical to the monolithic runner's in every
    configuration — multi-episode, action noise: randomness is a per-lane
    property (each lane carries its own PRNG chain, gathered along with its
    state on compaction — ``_rollout_init``), so compaction reorders lanes
    without touching any lane's dynamics, noise or resets. (With
    observation normalization the masked stat reductions cover the same
    lane set at every width, so scores agree up to float summation order.)

    Not traceable (it syncs lane counts to the host); use the monolithic
    runner inside jit/shard_map.
    """
    n = _params_popsize(params_batch)
    max_t = env.max_episode_steps if env.max_episode_steps is not None else 1000
    if episode_length is not None:
        max_t = min(max_t, int(episode_length))
    hard_cap = max_t * int(num_episodes) + 1

    num_groups = int(num_groups)
    if num_groups > 1 and groups is None:
        raise ValueError("num_groups > 1 requires a groups array of per-solution ids")
    if not (telemetry and num_groups > 1):
        groups, num_groups = None, 1

    init_fn, chunk_fn, compact_fn, finalize_fn = _compacting_fns(
        env,
        policy,
        int(num_episodes),
        max_t,
        hard_cap,
        bool(observation_normalization),
        alive_bonus_schedule,
        decrease_rewards_by,
        action_noise_stdev,
        compute_dtype,
        collect_telemetry=bool(telemetry),
        health=bool(health),
        num_groups=num_groups,
        nonfinite_quarantine=bool(nonfinite_quarantine),
        nonfinite_penalty=nonfinite_penalty,
    )
    groups_full = (
        jnp.asarray(groups, dtype=jnp.int32) if num_groups > 1 else None
    )

    if allowed_widths is None:
        if min_width is None:
            # floor 256 (one full lane tile's worth of sublane batches):
            # deeper menus than the r3 n/16 floor — with the compile set
            # bounded to the descent pairs (prewarmable), the tail of a
            # skewed-death population is worth tracking tightly
            min_width = max(256, _pow2_at_least(max(1, n // 64)))
        widths = []
        w = _pow2_at_least(min_width)
        while w <= n // 2:
            widths.append(w)
            w *= 2
        allowed_widths = tuple(sorted(widths))
    else:
        allowed_widths = tuple(sorted(int(w) for w in allowed_widths if w < n))

    carry, params = init_fn(params_batch, key, stats, groups=groups_full)
    lane_ids = jnp.arange(n, dtype=jnp.int32)
    scores_buf = jnp.zeros(n, dtype=jnp.float32)
    eps_buf = jnp.zeros(n, dtype=jnp.int32)

    if prewarm:
        # compile chunk + finalize at every width and EVERY (from, to)
        # compact pair a runtime jump can hit — the jump policy's first real
        # compaction is typically full-width -> min_width directly, so the
        # adjacent chain alone would leave that trace in the timing loop.
        # O(k^2) tiny gather traces + k stepping programs, on throwaway
        # copies of the initial state
        c0, _ = chunk_fn(params, carry, int(chunk_size))
        finalize_fn(c0, lane_ids, scores_buf, eps_buf, groups_full)
        states = {c0.active.shape[0]: (c0, params, lane_ids, scores_buf, eps_buf)}
        for w in sorted(allowed_widths, reverse=True):
            narrowed = None
            for fw in sorted(states, reverse=True):
                if fw > w:
                    narrowed = compact_fn(*states[fw], w)
            if narrowed is None:
                continue
            c, p, ids, sb, eb = narrowed
            c, _ = chunk_fn(p, c, int(chunk_size))
            finalize_fn(c, ids, sb, eb, groups_full)
            states[w] = (c, p, ids, sb, eb)
        jax.block_until_ready(jax.tree_util.tree_leaves(states)[0])

    max_chunks = -(-hard_cap // int(chunk_size)) + 1
    prev_count = None
    for _ in range(max_chunks):
        carry, count = chunk_fn(params, carry, int(chunk_size))
        if prev_count is not None:
            # reading the PREVIOUS chunk's count: that result is already (or
            # nearly) computed, while the chunk just dispatched keeps the
            # device busy during this host round-trip
            n_active = int(prev_count)
            if n_active == 0:
                break
            width = carry.active.shape[0]
            # jump straight to the TIGHTEST allowed width that holds the
            # survivors: with skewed death-time distributions most of the
            # population dies in the first chunks, and stepping the menu one
            # notch per chunk would pay several more chunks at wide widths.
            # The expensive compile (chunk_fn) is still one per width;
            # jumping only adds cheap (from, to) gather traces
            fits = [w for w in allowed_widths if w < width and n_active <= w]
            if fits:
                carry, params, lane_ids, scores_buf, eps_buf = compact_fn(
                    carry, params, lane_ids, scores_buf, eps_buf, min(fits)
                )
        prev_count = count

    mean_scores, total_episodes, eval_telemetry = finalize_fn(
        carry, lane_ids, scores_buf, eps_buf, groups_full
    )
    return RolloutResult(
        scores=mean_scores,
        stats=carry.stats,
        total_steps=carry.total_steps,
        total_episodes=total_episodes,
        telemetry=eval_telemetry,
    )


# ----------------------- sharded lane-compacting runner -----------------------
# The episodes contract on a device mesh (VERDICT r3 #5): the jitted chunk /
# compact / finalize building blocks above are shard_mapped over a "pop"
# axis, while the host loop — the compaction decision — stays outside,
# exactly as in the single-device runner. The loop carry crosses shard_map
# boundaries between chunks, so it must have a consistent sharded global
# form: per-lane leaves shard over the mesh; per-shard "scalars" (stats,
# key, step counters — which genuinely DIVERGE between shards) get a leading
# shard axis so their global form is a (n_shards, ...) stack. Widths are
# per-shard and uniform across shards (SPMD: one trace), so the compaction
# decision reads the MAX active count over shards.


def _expand_shard_scalars(carry: "RolloutCarry") -> "RolloutCarry":
    """Give the per-shard scalar leaves a leading length-1 axis (the local
    view of a (n_shards, ...) global stack). ``key`` is per-lane state and
    needs no expansion."""
    ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)  # noqa: E731
    return carry._replace(
        stats=ex(carry.stats),
        total_steps=carry.total_steps[None],
        t_global=carry.t_global[None],
        capacity=carry.capacity[None],
        # per-shard PARTIAL per-group sums (psum'd at finalize); lane_groups
        # is a lane leaf and shards like scores
        group_counts=carry.group_counts[None],
    )


def _squeeze_shard_scalars(carry: "RolloutCarry") -> "RolloutCarry":
    sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)  # noqa: E731
    return carry._replace(
        stats=sq(carry.stats),
        total_steps=carry.total_steps[0],
        t_global=carry.t_global[0],
        capacity=carry.capacity[0],
        # graftlint: allow(telemetry-schema): [0] squeezes the leading shard axis, not a wire column
        group_counts=carry.group_counts[0],
    )


def _sharded_carry_specs(env, axis_name: str) -> "RolloutCarry":
    from jax.sharding import PartitionSpec as P

    lane = P(axis_name)
    env_spec = (
        env.batch_shard_spec(axis_name)
        if getattr(env, "batched_native", False)
        else lane
    )
    # stats/counters carry the leading shard axis (see expand above); key is
    # the per-lane chain array, a lane leaf like scores
    return RolloutCarry(
        env_states=env_spec,
        obs=lane,
        policy_states=lane,
        scores=lane,
        episodes_done=lane,
        steps_in_episode=lane,
        active=lane,
        stats=lane,
        key=lane,
        total_steps=lane,
        t_global=lane,
        capacity=lane,
        lane_groups=lane,
        group_counts=lane,
        lane_steps=lane,
        lane_episodes=lane,
    )


def global_lane_ids(axis_name: str, n_local: int) -> jnp.ndarray:
    """This shard's GLOBAL lane indices (inside ``shard_map``): the seeding
    contract of the per-lane PRNG chains — every sharded caller must derive
    ids exactly this way (rank * n_local + local index) for sharded
    evaluation to reproduce the unsharded one."""
    rank = jax.lax.axis_index(axis_name)
    return rank * n_local + jnp.arange(n_local, dtype=jnp.int32)


def _params_kind(params_batch) -> str:
    """Hashable representation tag for the lru-cached sharded builders."""
    if isinstance(params_batch, TrunkDeltaParamsBatch):
        return "trunk_delta"
    if isinstance(params_batch, LowRankParamsBatch):
        return "lowrank"
    return "dense"


def _params_shard_spec(params_kind: str, axis_name: str):
    from jax.sharding import PartitionSpec as P

    if params_kind == "lowrank":
        # coefficients shard; the shared center/basis replicate
        return LowRankParamsBatch(center=P(), basis=P(), coeffs=P(axis_name))
    if params_kind == "trunk_delta":
        # coefficients shard; trunk, effective basis and the factor tree
        # replicate (factors=P() is a pytree-prefix spec over the subtree)
        return TrunkDeltaParamsBatch(
            center=P(), basis=P(), coeffs=P(axis_name), factors=P()
        )
    return P(axis_name)


@functools.lru_cache(maxsize=_ENGINE_CACHE_SIZE)
def _compacting_sharded_fns(
    env,
    policy: FlatParamsPolicy,
    num_episodes: int,
    max_t: int,
    hard_cap: int,
    observation_normalization: bool,
    alive_bonus_schedule,
    decrease_rewards_by,
    action_noise_stdev,
    compute_dtype,
    mesh,
    axis_name: str,
    params_kind: str,
    stats_sync: bool = False,
    collect_telemetry: bool = True,
    health: bool = True,
    num_groups: int = 1,
    nonfinite_quarantine: bool = False,
    nonfinite_penalty=None,
):
    from jax.sharding import PartitionSpec as P

    num_groups = int(num_groups)
    init_fn, chunk_fn, compact_fn, finalize_fn = _compacting_fns(
        env,
        policy,
        num_episodes,
        max_t,
        hard_cap,
        observation_normalization,
        alive_bonus_schedule,
        decrease_rewards_by,
        action_noise_stdev,
        compute_dtype,
        stats_sync_axis=axis_name if stats_sync else None,
        collect_telemetry=collect_telemetry,
        # the per-shard finalize must NOT append a health block: the
        # telemetry psum below would sum the bit-cast float columns across
        # shards into garbage. sh_finalize_local all_gathers the scores and
        # appends ONE mesh-global block (shard-0 masked) instead.
        health=False,
        num_groups=num_groups,
        nonfinite_quarantine=nonfinite_quarantine,
        nonfinite_penalty=nonfinite_penalty,
        # the worst-finite reduction pmins over the mesh so each shard
        # quarantines to the GLOBAL worst finite score (bit-identity with
        # the unsharded runner); a fixed penalty needs no collective
        nonfinite_sync_axis=(
            axis_name if (nonfinite_quarantine and nonfinite_penalty is None) else None
        ),
    )
    carry_specs = _sharded_carry_specs(env, axis_name)
    params_spec = _params_shard_spec(params_kind, axis_name)
    lane = P(axis_name)

    if num_groups > 1:
        # group ids ride in as a 4th lane-sharded input; each shard seeds
        # its partial per-group sums from its own lanes (psum'd at finalize)
        def sh_init_local(params_shard, groups_shard, key, stats):
            n_local = _params_popsize(params_shard)
            carry, params_cast = init_fn(
                params_shard,
                key,
                stats,
                global_lane_ids(axis_name, n_local),
                groups_shard,
            )
            lane_ids = jnp.arange(n_local, dtype=jnp.int32)  # LOCAL buffer ids
            scores_buf = jnp.zeros(n_local, dtype=jnp.float32)
            eps_buf = jnp.zeros(n_local, dtype=jnp.int32)
            return _expand_shard_scalars(carry), params_cast, lane_ids, scores_buf, eps_buf

        sh_init = jax.jit(
            jax.shard_map(
                sh_init_local,
                mesh=mesh,
                in_specs=(params_spec, lane, P(), P()),
                out_specs=(carry_specs, params_spec, lane, lane, lane),
                check_vma=False,
            )
        )
    else:

        def sh_init_local(params_shard, key, stats):
            # GLOBAL lane ids seed the per-lane PRNG chains (same key on
            # every shard): the sharded evaluation reproduces the unsharded
            # one, whatever the topology
            n_local = _params_popsize(params_shard)
            carry, params_cast = init_fn(
                params_shard, key, stats, global_lane_ids(axis_name, n_local)
            )
            lane_ids = jnp.arange(n_local, dtype=jnp.int32)  # LOCAL buffer ids
            scores_buf = jnp.zeros(n_local, dtype=jnp.float32)
            eps_buf = jnp.zeros(n_local, dtype=jnp.int32)
            return _expand_shard_scalars(carry), params_cast, lane_ids, scores_buf, eps_buf

        sh_init = jax.jit(
            jax.shard_map(
                sh_init_local,
                mesh=mesh,
                in_specs=(params_spec, P(), P()),
                out_specs=(carry_specs, params_spec, lane, lane, lane),
                check_vma=False,
            )
        )

    chunk_cache: dict = {}

    def sh_chunk(params, carry, num_steps: int):
        fn = chunk_cache.get(num_steps)
        if fn is None:

            def local(params_shard, carry):
                c, count = chunk_fn(params_shard, _squeeze_shard_scalars(carry), num_steps)
                return _expand_shard_scalars(c), count[None]

            fn = jax.jit(
                jax.shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(params_spec, carry_specs),
                    out_specs=(carry_specs, lane),
                    check_vma=False,
                )
            )
            chunk_cache[num_steps] = fn
        return fn(params, carry)

    compact_cache: dict = {}

    def sh_compact(carry, params, lane_ids, scores_buf, eps_buf, new_width: int):
        fn = compact_cache.get(new_width)
        if fn is None:

            def local(carry, params_shard, lane_ids, scores_buf, eps_buf):
                c, p, ids, sb, eb = compact_fn(
                    _squeeze_shard_scalars(carry),
                    params_shard,
                    lane_ids,
                    scores_buf,
                    eps_buf,
                    new_width,
                )
                return _expand_shard_scalars(c), p, ids, sb, eb

            fn = jax.jit(
                jax.shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(carry_specs, params_spec, lane, lane, lane),
                    out_specs=(carry_specs, params_spec, lane, lane, lane),
                    check_vma=False,
                )
            )
            compact_cache[new_width] = fn
        return fn(carry, params, lane_ids, scores_buf, eps_buf)

    def sh_finalize_local(carry, lane_ids, scores_buf, eps_buf, groups_shard, stats0):
        c = _squeeze_shard_scalars(carry)
        mean_scores, eps_total_local, telemetry = finalize_fn(
            c, lane_ids, scores_buf, eps_buf, groups_shard
        )
        if telemetry is None:
            telemetry_out = jnp.zeros((0,), dtype=jnp.int32)
        else:
            if health:
                # mesh-global search-health block: gather every shard's
                # final scores into GLOBAL lane order (shards hold
                # contiguous blocks, so tiled all_gather IS the unsharded
                # order), compute the identical full-population reduction
                # on every shard, then zero all but shard 0's copy — the
                # integer psum below then carries the bit-cast float
                # columns through exactly (0.0 bit-casts to 0)
                g_scores = jax.lax.all_gather(
                    mean_scores, axis_name, tiled=True
                )
                g_groups = (
                    jax.lax.all_gather(groups_shard, axis_name, tiled=True)
                    if groups_shard is not None
                    else None
                )
                block = compute_health_block(g_scores, g_groups, num_groups)
                shard0 = (jax.lax.axis_index(axis_name) == 0).astype(
                    block.dtype
                )
                telemetry = append_health_block(telemetry, block * shard0)
            # every slot is additive, so the mesh-global telemetry is one psum
            telemetry_out = jax.lax.psum(telemetry, axis_name)
        if stats_sync:
            # per-step psum already made every shard's stats mesh-global; a
            # final delta merge would count every delta n_shards times
            merged = c.stats
        else:
            # merge per-shard obs-norm stat deltas with a psum (the
            # collective form of the reference's actor delta-sync,
            # gymne.py:524-573)
            delta = jax.tree_util.tree_map(lambda new, old: new - old, c.stats, stats0)
            merged = jax.tree_util.tree_map(
                lambda old, d: old + jax.lax.psum(d, axis_name), stats0, delta
            )
        return (
            mean_scores,
            merged,
            jax.lax.psum(c.total_steps, axis_name),
            jax.lax.psum(eps_total_local, axis_name),
            # per-shard COUNTED interactions (total_steps sums active lanes
            # only, so it is invariant under compaction — compaction saves
            # wall-clock on dead lanes, not counted steps)
            c.total_steps[None],
            telemetry_out,
        )

    if num_groups > 1:
        sh_finalize = jax.jit(
            jax.shard_map(
                sh_finalize_local,
                mesh=mesh,
                in_specs=(carry_specs, lane, lane, lane, lane, P()),
                out_specs=(lane, P(), P(), P(), lane, P()),
                check_vma=False,
            )
        )
    else:
        # no group ids to ship: close over the sentinel so the shard_map
        # signature stays group-free (None is a zero-leaf pytree)
        def sh_finalize_nogroups(carry, lane_ids, scores_buf, eps_buf, stats0):
            return sh_finalize_local(
                carry, lane_ids, scores_buf, eps_buf, None, stats0
            )

        inner = jax.jit(
            jax.shard_map(
                sh_finalize_nogroups,
                mesh=mesh,
                in_specs=(carry_specs, lane, lane, lane, P()),
                out_specs=(lane, P(), P(), P(), lane, P()),
                check_vma=False,
            )
        )

        def sh_finalize(carry, lane_ids, scores_buf, eps_buf, groups, stats0):
            return inner(carry, lane_ids, scores_buf, eps_buf, stats0)

    return sh_init, sh_chunk, sh_compact, sh_finalize


def run_vectorized_rollout_compacting_sharded(
    env,
    policy: FlatParamsPolicy,
    params_batch,
    key,
    stats: CollectedStats,
    *,
    mesh,
    axis_name: str = "pop",
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    observation_normalization: bool = False,
    alive_bonus_schedule: Optional[tuple] = None,
    decrease_rewards_by: Optional[float] = None,
    action_noise_stdev: Optional[float] = None,
    compute_dtype=None,
    chunk_size: int = 25,
    min_width: Optional[int] = None,
    allowed_widths: Optional[tuple] = None,
    prewarm: bool = False,
    return_per_shard_steps: bool = False,
    stats_sync: bool = False,
    telemetry: bool = True,
    health: bool = True,
    groups=None,
    num_groups: int = 1,
    nonfinite_quarantine: bool = False,
    nonfinite_penalty: Optional[float] = None,
) -> RolloutResult:
    """``run_vectorized_rollout_compacting`` with the population sharded over
    ``mesh[axis_name]``: each device narrows ITS working set as its lanes
    finish, so the episodes contract stops paying for dead lanes on every
    shard — the single-device runner's win, preserved on the hardware the
    framework targets (VERDICT r3 #5).

    ``allowed_widths``/``min_width`` are PER-SHARD widths; the width descent
    is uniform across shards (one SPMD trace per width), driven by the MAX
    per-shard active count so no shard overflows. Per-lane PRNG chains are
    seeded by GLOBAL lane ids with the same base key on every shard, so
    without observation normalization scores/counters are BIT-IDENTICAL to
    the unsharded ``eval_mode="episodes"`` evaluation of the same
    population — the mesh is an execution detail. (With observation
    normalization, each shard's lanes are normalized by their shard-local
    running statistics mid-rollout — cohort semantics, like the reference's
    per-actor stats — so sharded scores differ from unsharded ones; pass
    ``stats_sync=True`` to psum-merge the stat deltas every step instead,
    making every shard normalize by the mesh-global cohort.)

    Not traceable (it syncs lane counts to the host between chunks); call it
    from host code. Returns a :class:`RolloutResult` whose ``stats`` are the
    psum-merged statistics and whose counters are mesh-global."""
    n = _params_popsize(params_batch)
    n_shards = int(mesh.shape[axis_name])
    if n % n_shards != 0:
        raise ValueError(f"Population size {n} must divide the mesh axis {n_shards}")
    n_local = n // n_shards
    max_t = env.max_episode_steps if env.max_episode_steps is not None else 1000
    if episode_length is not None:
        max_t = min(max_t, int(episode_length))
    hard_cap = max_t * int(num_episodes) + 1

    num_groups = int(num_groups)
    if num_groups > 1 and groups is None:
        raise ValueError("num_groups > 1 requires a groups array of per-solution ids")
    if not (telemetry and num_groups > 1):
        groups, num_groups = None, 1

    sh_init, sh_chunk, sh_compact, sh_finalize = _compacting_sharded_fns(
        env,
        policy,
        int(num_episodes),
        max_t,
        hard_cap,
        bool(observation_normalization),
        alive_bonus_schedule,
        decrease_rewards_by,
        action_noise_stdev,
        compute_dtype,
        mesh,
        str(axis_name),
        _params_kind(params_batch),
        bool(stats_sync),
        bool(telemetry),
        health=bool(health),
        num_groups=num_groups,
        nonfinite_quarantine=bool(nonfinite_quarantine),
        nonfinite_penalty=nonfinite_penalty,
    )
    groups_dev = (
        jnp.asarray(groups, dtype=jnp.int32)
        if num_groups > 1
        else jnp.zeros((n,), dtype=jnp.int32)
    )

    if allowed_widths is None:
        if min_width is None:
            # same deeper default floor as the single-device runner
            min_width = max(256, _pow2_at_least(max(1, n_local // 64)))
        widths = []
        w = _pow2_at_least(min_width)
        while w <= n_local // 2:
            widths.append(w)
            w *= 2
        allowed_widths = tuple(sorted(widths))
    else:
        allowed_widths = tuple(sorted(int(w) for w in allowed_widths if w < n_local))

    stats0 = stats
    if num_groups > 1:
        carry, params, lane_ids, scores_buf, eps_buf = sh_init(
            params_batch, jnp.asarray(groups, dtype=jnp.int32), key, stats
        )
    else:
        carry, params, lane_ids, scores_buf, eps_buf = sh_init(params_batch, key, stats)

    if prewarm:
        # compile chunk + finalize at every width and every (from, to)
        # compact pair a runtime jump can hit (mirrors the single-device
        # prewarm), so no trace+compile lands in a timing loop
        c0, _ = sh_chunk(params, carry, int(chunk_size))
        sh_finalize(c0, lane_ids, scores_buf, eps_buf, groups_dev, stats0)
        states = {
            c0.active.shape[0] // n_shards: (c0, params, lane_ids, scores_buf, eps_buf)
        }
        for w in sorted(allowed_widths, reverse=True):
            narrowed = None
            for fw in sorted(states, reverse=True):
                if fw > w:
                    narrowed = sh_compact(*states[fw], w)
            if narrowed is None:
                continue
            c, p, ids, sb, eb = narrowed
            c, _ = sh_chunk(p, c, int(chunk_size))
            sh_finalize(c, ids, sb, eb, groups_dev, stats0)
            states[w] = (c, p, ids, sb, eb)
        jax.block_until_ready(jax.tree_util.tree_leaves(states)[0])

    max_chunks = -(-hard_cap // int(chunk_size)) + 1
    prev_counts = None
    for _ in range(max_chunks):
        carry, counts = sh_chunk(params, carry, int(chunk_size))
        if prev_counts is not None:
            # pipelined one chunk behind, like the single-device runner: the
            # chunk just dispatched keeps all shards busy during this host
            # round-trip. The decision uses the MAX shard count so the new
            # width fits every shard.
            n_active = int(jnp.max(prev_counts))
            if n_active == 0:
                break
            width = carry.active.shape[0] // n_shards
            # jump to the tightest per-shard width that holds every shard's
            # survivors (see the single-device loop for the rationale)
            fits = [w for w in allowed_widths if w < width and n_active <= w]
            if fits:
                carry, params, lane_ids, scores_buf, eps_buf = sh_compact(
                    carry, params, lane_ids, scores_buf, eps_buf, min(fits)
                )
        prev_counts = counts

    mean_scores, merged_stats, total_steps, total_episodes, per_shard, eval_telemetry = (
        sh_finalize(carry, lane_ids, scores_buf, eps_buf, groups_dev, stats0)
    )
    result = RolloutResult(
        scores=mean_scores,
        stats=merged_stats,
        total_steps=total_steps,
        total_episodes=total_episodes,
        telemetry=eval_telemetry if eval_telemetry.size else None,
    )
    if return_per_shard_steps:
        return result, per_shard
    return result
