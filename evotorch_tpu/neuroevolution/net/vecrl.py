"""Vectorized-RL plumbing: batched policies and the jitted rollout engine.

Parity: reference ``net/vecrl.py`` (1912 LoC). What the reference assembles
from dlpack converters (``vecrl.py:53-82``), ``TorchWrapper``
(``vecrl.py:362-613``), a stateful ``Policy`` with auto-vmap forward and
per-env reset (``vecrl.py:1019-1361``), ``reset_tensors``
(``vecrl.py:866-1016``) and eager Python stepping (``vecgymne.py:837-904``)
becomes here ONE jitted program: ``run_vectorized_rollout`` compiles the
entire population x envs x time loop — masked activity, auto-reset,
episode/interaction accounting, obs-norm statistics in the carry — into a
single ``lax.while_loop`` (SURVEY.md §3.4 and §5 long-context note).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..net.functional import FlatParamsPolicy
from ..net.rl import alive_bonus_for_step
from ..net.runningnorm import CollectedStats, stats_normalize, stats_update

__all__ = ["Policy", "reset_tensors", "run_vectorized_rollout", "RolloutResult"]


def reset_tensors(tree: Any, mask: jnp.ndarray) -> Any:
    """Zero the rows of every leaf where ``mask`` is True (the reference's
    nested-state resetter, ``vecrl.py:866-1016``), as a pure function."""

    def zero_rows(leaf):
        m = mask.reshape(mask.shape + (1,) * (leaf.ndim - mask.ndim))
        return jnp.where(m, jnp.zeros_like(leaf), leaf)

    return jax.tree_util.tree_map(zero_rows, tree)


class Policy:
    """Stateful convenience wrapper over a flat-params policy
    (reference ``Policy``, ``vecrl.py:1019-1361``): give it parameters for one
    solution or a batch of solutions, call it on observations, and it manages
    the recurrent state — including per-env ``reset(indices)``."""

    def __init__(self, net, *, key=None):
        from .functional import FlatParamsPolicy
        from .layers import Module

        if isinstance(net, FlatParamsPolicy):
            self._flat = net
        elif isinstance(net, Module):
            self._flat = FlatParamsPolicy(net, key=key)
        else:
            raise TypeError(f"Policy expects a Module or FlatParamsPolicy, got {type(net)}")
        self._params: Optional[jnp.ndarray] = None
        self._state = None
        self._batched = False

    @property
    def parameter_count(self) -> int:
        return self._flat.parameter_count

    def set_parameters(self, parameters, *, reset: bool = True):
        """Accepts ``(L,)`` for one policy or ``(N, L)`` for a batch of
        policies (reference ``vecrl.py:1191``)."""
        parameters = jnp.asarray(parameters)
        self._params = parameters
        self._batched = parameters.ndim == 2
        if reset:
            self._state = None

    def _fresh_state(self, batch_size: Optional[int]):
        proto = self._flat.initial_state()
        if proto is None:
            return None
        if batch_size is None:
            return proto
        return jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(leaf, (batch_size,) + leaf.shape), proto
        )

    def __call__(self, obs) -> jnp.ndarray:
        if self._params is None:
            raise RuntimeError("Call set_parameters(...) before using the Policy")
        obs = jnp.asarray(obs)
        if self._batched:
            n = self._params.shape[0]
            if self._state is None:
                self._state = self._fresh_state(n)
            if self._state is None:
                out, _ = jax.vmap(lambda p, o: self._flat(p, o))(self._params, obs)
                return out
            out, self._state = jax.vmap(lambda p, o, s: self._flat(p, o, s))(
                self._params, obs, self._state
            )
            return out
        if self._state is None:
            self._state = self._fresh_state(None)
        out, self._state = self._flat(self._params, obs, self._state)
        return out

    def reset(self, indices=None):
        """Reset recurrent state — fully, or only the rows given by a boolean
        mask / index array (reference ``vecrl.py:1281``)."""
        if self._state is None or indices is None:
            self._state = None
            return
        mask = jnp.asarray(indices)
        if mask.dtype != jnp.bool_:
            n = self._params.shape[0]
            mask = jnp.zeros(n, dtype=bool).at[mask].set(True)
        self._state = reset_tensors(self._state, mask)

    @property
    def h(self):
        return self._state


class RolloutResult(NamedTuple):
    scores: jnp.ndarray  # (N,) mean episodic return per solution
    stats: CollectedStats  # obs-norm statistics collected during the rollout
    total_steps: jnp.ndarray  # scalar: total env interactions
    total_episodes: jnp.ndarray  # scalar: episodes finished


def _policy_to_action(raw, action_space, noise, clip: bool):
    if action_space.is_discrete:
        return jnp.argmax(raw, axis=-1)
    act = raw if noise is None else raw + noise
    if clip and action_space.lb is not None:
        act = jnp.clip(act, action_space.lb, action_space.ub)
    return act


@partial(
    jax.jit,
    static_argnames=(
        "env",
        "policy",
        "num_episodes",
        "episode_length",
        "observation_normalization",
        "alive_bonus_schedule",
        "decrease_rewards_by",
        "action_noise_stdev",
        "compute_dtype",
        "eval_mode",
    ),
)
def run_vectorized_rollout(
    env,
    policy: FlatParamsPolicy,
    params_batch: jnp.ndarray,
    key,
    stats: CollectedStats,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    observation_normalization: bool = False,
    alive_bonus_schedule: Optional[tuple] = None,
    decrease_rewards_by: Optional[float] = None,
    action_noise_stdev: Optional[float] = None,
    compute_dtype=None,
    eval_mode: str = "episodes",
) -> RolloutResult:
    """Evaluate ``N`` policies on ``N`` environments, fully on-device.

    The logic mirrors ``VecGymNE._evaluate_subbatch``
    (``vecgymne.py:744-916``): one sub-environment per solution, lockstep
    stepping with an activity mask, auto-reset until each env has finished
    ``num_episodes`` episodes, masked running-norm updates, alive-bonus and
    reward adjustments — but compiled into a single ``lax.while_loop``.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) casts the policy parameters and
    its inputs for the forward pass — the MXU fast path; ES is robust to
    low-precision fitness since ranking is scale-free. Env dynamics, rewards
    and statistics stay in f32.

    ``eval_mode`` selects the evaluation contract:

    - ``"episodes"`` (the reference's ``VecGymNE`` semantics): each lane runs
      exactly ``num_episodes`` episodes, then idles (masked) until every lane
      is finished. The ``lax.while_loop`` exits as soon as all lanes are done,
      but in the worst case the whole population waits on its longest
      survivor — finished lanes burn compute producing nothing.
    - ``"budget"``: each lane consumes a fixed interaction budget of
      ``num_episodes * max_episode_steps`` steps, auto-resetting whenever an
      episode ends; the score is the average episodic return over the budget
      (completed episodes plus the fractional trailing episode). Every lane
      is active on every step, so the whole program is one fixed-length
      ``lax.fori_loop`` and 100% of computed env steps are genuine, counted
      interactions — on accelerators this is the throughput-optimal contract
      (it also gives low-variance fitness: constant compute per solution, no
      survivorship skew). This is the flagship benchmark path.
    """
    if eval_mode not in ("episodes", "budget"):
        raise ValueError(f"eval_mode must be 'episodes' or 'budget', got {eval_mode!r}")
    n = params_batch.shape[0]
    if compute_dtype is not None:
        params_batch = params_batch.astype(compute_dtype)
    max_t = env.max_episode_steps if env.max_episode_steps is not None else 1000
    if episode_length is not None:
        max_t = min(max_t, int(episode_length))
    hard_cap = max_t * int(num_episodes) + 1

    # natively-batched envs (population-minor internal layout; see
    # envs/base.py) expose batch_reset/batch_step/batch_where, which the
    # engine prefers over vmap — on TPU this is the difference between 3%
    # and full lane utilization in the loop-carried physics state
    batched_env = getattr(env, "batched_native", False)

    def env_reset(keys):
        if batched_env:
            return env.batch_reset(keys)
        return jax.vmap(env.reset)(keys)

    key, sub = jax.random.split(key)
    reset_keys = jax.random.split(sub, n)
    env_states, obs = env_reset(reset_keys)
    if observation_normalization:
        # the initial reset observations are fed to the policy at t=0, so
        # they belong in the normalization statistics (the reference updates
        # stats on every observation the policy consumes)
        stats = stats_update(stats, obs, mask=jnp.ones(n, dtype=bool))

    policy_proto = policy.initial_state()
    if policy_proto is None:
        policy_states = None
    else:
        state_dtype = compute_dtype  # recurrent state lives in compute dtype
        policy_states = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(
                leaf if state_dtype is None else leaf.astype(state_dtype),
                (n,) + leaf.shape,
            ),
            policy_proto,
        )

    class Carry(NamedTuple):
        env_states: Any
        obs: jnp.ndarray
        policy_states: Any
        scores: jnp.ndarray
        episodes_done: jnp.ndarray
        steps_in_episode: jnp.ndarray
        active: jnp.ndarray
        stats: CollectedStats
        key: Any
        total_steps: jnp.ndarray
        t_global: jnp.ndarray

    carry = Carry(
        env_states=env_states,
        obs=obs,
        policy_states=policy_states,
        scores=jnp.zeros(n),
        episodes_done=jnp.zeros(n, dtype=jnp.int32),
        steps_in_episode=jnp.zeros(n, dtype=jnp.int32),
        active=jnp.ones(n, dtype=bool),
        stats=stats,
        key=key,
        total_steps=jnp.zeros((), dtype=jnp.int32),
        t_global=jnp.zeros((), dtype=jnp.int32),
    )

    budget_mode = eval_mode == "budget"

    def cond(c: Carry):
        return jnp.any(c.active) & (c.t_global < hard_cap)

    def body(c: Carry) -> Carry:
        key, noise_key, reset_key = jax.random.split(c.key, 3)

        policy_in = (
            stats_normalize(c.stats, c.obs) if observation_normalization else c.obs
        )
        if compute_dtype is not None:
            policy_in = policy_in.astype(compute_dtype)
        if c.policy_states is None:
            raw, new_policy_states = jax.vmap(lambda p, o: policy(p, o))(
                params_batch, policy_in
            )
        else:
            raw, new_policy_states = jax.vmap(policy)(params_batch, policy_in, c.policy_states)
        if compute_dtype is not None:
            raw = raw.astype(jnp.float32)

        noise = None
        if action_noise_stdev is not None:
            noise = action_noise_stdev * jax.random.normal(noise_key, raw.shape)
        actions = _policy_to_action(raw, env.action_space, noise, clip=True)

        if batched_env:
            new_env_states, new_obs, rewards, dones = env.batch_step(
                c.env_states, actions
            )
        else:
            new_env_states, new_obs, rewards, dones = jax.vmap(env.step)(
                c.env_states, actions
            )

        steps_in_episode = c.steps_in_episode + 1
        # guaranteed truncation at max_t (gym TimeLimit semantics): even an
        # env that never emits done internally ends its episode here, so
        # per-episode score averaging stays well-defined
        dones = dones | (steps_in_episode >= max_t)

        if decrease_rewards_by is not None:
            rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            rewards = rewards + alive_bonus_for_step(
                steps_in_episode, alive_bonus_schedule
            ) * (~dones)

        active_f = c.active
        scores = c.scores + jnp.where(active_f, rewards, 0.0)

        # auto-reset the envs that finished an episode (only matters while active)
        finished = dones & active_f
        episodes_done = c.episodes_done + finished.astype(jnp.int32)
        reset_keys = jax.random.split(reset_key, n)
        fresh_states, fresh_obs = env_reset(reset_keys)

        def select(new, fresh):
            m = finished.reshape(finished.shape + (1,) * (new.ndim - 1))
            return jnp.where(m, fresh, new)

        if batched_env:
            env_states_next = env.batch_where(finished, fresh_states, new_env_states)
        else:
            env_states_next = jax.tree_util.tree_map(
                select, new_env_states, fresh_states
            )
        obs_next = select(new_obs, fresh_obs)
        steps_in_episode = jnp.where(finished, 0, steps_in_episode)
        if new_policy_states is not None:
            new_policy_states = reset_tensors(new_policy_states, finished)

        if budget_mode:
            active = active_f  # every lane runs its full budget
            total_steps = c.total_steps + n
        else:
            active = episodes_done < num_episodes
            total_steps = c.total_steps + jnp.sum(active_f.astype(jnp.int32))
        # normalization statistics come from the observations the policy will
        # actually consume next step: post-reset-selection obs, masked by the
        # envs still running (ADVICE r1: not the pre-reset terminal obs)
        new_stats = (
            stats_update(c.stats, obs_next, mask=active)
            if observation_normalization
            else c.stats
        )

        return Carry(
            env_states=env_states_next,
            obs=obs_next,
            policy_states=new_policy_states,
            scores=scores,
            episodes_done=episodes_done,
            steps_in_episode=steps_in_episode,
            active=active,
            stats=new_stats,
            key=key,
            total_steps=total_steps,
            t_global=c.t_global + 1,
        )

    if budget_mode:
        budget = max_t * int(num_episodes)
        final = jax.lax.fori_loop(0, budget, lambda _, c: body(c), carry)
        # average episodic return over the budget: completed episodes plus
        # the fractional trailing one (exactly the episodic mean whenever the
        # budget lands on an episode boundary)
        episodes_frac = (
            final.episodes_done + final.steps_in_episode.astype(jnp.float32) / max_t
        )
        mean_scores = final.scores / jnp.maximum(episodes_frac, 1.0 / max_t)
    else:
        final = jax.lax.while_loop(cond, body, carry)
        mean_scores = final.scores / jnp.maximum(final.episodes_done, 1)
    return RolloutResult(
        scores=mean_scores,
        stats=final.stats,
        total_steps=final.total_steps,
        total_episodes=jnp.sum(final.episodes_done),
    )
