"""Low-rank-perturbation policy evaluation: the MXU path for wide policies.

The defining cost of ES evaluation is that every population lane carries its
OWN parameter vector, so the policy forward is a batch of N tiny per-lane
matvecs — the MXU cannot amortize weight loads across lanes, and throughput
collapses as the policy grows (measured in BENCH_NOTES.md: 8x params ->
3.4x slower). The classic low-rank answer (the LM-MA-ES / random-subspace ES
family) restructures the perturbation instead of the hardware:

    theta_i = c + B z_i          B: (L, k) shared basis,  z_i: (k,) per lane

Then every Linear layer's effective weight is ``W_c + sum_m z_im D_m`` with
shared direction matrices ``D_m``, and the whole population's forward is

    Y_aug = X @ [W_c; D_1; ...; D_k]^T        one LARGE dense matmul (MXU)
    y_i   = Y_aug[i, :o] + sum_m z_im Y_aug[i, o*m:o*(m+1)]   (VPU epilogue)

(k+1) dense shared-weight matmuls instead of N tiny per-lane matvecs — and
the (N, L) population matrix is never materialized at all (for a 256x256
policy at popsize 10k that matrix alone is 3.9 GB).

Recurrent cells get the same treatment: an RNN/LSTM step is two matmuls
(input-to-hidden and hidden-to-hidden), each of which augments exactly like
a Linear — so recurrent policies run the MXU path at full speed too, with
the per-lane hidden state threaded through unchanged (VERDICT r3 #4).

``LowRankParamsBatch`` is the population representation (defined in
``tools/lowrank.py`` so core/distributions can speak it too); the rollout
engine (``vecrl.py``) accepts it anywhere it accepts a dense ``(N, L)``
matrix. Modules without a structured path (custom/unstructured) fall back to
materializing the dense population — correct everywhere, fast where it
matters, and LOUD (a trace-time warning) when the fallback fires.

No reference counterpart: the reference evaluates dense populations only
(``distributions.py:616-773`` samples full vectors); this is a TPU-first
framework feature (VERDICT r2 #2).
"""

from __future__ import annotations

import warnings
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from jax.flatten_util import ravel_pytree

from ...tools.lowrank import LowRankParamsBatch, TrunkDeltaParamsBatch
from .layers import LSTM, RNN, Bias, Linear, Module, Sequential

__all__ = [
    "LowRankParamsBatch",
    "TrunkDeltaParamsBatch",
    "lowrank_supported",
    "prepare_lowrank",
    "lowrank_forward",
    "trunk_delta_supported",
    "sample_trunk_delta_factors",
    "prepare_trunk_delta",
    "trunk_delta_forward",
]


def lowrank_supported(module: Module) -> bool:
    """True when the module stack has a structured low-rank forward:
    Sequential pipelines of Linear / Bias / RNN / LSTM / parameterless
    layers."""
    if isinstance(module, Sequential):
        return all(lowrank_supported(m) for m in module.modules)
    if isinstance(module, (Linear, Bias, RNN, LSTM)):
        return True
    # parameterless layers (activations, Clip, Slice, ...) pass through
    return _is_parameterless(module)


def _is_parameterless(module: Module) -> bool:
    try:
        params = module.init(jax.random.key(0))
    except Exception:  # graftlint: allow(swallow): probe: a module that cannot init is simply not parameterless
        return False
    return len(jax.tree_util.tree_leaves(params)) == 0 and not module.is_stateful


class _Prepared(NamedTuple):
    """Per-layer center/basis parameter trees, precomputed once per rollout
    (loop-invariant): ``basis_tree`` leaves carry a trailing ``k`` axis."""

    center_tree: Any
    basis_tree: Any
    coeffs: jnp.ndarray


def prepare_lowrank(policy, params: LowRankParamsBatch) -> _Prepared:
    """Split the flat center/basis into per-layer trees. Cheap (slices and
    reshapes); call once per rollout, outside the stepping loop."""
    center_tree = policy.unravel(params.center)
    basis_tree = jax.vmap(policy.unravel, in_axes=1, out_axes=-1)(params.basis)
    return _Prepared(center_tree, basis_tree, params.coeffs)


def _augmented_matmul(W_c, W_b, z, x):
    """``x`` (B, in) times the per-lane effective weight
    ``W_i = W_c + sum_m z_im W_b[..., m]``, computed as ONE augmented dense
    matmul: the center weight and the k direction matrices stacked row-wise,
    so the MXU sees a single (B, in) @ (in, (k+1)*out) contraction; the
    per-lane combination is a cheap VPU epilogue. Returns (B, out)."""
    out_f, in_f = W_c.shape
    k = W_b.shape[-1]
    # (k, out, in) -> (k*out, in); stack center on top -> ((k+1)*out, in)
    W_dirs = jnp.moveaxis(W_b, -1, 0).reshape(k * out_f, in_f)
    W_aug = jnp.concatenate([W_c, W_dirs], axis=0)
    y_aug = x @ W_aug.T  # (B, (k+1)*out)
    y = y_aug[:, :out_f]
    corr = y_aug[:, out_f:].reshape(-1, k, out_f)
    return y + jnp.einsum("bko,bk->bo", corr, z)


def _lane_bias(cp_bias, bp_bias, z):
    """Per-lane effective bias ``b_c + sum_m z_im b_b[:, m]`` -> (B, out)."""
    return cp_bias + z @ bp_bias.T


def _linear_lowrank(layer: Linear, cp, bp, z, x):
    y = _augmented_matmul(cp["weight"], bp["weight"], z, x)
    if layer.bias:
        y = y + _lane_bias(cp["bias"], bp["bias"], z)
    return y


def _bias_lowrank(layer: Bias, cp, bp, z, x):
    return x + _lane_bias(cp["bias"], bp["bias"], z)


def _rnn_lowrank(layer: RNN, cp, bp, z, x, state):
    """Elman cell (layers.py:309): both matmuls augment like Linear; the
    per-lane hidden state is just another (B, hidden) activation."""
    if state is None:
        state = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    pre = (
        _augmented_matmul(cp["W_ih"], bp["W_ih"], z, x)
        + _augmented_matmul(cp["W_hh"], bp["W_hh"], z, state)
        + _lane_bias(cp["b_ih"], bp["b_ih"], z)
        + _lane_bias(cp["b_hh"], bp["b_hh"], z)
    )
    h = jnp.tanh(pre) if layer.nonlinearity == "tanh" else jax.nn.relu(pre)
    return h, h


def _lstm_lowrank(layer: LSTM, cp, bp, z, x, state):
    """LSTM cell (layers.py:350): the (4h, in) and (4h, h) gate matmuls
    augment like Linear; gate nonlinearities are the same VPU epilogue as
    the dense path."""
    if state is None:
        h = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
        c = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    else:
        h, c = state
    gates = (
        _augmented_matmul(cp["W_ih"], bp["W_ih"], z, x)
        + _augmented_matmul(cp["W_hh"], bp["W_hh"], z, h)
        + _lane_bias(cp["b_ih"], bp["b_ih"], z)
        + _lane_bias(cp["b_hh"], bp["b_hh"], z)
    )
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return h, (h, c)


def _apply_lowrank(module: Module, cp, bp, z, x, state):
    """Structured whole-population forward, threading per-lane recurrent
    state exactly like ``Sequential.apply`` threads it in the dense path.
    Returns ``(y, new_state)``."""
    if isinstance(module, Sequential):
        if state is None:
            state = tuple(None for _ in module.modules)
        new_states = []
        for m, c, b, s in zip(module.modules, cp, bp, state):
            x, ns = _apply_lowrank(m, c, b, z, x, s)
            new_states.append(ns)
        out_state = tuple(new_states)
        if all(s is None for s in out_state):
            out_state = None
        return x, out_state
    if isinstance(module, Linear):
        return _linear_lowrank(module, cp, bp, z, x), state
    if isinstance(module, Bias):
        return _bias_lowrank(module, cp, bp, z, x), state
    if isinstance(module, RNN):
        return _rnn_lowrank(module, cp, bp, z, x, state)
    if isinstance(module, LSTM):
        return _lstm_lowrank(module, cp, bp, z, x, state)
    # parameterless layer: batched apply is the plain apply
    return module.apply(cp, x, state)


def lowrank_forward(
    policy, params: LowRankParamsBatch, prepared: Optional[_Prepared], obs, states
) -> Tuple[jnp.ndarray, Any]:
    """Whole-population forward: ``obs`` (B, obs_dim) -> (B, act_dim).
    ``prepared`` may be None (computed on the fly — only sensible outside
    hot loops). ``states`` is the batched per-lane state pytree (leading
    axis B) for recurrent stacks, or None."""
    module = policy.module
    if lowrank_supported(module):
        if prepared is None:
            prepared = prepare_lowrank(policy, params)
        return _apply_lowrank(
            module, prepared.center_tree, prepared.basis_tree, prepared.coeffs, obs, states
        )
    # fallback: materialize the dense population and vmap (correct for any
    # module). Loud, not silent: the caller chose the low-rank representation
    # to AVOID this matrix (VERDICT r3 #3) — the warning fires at trace time,
    # once per compile
    warnings.warn(
        f"low-rank forward fell back to materializing the dense "
        f"({params.popsize}, {params.center.shape[-1]}) population: "
        f"{type(module).__name__} has no structured low-rank path "
        "(supported: Sequential stacks of Linear/Bias/RNN/LSTM/"
        "parameterless layers)",
        stacklevel=2,
    )
    dense = params.materialize()
    if states is None:
        return jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    return jax.vmap(policy)(dense, obs, states)


# ---------------------------------------------------------------------------
# the shared-trunk + per-lane low-rank-delta form (docs/policies.md)
#
# The augmented matmul above still pays (k+1) trunk-sized matmuls per layer.
# Structuring each basis column as a RANK-1 block per 2-D weight —
# ``D_m = b_m a_m^T`` — collapses the per-layer forward to
#
#     y = x @ W_c^T + ((x @ A) * z) @ B^T        A: (in, k), B: (out, k)
#
# ONE trunk GEMM over the whole population batch (the weight is loaded once
# for every lane — real MXU arithmetic intensity) plus two thin shared
# GEMMs; per-lane cost drops from (k+1)·in·out to in·out + k·(in+out).
# ---------------------------------------------------------------------------


class _Factor(NamedTuple):
    """Per-parameter-leaf delta factors. For a 2-D weight leaf ``a`` is
    (in, k) and ``b`` is (out, k) with sigma's block scale folded into
    ``b``; for a 1-D leaf ``a`` is an empty (0, k) placeholder and ``b``
    holds the sigma-folded dense direction matrix (size, k) — exactly a
    low-rank bias basis."""

    a: jnp.ndarray
    b: jnp.ndarray


def trunk_delta_supported(module: Module) -> bool:
    """The trunk-delta path covers the same structured stacks as the
    augmented-matmul path: Sequential pipelines of Linear / Bias / RNN /
    LSTM / parameterless layers."""
    return lowrank_supported(module)


def sample_trunk_delta_factors(key, policy, sigma: jnp.ndarray, rank: int):
    """Draw one generation's delta factors and materialize their effective
    basis.

    Returns ``(factors, basis)``: ``factors`` is a pytree mirroring the
    policy's parameter tree with a :class:`_Factor` at every leaf, and
    ``basis`` is the flat (L, k) effective basis whose column ``m`` is the
    concatenation of ``vec(b_m a_m^T)`` (2-D leaves) and the 1-D direction
    columns — the SAME ``theta_i = center + basis @ z_i`` algebra as
    :class:`LowRankParamsBatch`, so gradients and the exhaustion guardrail
    apply unchanged.

    Sigma folding: 1-D leaves fold the per-parameter sigma exactly; 2-D
    leaves fold the block's RMS sigma (a per-parameter scale would break
    the rank-1 structure the fast forward depends on). Per-entry delta
    variance is ``sigma^2`` (blockwise for matrices), matching the default
    low-rank basis scaling at equal rank.
    """
    sigma_tree = policy.unravel(sigma)
    leaves, treedef = jax.tree_util.tree_flatten(sigma_tree)
    factor_nodes = []
    basis_leaves = []
    inv_sqrt_k = 1.0 / jnp.sqrt(jnp.asarray(float(rank), sigma.dtype))
    for i, sigma_leaf in enumerate(leaves):
        k_a = jax.random.fold_in(key, 2 * i)
        k_b = jax.random.fold_in(key, 2 * i + 1)
        if sigma_leaf.ndim == 2:
            out_f, in_f = sigma_leaf.shape
            a = jax.random.normal(k_a, (in_f, rank), sigma_leaf.dtype)
            block_rms = jnp.sqrt(jnp.mean(sigma_leaf * sigma_leaf))
            b = jax.random.normal(k_b, (out_f, rank), sigma_leaf.dtype) * (
                block_rms * inv_sqrt_k
            )
            factor_nodes.append(_Factor(a=a, b=b))
            basis_leaves.append(jnp.einsum("om,im->oim", b, a))
        elif sigma_leaf.ndim == 1:
            dirs = (
                jax.random.normal(k_b, sigma_leaf.shape + (rank,), sigma_leaf.dtype)
                * inv_sqrt_k
                * sigma_leaf[:, None]
            )
            factor_nodes.append(
                _Factor(a=jnp.zeros((0, rank), sigma_leaf.dtype), b=dirs)
            )
            basis_leaves.append(dirs)
        else:
            raise ValueError(
                "trunk-delta factors need 1-D or 2-D parameter leaves; got "
                f"shape {sigma_leaf.shape} (leaf {i})"
            )
    factors = jax.tree_util.tree_unflatten(treedef, factor_nodes)
    basis_tree = jax.tree_util.tree_unflatten(treedef, basis_leaves)
    basis = jax.vmap(lambda t: ravel_pytree(t)[0], in_axes=-1, out_axes=-1)(
        basis_tree
    )
    return factors, basis


class _TrunkPrepared(NamedTuple):
    """Loop-invariant forward context of a trunk-delta rollout: the
    unraveled trunk tree, the factor tree, the per-lane coefficients, and
    the static lane-block size (0 = single block; the autotuner's ``policy``
    knob group searches it)."""

    center_tree: Any
    factors: Any
    coeffs: jnp.ndarray
    trunk_block: int = 0


def prepare_trunk_delta(
    policy, params: TrunkDeltaParamsBatch, *, trunk_block: int = 0
) -> _TrunkPrepared:
    """Split the flat trunk into its per-layer tree. Cheap; call once per
    rollout, outside the stepping loop."""
    return _TrunkPrepared(
        policy.unravel(params.center), params.factors, params.coeffs, int(trunk_block)
    )


def _trunk_matmul(W_c, fac: _Factor, z, x):
    """``x`` (B, in) times the per-lane effective weight
    ``W_i = W_c + sum_m z_im b_m a_m^T``: one shared trunk GEMM plus the
    thin delta GEMMs. Returns (B, out)."""
    return x @ W_c.T + ((x @ fac.a) * z) @ fac.b.T


def _linear_trunk(layer: Linear, cp, fx, z, x):
    y = _trunk_matmul(cp["weight"], fx["weight"], z, x)
    if layer.bias:
        y = y + _lane_bias(cp["bias"], fx["bias"].b, z)
    return y


def _bias_trunk(layer: Bias, cp, fx, z, x):
    return x + _lane_bias(cp["bias"], fx["bias"].b, z)


def _rnn_trunk(layer: RNN, cp, fx, z, x, state):
    if state is None:
        state = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    pre = (
        _trunk_matmul(cp["W_ih"], fx["W_ih"], z, x)
        + _trunk_matmul(cp["W_hh"], fx["W_hh"], z, state)
        + _lane_bias(cp["b_ih"], fx["b_ih"].b, z)
        + _lane_bias(cp["b_hh"], fx["b_hh"].b, z)
    )
    h = jnp.tanh(pre) if layer.nonlinearity == "tanh" else jax.nn.relu(pre)
    return h, h


def _lstm_trunk(layer: LSTM, cp, fx, z, x, state):
    if state is None:
        h = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
        c = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    else:
        h, c = state
    gates = (
        _trunk_matmul(cp["W_ih"], fx["W_ih"], z, x)
        + _trunk_matmul(cp["W_hh"], fx["W_hh"], z, h)
        + _lane_bias(cp["b_ih"], fx["b_ih"].b, z)
        + _lane_bias(cp["b_hh"], fx["b_hh"].b, z)
    )
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return h, (h, c)


def _apply_trunk_delta(module: Module, cp, fx, z, x, state):
    """Whole-population trunk-delta forward, threading per-lane recurrent
    state exactly like ``_apply_lowrank``. Returns ``(y, new_state)``."""
    if isinstance(module, Sequential):
        if state is None:
            state = tuple(None for _ in module.modules)
        new_states = []
        for m, c, f, s in zip(module.modules, cp, fx, state):
            x, ns = _apply_trunk_delta(m, c, f, z, x, s)
            new_states.append(ns)
        out_state = tuple(new_states)
        if all(s is None for s in out_state):
            out_state = None
        return x, out_state
    if isinstance(module, Linear):
        return _linear_trunk(module, cp, fx, z, x), state
    if isinstance(module, Bias):
        return _bias_trunk(module, cp, fx, z, x), state
    if isinstance(module, RNN):
        return _rnn_trunk(module, cp, fx, z, x, state)
    if isinstance(module, LSTM):
        return _lstm_trunk(module, cp, fx, z, x, state)
    # parameterless layer: batched apply is the plain apply
    return module.apply(cp, x, state)


def _apply_trunk_delta_blocked(module, cp, fx, z, obs, states, block: int):
    """The same forward with the LANE axis chunked into static blocks of
    ``block`` via ``lax.map`` — bounds the per-GEMM activation working set
    (the autotuner's trunk-blocking knob). Per-lane results are independent,
    so blocking changes scheduling, not values."""
    n = obs.shape[0]
    nb = n // block

    def _split(t):
        return t.reshape((nb, block) + t.shape[1:])

    xs = (
        _split(obs),
        _split(z),
        None
        if states is None
        else jax.tree_util.tree_map(_split, states),
    )

    def _body(args):
        o, zz, ss = args
        return _apply_trunk_delta(module, cp, fx, zz, o, ss)

    y_b, ns_b = jax.lax.map(_body, xs)
    y = y_b.reshape((n,) + y_b.shape[2:])
    if ns_b is not None:
        ns_b = jax.tree_util.tree_map(
            lambda t: t.reshape((n,) + t.shape[2:]), ns_b
        )
    return y, ns_b


def trunk_delta_forward(
    policy,
    params: TrunkDeltaParamsBatch,
    prepared: Optional[_TrunkPrepared],
    obs,
    states,
) -> Tuple[jnp.ndarray, Any]:
    """Whole-population shared-trunk forward: ``obs`` (B, obs_dim) ->
    (B, act_dim). Mirrors :func:`lowrank_forward`'s contract, including the
    LOUD materializing fallback for unstructured modules."""
    module = policy.module
    if trunk_delta_supported(module):
        if prepared is None:
            prepared = prepare_trunk_delta(policy, params)
        z = prepared.coeffs
        block = int(prepared.trunk_block)
        n = obs.shape[0]
        if block > 0 and n > block and n % block == 0:
            return _apply_trunk_delta_blocked(
                module, prepared.center_tree, prepared.factors, z, obs, states, block
            )
        return _apply_trunk_delta(
            module, prepared.center_tree, prepared.factors, z, obs, states
        )
    warnings.warn(
        f"trunk-delta forward fell back to materializing the dense "
        f"({params.popsize}, {params.center.shape[-1]}) population: "
        f"{type(module).__name__} has no structured trunk-delta path "
        "(supported: Sequential stacks of Linear/Bias/RNN/LSTM/"
        "parameterless layers)",
        stacklevel=2,
    )
    dense = params.materialize()
    if states is None:
        return jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    return jax.vmap(policy)(dense, obs, states)
