"""Low-rank-perturbation policy evaluation: the MXU path for wide policies.

The defining cost of ES evaluation is that every population lane carries its
OWN parameter vector, so the policy forward is a batch of N tiny per-lane
matvecs — the MXU cannot amortize weight loads across lanes, and throughput
collapses as the policy grows (measured in BENCH_NOTES.md: 8x params ->
3.4x slower). The classic low-rank answer (the LM-MA-ES / random-subspace ES
family) restructures the perturbation instead of the hardware:

    theta_i = c + B z_i          B: (L, k) shared basis,  z_i: (k,) per lane

Then every Linear layer's effective weight is ``W_c + sum_m z_im D_m`` with
shared direction matrices ``D_m``, and the whole population's forward is

    Y_aug = X @ [W_c; D_1; ...; D_k]^T        one LARGE dense matmul (MXU)
    y_i   = Y_aug[i, :o] + sum_m z_im Y_aug[i, o*m:o*(m+1)]   (VPU epilogue)

(k+1) dense shared-weight matmuls instead of N tiny per-lane matvecs — and
the (N, L) population matrix is never materialized at all (for a 256x256
policy at popsize 10k that matrix alone is 3.9 GB).

Recurrent cells get the same treatment: an RNN/LSTM step is two matmuls
(input-to-hidden and hidden-to-hidden), each of which augments exactly like
a Linear — so recurrent policies run the MXU path at full speed too, with
the per-lane hidden state threaded through unchanged (VERDICT r3 #4).

``LowRankParamsBatch`` is the population representation (defined in
``tools/lowrank.py`` so core/distributions can speak it too); the rollout
engine (``vecrl.py``) accepts it anywhere it accepts a dense ``(N, L)``
matrix. Modules without a structured path (custom/unstructured) fall back to
materializing the dense population — correct everywhere, fast where it
matters, and LOUD (a trace-time warning) when the fallback fires.

No reference counterpart: the reference evaluates dense populations only
(``distributions.py:616-773`` samples full vectors); this is a TPU-first
framework feature (VERDICT r2 #2).
"""

from __future__ import annotations

import warnings
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ...tools.lowrank import LowRankParamsBatch
from .layers import LSTM, RNN, Bias, Linear, Module, Sequential

__all__ = ["LowRankParamsBatch", "lowrank_supported", "prepare_lowrank", "lowrank_forward"]


def lowrank_supported(module: Module) -> bool:
    """True when the module stack has a structured low-rank forward:
    Sequential pipelines of Linear / Bias / RNN / LSTM / parameterless
    layers."""
    if isinstance(module, Sequential):
        return all(lowrank_supported(m) for m in module.modules)
    if isinstance(module, (Linear, Bias, RNN, LSTM)):
        return True
    # parameterless layers (activations, Clip, Slice, ...) pass through
    return _is_parameterless(module)


def _is_parameterless(module: Module) -> bool:
    try:
        params = module.init(jax.random.key(0))
    except Exception:
        return False
    return len(jax.tree_util.tree_leaves(params)) == 0 and not module.is_stateful


class _Prepared(NamedTuple):
    """Per-layer center/basis parameter trees, precomputed once per rollout
    (loop-invariant): ``basis_tree`` leaves carry a trailing ``k`` axis."""

    center_tree: Any
    basis_tree: Any
    coeffs: jnp.ndarray


def prepare_lowrank(policy, params: LowRankParamsBatch) -> _Prepared:
    """Split the flat center/basis into per-layer trees. Cheap (slices and
    reshapes); call once per rollout, outside the stepping loop."""
    center_tree = policy.unravel(params.center)
    basis_tree = jax.vmap(policy.unravel, in_axes=1, out_axes=-1)(params.basis)
    return _Prepared(center_tree, basis_tree, params.coeffs)


def _augmented_matmul(W_c, W_b, z, x):
    """``x`` (B, in) times the per-lane effective weight
    ``W_i = W_c + sum_m z_im W_b[..., m]``, computed as ONE augmented dense
    matmul: the center weight and the k direction matrices stacked row-wise,
    so the MXU sees a single (B, in) @ (in, (k+1)*out) contraction; the
    per-lane combination is a cheap VPU epilogue. Returns (B, out)."""
    out_f, in_f = W_c.shape
    k = W_b.shape[-1]
    # (k, out, in) -> (k*out, in); stack center on top -> ((k+1)*out, in)
    W_dirs = jnp.moveaxis(W_b, -1, 0).reshape(k * out_f, in_f)
    W_aug = jnp.concatenate([W_c, W_dirs], axis=0)
    y_aug = x @ W_aug.T  # (B, (k+1)*out)
    y = y_aug[:, :out_f]
    corr = y_aug[:, out_f:].reshape(-1, k, out_f)
    return y + jnp.einsum("bko,bk->bo", corr, z)


def _lane_bias(cp_bias, bp_bias, z):
    """Per-lane effective bias ``b_c + sum_m z_im b_b[:, m]`` -> (B, out)."""
    return cp_bias + z @ bp_bias.T


def _linear_lowrank(layer: Linear, cp, bp, z, x):
    y = _augmented_matmul(cp["weight"], bp["weight"], z, x)
    if layer.bias:
        y = y + _lane_bias(cp["bias"], bp["bias"], z)
    return y


def _bias_lowrank(layer: Bias, cp, bp, z, x):
    return x + _lane_bias(cp["bias"], bp["bias"], z)


def _rnn_lowrank(layer: RNN, cp, bp, z, x, state):
    """Elman cell (layers.py:309): both matmuls augment like Linear; the
    per-lane hidden state is just another (B, hidden) activation."""
    if state is None:
        state = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    pre = (
        _augmented_matmul(cp["W_ih"], bp["W_ih"], z, x)
        + _augmented_matmul(cp["W_hh"], bp["W_hh"], z, state)
        + _lane_bias(cp["b_ih"], bp["b_ih"], z)
        + _lane_bias(cp["b_hh"], bp["b_hh"], z)
    )
    h = jnp.tanh(pre) if layer.nonlinearity == "tanh" else jax.nn.relu(pre)
    return h, h


def _lstm_lowrank(layer: LSTM, cp, bp, z, x, state):
    """LSTM cell (layers.py:350): the (4h, in) and (4h, h) gate matmuls
    augment like Linear; gate nonlinearities are the same VPU epilogue as
    the dense path."""
    if state is None:
        h = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
        c = jnp.zeros(x.shape[:-1] + (layer.hidden_size,), dtype=x.dtype)
    else:
        h, c = state
    gates = (
        _augmented_matmul(cp["W_ih"], bp["W_ih"], z, x)
        + _augmented_matmul(cp["W_hh"], bp["W_hh"], z, h)
        + _lane_bias(cp["b_ih"], bp["b_ih"], z)
        + _lane_bias(cp["b_hh"], bp["b_hh"], z)
    )
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c + i * g
    h = o * jnp.tanh(c)
    return h, (h, c)


def _apply_lowrank(module: Module, cp, bp, z, x, state):
    """Structured whole-population forward, threading per-lane recurrent
    state exactly like ``Sequential.apply`` threads it in the dense path.
    Returns ``(y, new_state)``."""
    if isinstance(module, Sequential):
        if state is None:
            state = tuple(None for _ in module.modules)
        new_states = []
        for m, c, b, s in zip(module.modules, cp, bp, state):
            x, ns = _apply_lowrank(m, c, b, z, x, s)
            new_states.append(ns)
        out_state = tuple(new_states)
        if all(s is None for s in out_state):
            out_state = None
        return x, out_state
    if isinstance(module, Linear):
        return _linear_lowrank(module, cp, bp, z, x), state
    if isinstance(module, Bias):
        return _bias_lowrank(module, cp, bp, z, x), state
    if isinstance(module, RNN):
        return _rnn_lowrank(module, cp, bp, z, x, state)
    if isinstance(module, LSTM):
        return _lstm_lowrank(module, cp, bp, z, x, state)
    # parameterless layer: batched apply is the plain apply
    return module.apply(cp, x, state)


def lowrank_forward(
    policy, params: LowRankParamsBatch, prepared: Optional[_Prepared], obs, states
) -> Tuple[jnp.ndarray, Any]:
    """Whole-population forward: ``obs`` (B, obs_dim) -> (B, act_dim).
    ``prepared`` may be None (computed on the fly — only sensible outside
    hot loops). ``states`` is the batched per-lane state pytree (leading
    axis B) for recurrent stacks, or None."""
    module = policy.module
    if lowrank_supported(module):
        if prepared is None:
            prepared = prepare_lowrank(policy, params)
        return _apply_lowrank(
            module, prepared.center_tree, prepared.basis_tree, prepared.coeffs, obs, states
        )
    # fallback: materialize the dense population and vmap (correct for any
    # module). Loud, not silent: the caller chose the low-rank representation
    # to AVOID this matrix (VERDICT r3 #3) — the warning fires at trace time,
    # once per compile
    warnings.warn(
        f"low-rank forward fell back to materializing the dense "
        f"({params.popsize}, {params.center.shape[-1]}) population: "
        f"{type(module).__name__} has no structured low-rank path "
        "(supported: Sequential stacks of Linear/Bias/RNN/LSTM/"
        "parameterless layers)",
        stacklevel=2,
    )
    dense = params.materialize()
    if states is None:
        return jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    return jax.vmap(policy)(dense, obs, states)
