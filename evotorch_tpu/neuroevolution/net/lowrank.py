"""Low-rank-perturbation policy evaluation: the MXU path for wide policies.

The defining cost of ES evaluation is that every population lane carries its
OWN parameter vector, so the policy forward is a batch of N tiny per-lane
matvecs — the MXU cannot amortize weight loads across lanes, and throughput
collapses as the policy grows (measured in BENCH_NOTES.md: 8x params ->
3.4x slower). The classic low-rank answer (the LM-MA-ES / random-subspace ES
family) restructures the perturbation instead of the hardware:

    theta_i = c + B z_i          B: (L, k) shared basis,  z_i: (k,) per lane

Then every Linear layer's effective weight is ``W_c + sum_m z_im D_m`` with
shared direction matrices ``D_m``, and the whole population's forward is

    Y_aug = X @ [W_c; D_1; ...; D_k]^T        one LARGE dense matmul (MXU)
    y_i   = Y_aug[i, :o] + sum_m z_im Y_aug[i, o*m:o*(m+1)]   (VPU epilogue)

(k+1) dense shared-weight matmuls instead of N tiny per-lane matvecs — and
the (N, L) population matrix is never materialized at all (for a 256x256
policy at popsize 10k that matrix alone is 3.9 GB).

``LowRankParamsBatch`` is the population representation; the rollout engine
(``vecrl.py``) accepts it anywhere it accepts a dense ``(N, L)`` matrix.
Modules without a structured path (RNN/LSTM, custom) fall back to
materializing the dense population — correct everywhere, fast where it
matters.

No reference counterpart: the reference evaluates dense populations only
(``distributions.py:616-773`` samples full vectors); this is a TPU-first
framework feature (VERDICT r2 #2).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .layers import Bias, Linear, Module, Sequential

__all__ = ["LowRankParamsBatch", "lowrank_supported", "prepare_lowrank", "lowrank_forward"]


class LowRankParamsBatch(NamedTuple):
    """A population expressed as ``theta_i = center + basis @ coeffs[i]``.

    ``basis`` is the *effective* basis: per-generation direction matrix with
    any per-parameter scale (e.g. PGPE's sigma) already folded in.
    """

    center: jnp.ndarray  # (L,)
    basis: jnp.ndarray  # (L, k)
    coeffs: jnp.ndarray  # (N, k)

    @property
    def popsize(self) -> int:
        return self.coeffs.shape[0]

    @property
    def rank(self) -> int:
        return self.basis.shape[-1]

    def take(self, idx) -> "LowRankParamsBatch":
        """Gather lanes (the rollout engine's compaction); center/basis are
        shared across lanes and ride along untouched."""
        return LowRankParamsBatch(self.center, self.basis, self.coeffs[idx])

    def materialize(self) -> jnp.ndarray:
        """The dense ``(N, L)`` population (the correctness fallback — avoid
        on the hot path; this is exactly the matrix the representation
        exists to not build)."""
        return self.center + self.coeffs @ self.basis.T


def lowrank_supported(module: Module) -> bool:
    """True when the module stack has a structured low-rank forward (today:
    Sequential pipelines of Linear / Bias / parameterless layers)."""
    if isinstance(module, Sequential):
        return all(lowrank_supported(m) for m in module.modules)
    if isinstance(module, (Linear, Bias)):
        return True
    # parameterless layers (activations, Clip, Slice, ...) pass through
    return _is_parameterless(module)


def _is_parameterless(module: Module) -> bool:
    try:
        params = module.init(jax.random.key(0))
    except Exception:
        return False
    return len(jax.tree_util.tree_leaves(params)) == 0 and not module.is_stateful


class _Prepared(NamedTuple):
    """Per-layer center/basis parameter trees, precomputed once per rollout
    (loop-invariant): ``basis_tree`` leaves carry a trailing ``k`` axis."""

    center_tree: Any
    basis_tree: Any
    coeffs: jnp.ndarray


def prepare_lowrank(policy, params: LowRankParamsBatch) -> _Prepared:
    """Split the flat center/basis into per-layer trees. Cheap (slices and
    reshapes); call once per rollout, outside the stepping loop."""
    center_tree = policy.unravel(params.center)
    basis_tree = jax.vmap(policy.unravel, in_axes=1, out_axes=-1)(params.basis)
    return _Prepared(center_tree, basis_tree, params.coeffs)


def _linear_lowrank(layer: Linear, cp, bp, z, x):
    """``x``: (B, in); returns (B, out). One augmented dense matmul: the
    center weight and the k direction matrices stacked row-wise, so the MXU
    sees a single (B, in) @ (in, (k+1)*out) contraction; the per-lane
    combination is a cheap VPU epilogue."""
    W_c = cp["weight"]  # (out, in)
    W_b = bp["weight"]  # (out, in, k)
    out_f, in_f = W_c.shape
    k = W_b.shape[-1]
    # (k, out, in) -> (k*out, in); stack center on top -> ((k+1)*out, in)
    W_dirs = jnp.moveaxis(W_b, -1, 0).reshape(k * out_f, in_f)
    W_aug = jnp.concatenate([W_c, W_dirs], axis=0)
    y_aug = x @ W_aug.T  # (B, (k+1)*out)
    y = y_aug[:, :out_f]
    corr = y_aug[:, out_f:].reshape(-1, k, out_f)
    y = y + jnp.einsum("bko,bk->bo", corr, z)
    if layer.bias:
        y = y + cp["bias"] + z @ bp["bias"].T  # (B,k)@(k,out)
    return y


def _bias_lowrank(layer: Bias, cp, bp, z, x):
    return x + cp["bias"] + z @ bp["bias"].T


def _apply_lowrank(module: Module, cp, bp, z, x):
    if isinstance(module, Sequential):
        for m, c, b in zip(module.modules, cp, bp):
            x = _apply_lowrank(m, c, b, z, x)
        return x
    if isinstance(module, Linear):
        return _linear_lowrank(module, cp, bp, z, x)
    if isinstance(module, Bias):
        return _bias_lowrank(module, cp, bp, z, x)
    # parameterless layer: batched apply is the plain apply
    y, _ = module.apply(cp, x, None)
    return y


def lowrank_forward(
    policy, params: LowRankParamsBatch, prepared: Optional[_Prepared], obs, states
) -> Tuple[jnp.ndarray, Any]:
    """Whole-population forward: ``obs`` (B, obs_dim) -> (B, act_dim).
    ``prepared`` may be None (computed on the fly — only sensible outside
    hot loops)."""
    module = policy.module
    if states is None and lowrank_supported(module):
        if prepared is None:
            prepared = prepare_lowrank(policy, params)
        out = _apply_lowrank(
            module, prepared.center_tree, prepared.basis_tree, prepared.coeffs, obs
        )
        return out, None
    # fallback: materialize the dense population and vmap (correct for any
    # module, including stateful/recurrent ones)
    dense = params.materialize()
    if states is None:
        return jax.vmap(lambda p, o: policy(p, o))(dense, obs)
    return jax.vmap(policy)(dense, obs, states)
