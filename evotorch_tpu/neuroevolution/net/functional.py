"""Flat-parameter policy interface.

Parity: reference ``net/functional.py:46-259`` — the
``ModuleExpectingFlatParameters`` wrapper that turns a network into a pure
function ``f(flat_params, x, h=None)`` by slicing a flat vector into named
parameters, and ``make_functional_module`` (``functional.py:203``). Also the
parameter-vector helpers of ``net/misc.py:26-116``
(``count_parameters``/``parameter_vector``/``fill_parameters``).

In JAX this is ``ravel_pytree`` rather than meta-device ``functional_call``
tricks: the unravel function is computed once from the module's parameter
template and is jit/vmap-transparent.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from .layers import Module

__all__ = [
    "FlatParamsPolicy",
    "make_functional_module",
    "count_parameters",
    "parameter_vector",
    "fill_parameters",
]


class FlatParamsPolicy:
    """A network exposed through a flat parameter vector
    (reference ``ModuleExpectingFlatParameters``, ``net/functional.py:46``).

    Usage::

        policy = FlatParamsPolicy(module, key=jax.random.key(0))
        flat0 = policy.init_parameters(key)      # (n,) template init
        y, h  = policy(flat, x)                  # stateless / fresh state
        y, h  = policy(flat, x, h)               # recurrent step
    """

    def __init__(self, module: Module, *, key=None):
        self.module = module
        template_key = key if key is not None else jax.random.key(0)
        template = module.init(template_key)
        flat, unravel = ravel_pytree(template)
        self._template_flat = flat
        self._unravel = unravel
        self.parameter_count = int(flat.shape[0])

    @property
    def num_parameters(self) -> int:
        return self.parameter_count

    def init_parameters(self, key) -> jnp.ndarray:
        """A freshly initialized flat parameter vector."""
        flat, _ = ravel_pytree(self.module.init(key))
        return flat

    def unravel(self, flat_params: jnp.ndarray) -> Any:
        return self._unravel(flat_params)

    def initial_state(self):
        return self.module.initial_state()

    def __call__(self, flat_params, x, state=None) -> Tuple[jnp.ndarray, Any]:
        params = self._unravel(flat_params)
        return self.module.apply(params, x, state)


def make_functional_module(module: Module, *, key=None) -> FlatParamsPolicy:
    """Reference ``net/functional.py:203``."""
    return FlatParamsPolicy(module, key=key)


def count_parameters(module: Module, *, key=None) -> int:
    """Reference ``net/misc.py:84``."""
    return FlatParamsPolicy(module, key=key).parameter_count


def parameter_vector(params: Any) -> jnp.ndarray:
    """Flatten a parameter pytree into one vector (reference ``net/misc.py:44``)."""
    flat, _ = ravel_pytree(params)
    return flat


def fill_parameters(template_params: Any, vector: jnp.ndarray) -> Any:
    """Inverse of :func:`parameter_vector` against a template pytree
    (reference ``net/misc.py:26``)."""
    _, unravel = ravel_pytree(template_params)
    return unravel(jnp.asarray(vector))
