"""Host-side vector envs + batched/pipelined rollouts for gym-API envs.

Parity: reference ``net/vecrl.py:1541-1912`` (``SyncVectorEnv``) and the
vectorized evaluation loop of ``vecgymne.py:744-916`` as applied to
``"gym::"`` environments: N gymnasium environments stepped in lockstep on the
host, eager auto-reset, per-env episode accounting with activity masking, and
a *batched* policy forward — one device call per timestep for the whole lane
block, instead of one per env (the reference's torch-policy-over-numpy-envs
pattern, jax-side here).

Two rollout engines share the vector-env contract:

- :func:`run_host_vectorized_rollout` — the original synchronous loop: one
  lane block, device forward and host physics strictly alternating, each
  solution pinned to one lane for all its episodes. Deliberately kept
  **byte-stable as the PR-2 reference implementation**: the pipelined
  engine's regression tests compare against it bit-exactly, and it is the
  "synchronous host path" baseline `bench.py`'s `mj_pipeline_speedup`
  measures against (`GymNE(host_pipeline="chunked")` routes here).
- :func:`run_host_pipelined_rollout` — the Sebulba-style scheduler
  (Podracer, arXiv:2104.06272): the lanes are split into blocks; while the
  device runs the batched policy forward for block A, a host worker thread
  runs the physics for block B, with the ``np.asarray`` device sync confined
  to the swap point. On top of the overlap it is **work-conserving**: the
  whole batch's (solution, episode) items form one pending queue, and a lane
  whose episode finishes is immediately re-seeded with the next pending item
  — the host-side mirror of the on-device ``episodes_refill`` contract
  (``vecrl.py``), so a single long episode no longer stalls its block. Its
  ``mode="sync"`` fallback executes the *identical* event order without the
  worker thread, which makes pipelined-vs-sync bit-identity a testable
  invariant (see ``docs/eval_contracts.md``, "The host pipeline").

This is the capability class for environments that only exist as Python/gym
code. The TPU-native throughput path remains ``VecNE`` over pure-JAX envs
(``vecrl.run_vectorized_rollout``).
"""

from __future__ import annotations

import os
import queue
import threading
from collections import deque
from functools import partial
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...observability import tracer
from ...observability.tracer import span
from .rl import alive_bonus_for_step_host
from .vecrl import reset_tensors

__all__ = [
    "SyncVectorEnv",
    "run_host_vectorized_rollout",
    "run_host_pipelined_rollout",
    "HungPhysicsWorkerError",
]


class HungPhysicsWorkerError(RuntimeError):
    """The pipeline's physics worker thread would not exit (a hung native
    step). The vector env it was driving must be discarded, NOT closed or
    reused — its buffers may still be touched by the stuck thread."""


# module-level jitted forwards with the policy as a static arg: the jit cache
# persists across rollout calls (a per-call jit wrapper would recompile every
# chunk of every generation)
@partial(jax.jit, static_argnames=("policy",))
def _forward_stateless(policy, params, obs):
    return jax.vmap(lambda p, o: policy(p, o))(params, obs)


@partial(jax.jit, static_argnames=("policy",))
def _forward_stateful(policy, params, obs, states):
    return jax.vmap(policy)(params, obs, states)


class SyncVectorEnv:
    """Steps ``num_envs`` gymnasium environments in lockstep.

    - ``reset()`` -> ``(num_envs, obs_dim)`` float32 observations.
    - ``step(actions, active=None)`` -> ``(obs, rewards, dones)``; an env
      whose episode ended is eagerly auto-reset (its returned observation is
      the fresh reset observation, matching the reference's eager-autoreset
      contract, ``vecrl.py:1541``); inactive lanes are skipped and yield NaN
      dummy observations (the reference's exhausted-lane marker).
    """

    def __init__(
        self,
        env_fn: Union[Callable, Sequence[Callable]],
        num_envs: Optional[int] = None,
    ):
        if callable(env_fn):
            if num_envs is None:
                raise ValueError("Give num_envs when env_fn is a single factory")
            fns: List[Callable] = [env_fn] * int(num_envs)
        else:
            fns = list(env_fn)
        self.envs = [fn() for fn in fns]
        first = self.envs[0]
        self.observation_space = first.observation_space
        self.action_space = first.action_space
        self._obs_dim = int(np.prod(first.observation_space.shape))

    @property
    def num_envs(self) -> int:
        return len(self.envs)

    @property
    def is_discrete(self) -> bool:
        return hasattr(self.action_space, "n")

    def _flat_obs(self, obs) -> np.ndarray:
        return np.asarray(obs, dtype=np.float32).reshape(-1)

    def _reset_one(self, i: int) -> np.ndarray:
        out = self.envs[i].reset()
        if isinstance(out, tuple):  # modern gym API: (obs, info)
            out = out[0]
        return self._flat_obs(out)

    def reset(self) -> np.ndarray:
        return np.stack([self._reset_one(i) for i in range(self.num_envs)])

    def step(self, actions, active: Optional[np.ndarray] = None):
        n = self.num_envs
        obs = np.full((n, self._obs_dim), np.nan, dtype=np.float32)
        rewards = np.zeros(n, dtype=np.float32)
        dones = np.zeros(n, dtype=bool)
        for i in range(n):
            if active is not None and not active[i]:
                continue
            result = self.envs[i].step(actions[i])
            if len(result) == 5:  # modern API: obs, r, terminated, truncated, info
                o, r, terminated, truncated, _ = result
                done = bool(terminated) or bool(truncated)
            else:  # classic API: obs, r, done, info
                o, r, done, _ = result
                done = bool(done)
            rewards[i] = float(r)
            dones[i] = done
            obs[i] = self._reset_one(i) if done else self._flat_obs(o)
        return obs, rewards, dones

    def seed(self, seeds: Sequence[int]):
        for env, s in zip(self.envs, seeds):
            if hasattr(env, "reset"):
                try:
                    env.reset(seed=int(s))
                except TypeError:
                    pass  # classic API without seed kwarg

    def close(self):
        for env in self.envs:
            if hasattr(env, "close"):
                env.close()


def run_host_vectorized_rollout(
    vec_env: SyncVectorEnv,
    policy,
    params_batch,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    obs_stats=None,
    update_stats: bool = True,
    decrease_rewards_by: float = 0.0,
    alive_bonus_schedule: Optional[tuple] = None,
    action_noise_stdev: Optional[float] = None,
    rng: Optional[np.random.Generator] = None,
) -> dict:
    """Evaluate ``n <= num_envs`` policies, one per env lane, with a single
    batched device forward per timestep (the vectorized-evaluation loop of
    reference ``vecgymne.py:744-916`` over a host vector env).

    ``policy`` is a :class:`FlatParamsPolicy`; ``params_batch`` is ``(n, L)``.
    ``obs_stats`` is an optional ``RunningStat`` updated in place with every
    observation the policies consume (when ``update_stats``) and used for
    normalization. Returns ``{"scores", "interactions", "episodes"}``.
    """
    params_batch = jnp.asarray(params_batch)
    n = params_batch.shape[0]
    if n > vec_env.num_envs:
        raise ValueError(f"{n} solutions > {vec_env.num_envs} env lanes")
    rng = np.random.default_rng() if rng is None else rng

    lanes = np.arange(n)
    obs = vec_env.reset()[:n]
    if obs_stats is not None and update_stats:
        obs_stats.update(obs)

    proto = policy.initial_state()
    if proto is None:
        states = None
    else:
        states = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(leaf, (n,) + leaf.shape), proto
        )

    scores = np.zeros(n, dtype=np.float64)
    episodes_done = np.zeros(n, dtype=np.int64)
    steps_in_episode = np.zeros(n, dtype=np.int64)
    active = np.ones(n, dtype=bool)
    interactions = 0
    act_space = vec_env.action_space
    discrete = vec_env.is_discrete

    # hard iteration cap (ADVICE r2): with episode_length=None and an env
    # lacking its own TimeLimit the loop would otherwise never terminate;
    # 100k steps/episode is far beyond any gym episode horizon
    per_episode_cap = int(episode_length) if episode_length is not None else 100_000
    step_cap = per_episode_cap * int(num_episodes)
    total_loop_steps = 0

    while active.any():
        if total_loop_steps >= step_cap:
            raise RuntimeError(
                f"run_host_vectorized_rollout exceeded {step_cap} lockstep"
                " iterations without every lane finishing its episodes; the"
                " env likely never terminates — pass episode_length= or wrap"
                " it in a TimeLimit"
            )
        total_loop_steps += 1
        norm_obs = obs
        if obs_stats is not None and obs_stats.count >= 2:
            norm_obs = obs_stats.normalize(obs).astype(np.float32)
        norm_obs = np.nan_to_num(norm_obs)  # NaN dummy rows of inactive lanes
        if states is None:
            out, new_states = _forward_stateless(
                policy, params_batch, jnp.asarray(norm_obs)
            )
        else:
            out, new_states = _forward_stateful(
                policy, params_batch, jnp.asarray(norm_obs), states
            )
        out = np.asarray(out)

        if discrete:
            actions = np.argmax(out, axis=-1)
        else:
            actions = out.astype(np.float64).reshape((n,) + act_space.shape)
            if action_noise_stdev is not None:
                actions = actions + rng.normal(size=actions.shape) * float(
                    action_noise_stdev
                )
            actions = np.clip(actions, act_space.low, act_space.high)

        # lanes beyond n (shorter final chunk) stay permanently inactive
        pad = vec_env.num_envs - n
        if pad:
            actions = np.concatenate(
                [actions, np.zeros((pad,) + actions.shape[1:], actions.dtype)]
            )
            full_active = np.concatenate([active, np.zeros(pad, dtype=bool)])
        else:
            full_active = active
        new_obs, rewards, env_dones = vec_env.step(actions, active=full_active)
        new_obs, rewards, env_dones = new_obs[:n], rewards[:n], env_dones[:n]
        steps_in_episode[active] += 1
        interactions += int(active.sum())
        dones = env_dones.copy()
        if episode_length is not None:
            dones = dones | (active & (steps_in_episode >= int(episode_length)))

        rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            # host loop, host step counters: pure-python bonus — the jnp form
            # would dispatch + sync one device scalar per active lane per step
            for i in lanes[active & ~dones]:
                rewards[i] += alive_bonus_for_step_host(
                    int(steps_in_episode[i]), alive_bonus_schedule
                )
        scores[active] += rewards[active]

        finished = dones & active
        episodes_done[finished] += 1
        steps_in_episode[finished] = 0
        if new_states is not None:
            new_states = reset_tensors(new_states, jnp.asarray(finished))
        states = new_states
        active = episodes_done < int(num_episodes)

        # lanes truncated by episode_length need a manual reset — the env
        # auto-resets only on its own terminal signal (env_dones)
        for i in lanes[finished & active & ~env_dones]:
            new_obs[i] = vec_env._reset_one(i)
        obs = new_obs

        if obs_stats is not None and update_stats and active.any():
            obs_stats.update(obs[active])

    return {
        "scores": scores / np.maximum(episodes_done, 1),
        "interactions": interactions,
        "episodes": int(episodes_done.sum()),
    }


# ---------------------------------------------------------------------------
# the Sebulba-style pipelined scheduler (host refill + host/device overlap)
# ---------------------------------------------------------------------------

# gathered forwards: the full (P, L) parameter matrix lives on device once per
# evaluation; each block's forward gathers its lanes' CURRENT solutions by
# index inside the jitted program, so a refill changes one integer per lane
# instead of shipping a fresh (w, L) parameter block over the host link every
# timestep. sol_idx is a traced argument — refills never retrace.
@partial(jax.jit, static_argnames=("policy",))
def _forward_gather_stateless(policy, params_all, sol_idx, obs):
    return jax.vmap(lambda p, o: policy(p, o))(params_all[sol_idx], obs)


@partial(jax.jit, static_argnames=("policy",))
def _forward_gather_stateful(policy, params_all, sol_idx, obs, states):
    return jax.vmap(policy)(params_all[sol_idx], obs, states)


class _PhysicsWorker:
    """One host thread draining a FIFO of ``vec_env.step`` calls.

    The double buffer of the pipeline: the main thread submits block A's
    actions and immediately goes on to materialize block B's forward (the
    only ``block_until_ready``-equivalent sync point) while the physics for
    A runs here. ``mujoco.rollout`` releases the GIL, so on a multi-core
    host the physics genuinely overlaps the device forward *and* the main
    thread's numpy bookkeeping. Results come back in submission order —
    exactly the order the scheduler retires blocks — so a single result
    queue is the whole synchronization story.
    """

    def __init__(self, vec_env):
        self._vec_env = vec_env
        self._tasks: "queue.Queue" = queue.Queue()
        self._results: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name="hostvecenv-physics", daemon=True
        )
        self._thread.start()

    def _run(self):
        while True:
            task = self._tasks.get()
            if task is None:
                return
            actions, active, label = task
            try:
                # the physics track: this span lives on the WORKER thread's
                # tid, so in a Perfetto view it overlaps the main thread's
                # device-forward spans — the pipeline's whole point, visible
                with span("physics", "pipeline", block=label):
                    result = self._vec_env.step(actions, active=active)
                self._results.put(("ok", result))
            except BaseException as exc:  # surfaced on the main thread  # graftlint: allow(swallow): shipped to the main thread via the result queue and re-raised there
                self._results.put(("error", exc))

    def submit(self, actions, active, label=None):
        self._tasks.put((actions, active, label))

    def result(self):
        status, payload = self._results.get()
        if status == "error":
            raise payload
        return payload

    def close(self):
        """Stop the thread; raises if it will not die (a hung native physics
        call) — the caller must then discard the vec_env rather than hand it
        to a fresh worker, or two threads would race on the same MjData
        buffers."""
        self._tasks.put(None)
        # generous: at most ONE physics step is in flight ahead of the
        # sentinel, and a block step is milliseconds — only a hung native
        # call exceeds this
        self._thread.join(timeout=60)
        if self._thread.is_alive():
            raise HungPhysicsWorkerError(
                "hostvecenv physics worker did not exit (native step hung);"
                " discard this vector env — it is not safe to reuse"
            )


class _LaneBlock:
    """One lane block of the pipeline: a contiguous slice of env lanes, the
    (solution, episode) item each lane is currently serving, and the block's
    in-flight forward."""

    __slots__ = (
        "lanes", "sl", "item", "active", "obs", "states", "fwd", "pending_states",
        "iters", "sol_idx_dev", "full_actions", "full_active", "index", "fwd_t0",
    )

    def __init__(self, lanes: np.ndarray, items: np.ndarray, obs: np.ndarray, states, num_envs: int, act_shape, act_dtype, index: int = 0):
        self.index = index  # block number (trace-span labeling only)
        self.fwd_t0 = None  # trace clock at forward dispatch (tracing only)
        self.lanes = lanes  # global lane indices, (w,) — contiguous
        self.sl = slice(int(lanes[0]), int(lanes[-1]) + 1)  # view, not copy
        self.item = items  # global item id per lane, -1 = exhausted, (w,)
        self.active = items >= 0
        self.obs = obs  # (w, obs_dim) float32
        self.states = states  # per-lane policy state pytree or None
        self.fwd = None  # dispatched forward (out, new_states) or None
        self.pending_states = None
        self.iters = 0  # lockstep iterations this block executed
        self.sol_idx_dev = None  # cached lane->solution index vector
        # reusable full-width submission buffers (refreshed in place)
        self.full_actions = np.zeros((num_envs,) + act_shape, dtype=act_dtype)
        self.full_active = np.zeros(num_envs, dtype=bool)
        self.full_active[lanes] = self.active


def run_host_pipelined_rollout(
    vec_env,
    policy,
    params_batch,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    obs_stats=None,
    update_stats: bool = True,
    decrease_rewards_by: float = 0.0,
    alive_bonus_schedule: Optional[tuple] = None,
    action_noise_stdev: Optional[float] = None,
    rng: Optional[np.random.Generator] = None,
    mode: str = "pipelined",
    num_blocks: Optional[int] = None,
    use_tuned_cache: bool = True,
    tuned_config_source: Optional[str] = None,
) -> dict:
    """Evaluate a whole batch of ``P`` policies over ``vec_env``'s lanes with
    the pipelined two-lane-block scheduler.

    The work list is every (solution, episode) pair — ``P * num_episodes``
    items, solution-major. ``W = min(items, num_envs)`` lanes are split into
    ``num_blocks`` contiguous blocks; each scheduler round runs, per block:

    - **S1** normalize the block's observations and *dispatch* the batched
      device forward (async);
    - **S2** materialize the actions (``np.asarray`` — the swap point, the
      only device sync) and submit the block's physics;
    - **S3** collect the physics results, do all bookkeeping (reward credit,
      episode accounting, obs-stat updates) and **refill** each finished lane
      with the next pending item, so lanes never idle while work remains.

    ``mode="pipelined"`` runs the physics on a worker thread with a
    one-submission pipeline depth: block A's physics overlaps block B's
    device forward (the Sebulba split). ``mode="sync"`` executes the physics
    inline at the submit point — the **same S1/S2/S3 event order**, so
    scores, per-episode step counts, RNG draws and obs-normalization
    statistics are bit-identical between the two modes; the thread is the
    only difference. All bookkeeping lives on the main thread, which is what
    makes that determinism structural rather than lucky.

    Returns ``{"scores" (P,), "interactions", "episodes",
    "episode_steps" (P, num_episodes), "lane_episodes" (num_envs,),
    "block_iters" [per-block lockstep iteration counts],
    "occupancy" [counted interactions / executed lane-step slots]}``.

    With tracing on (``EVOTORCH_TRACE`` / ``observability.tracer``), each
    scheduler stage emits a span — ``s1.forward_dispatch``,
    ``s2.actions_sync`` (the device sync), ``s3.bookkeep_refill``,
    ``physics_wait`` — plus a ``device_forward`` span covering each block's
    dispatch->materialize window; the worker thread's ``physics`` spans land
    on their own track, so S1/S2/S3 overlap is directly visible in Perfetto.
    """
    if mode not in ("pipelined", "sync"):
        raise ValueError(f"mode must be 'pipelined' or 'sync', got {mode!r}")
    params_batch = jnp.asarray(params_batch)
    num_solutions = int(params_batch.shape[0])
    episodes_per_solution = int(num_episodes)
    total_items = num_solutions * episodes_per_solution
    if total_items == 0:
        return {
            "scores": np.zeros(num_solutions, dtype=np.float64),
            "interactions": 0,
            "episodes": 0,
            "episode_steps": np.zeros((num_solutions, episodes_per_solution), dtype=np.int64),
            "lane_episodes": np.zeros(vec_env.num_envs, dtype=np.int64),
            "block_iters": [],
            "tuned_config_source": (
                tuned_config_source
                if tuned_config_source is not None
                else ("override" if num_blocks is not None else "fallback")
            ),
        }
    rng = np.random.default_rng() if rng is None else rng

    width = min(total_items, vec_env.num_envs)
    caller_source = tuned_config_source
    if num_blocks is None:
        # no explicit block count: consult the machine-scoped
        # "host_pipeline" entry of the tuned-config cache (the autotuner's
        # measured split for THIS box — observability/timings.py) before
        # the heuristic. Callers that already resolved the group at their
        # own altitude (GymNE) — or that must NOT see tuned configs (the
        # autotuner's own baseline, bench's BENCH_TUNED=0 path) — pass
        # use_tuned_cache=False so the group is resolved exactly once.
        # auto-heuristic: the two-block split only pays when the host
        # physics can genuinely overlap the device forward — on a
        # single-core box the split just doubles the per-round dispatch
        # cost, so run one block and keep the refill win.
        from ...observability.timings import SOURCE_CACHE, SOURCE_FALLBACK, lookup_tuned

        entry = lookup_tuned("host_pipeline", {}) if use_tuned_cache else None
        if entry is not None and set(entry.config) - {"num_blocks"}:
            # the entry was measured as a JOINT config (e.g. blocks +
            # mj_nthread together), but nthread is baked into the already-
            # built vec_env at this altitude — applying only part of it
            # would run an unmeasured combination labeled "cache". GymNE,
            # which builds the vec env, applies the full group; direct
            # callers fall back to the heuristic.
            entry = None
        if entry is not None and entry.config.get("num_blocks") is not None:
            num_blocks = int(entry.config["num_blocks"])
            tuned_config_source = SOURCE_CACHE
        else:
            num_blocks = 2 if (os.cpu_count() or 1) > 1 else 1
            tuned_config_source = SOURCE_FALLBACK
    else:
        from ...observability.timings import SOURCE_OVERRIDE

        tuned_config_source = SOURCE_OVERRIDE
    if caller_source is not None:
        # a caller that resolved the group at its own altitude (GymNE:
        # explicit > cache > fallback across blocks AND nthread together)
        # passes the TRUE provenance — its concrete num_blocks must not be
        # mislabeled "override" when it actually came from the cache
        tuned_config_source = caller_source
    num_blocks = max(1, min(int(num_blocks), width))
    act_space = vec_env.action_space
    discrete = vec_env.is_discrete
    act_shape = () if discrete else tuple(act_space.shape)

    # hard cap (ADVICE r2, same contract as the synchronous loop): an env
    # with neither its own TimeLimit nor episode_length= must fail loudly
    per_episode_cap = int(episode_length) if episode_length is not None else 100_000

    # ---- global accounting --------------------------------------------------
    item_return = np.zeros(total_items, dtype=np.float64)
    item_steps = np.zeros(total_items, dtype=np.int64)
    lane_episodes = np.zeros(vec_env.num_envs, dtype=np.int64)
    steps_in_episode = np.zeros(vec_env.num_envs, dtype=np.int64)
    interactions = 0
    episodes_finished = 0
    next_item = width  # items 0..width-1 seed the lanes below

    # ---- lanes + blocks -----------------------------------------------------
    all_obs = vec_env.reset()[:width]
    proto = policy.initial_state()
    blocks: List[_LaneBlock] = []
    for bi, lanes in enumerate(np.array_split(np.arange(width), num_blocks)):
        lanes = lanes.astype(np.int64)
        if proto is None:
            states = None
        else:
            states = jax.tree_util.tree_map(
                lambda leaf: jnp.broadcast_to(leaf, (len(lanes),) + leaf.shape), proto
            )
        blocks.append(
            _LaneBlock(
                lanes, lanes.copy(), all_obs[lanes], states, vec_env.num_envs,
                act_shape, np.int64 if discrete else np.float64, index=bi,
            )
        )
        lane_episodes[lanes] += 1
    if obs_stats is not None and update_stats:
        for blk in blocks:  # block order: the canonical accumulation order
            obs_stats.update(blk.obs[blk.active])

    # ---- stages -------------------------------------------------------------
    def s1_dispatch_forward(blk: _LaneBlock):
        with span("s1.forward_dispatch", "pipeline", block=blk.index):
            norm_obs = blk.obs
            if obs_stats is not None and obs_stats.count >= 2:
                norm_obs = np.asarray(obs_stats.normalize(norm_obs), dtype=np.float32)
            # unconditional, matching the reference loop: scrubs both the NaN
            # dummy rows of exhausted lanes AND non-finite observations from
            # diverged physics on live lanes (no-termination families)
            norm_obs = np.nan_to_num(norm_obs)
            if blk.sol_idx_dev is None:  # refreshed only after a refill/exhaustion
                blk.sol_idx_dev = np.where(blk.item >= 0, blk.item // episodes_per_solution, 0)
            # numpy arguments go straight into the jitted call: jit's own arg
            # transfer is ~3x cheaper than a separate jnp.asarray dispatch here
            if blk.states is None:
                blk.fwd = _forward_gather_stateless(
                    policy, params_batch, blk.sol_idx_dev, norm_obs
                )
            else:
                blk.fwd = _forward_gather_stateful(
                    policy, params_batch, blk.sol_idx_dev, norm_obs, blk.states
                )
        trace = tracer.get_tracer()
        if trace is not None:
            blk.fwd_t0 = trace.now_us()

    def s2_submit_physics(blk: _LaneBlock, worker: Optional[_PhysicsWorker]):
        with span("s2.actions_sync", "pipeline", block=blk.index):
            out, new_states = blk.fwd
            blk.fwd = None
            blk.pending_states = new_states
            out = np.asarray(out)  # the swap point: the pipeline's only device sync
            trace = tracer.get_tracer()
            if trace is not None and blk.fwd_t0 is not None:
                # the dispatched forward's lifetime, dispatch -> materialize:
                # the host-visible "device forward" span the physics track
                # overlaps with
                trace.complete(
                    "device_forward",
                    blk.fwd_t0,
                    trace.now_us() - blk.fwd_t0,
                    "pipeline",
                    block=blk.index,
                )
                blk.fwd_t0 = None
            if discrete:
                actions = np.argmax(out, axis=-1)
            else:
                actions = out.astype(np.float64).reshape((len(blk.lanes),) + act_shape)
                if action_noise_stdev is not None:
                    actions = actions + rng.normal(size=actions.shape) * float(action_noise_stdev)
                actions = np.clip(actions, act_space.low, act_space.high)
            blk.full_actions[blk.sl] = actions
        if worker is not None:
            worker.submit(blk.full_actions, blk.full_active, blk.index)
            return None
        with span("physics", "pipeline", block=blk.index):  # sync mode: inline
            return vec_env.step(blk.full_actions, active=blk.full_active)

    def s3_bookkeep_and_refill(blk: _LaneBlock, step_result):
        with span("s3.bookkeep_refill", "pipeline", block=blk.index):
            _s3_inner(blk, step_result)

    def _s3_inner(blk: _LaneBlock, step_result):
        nonlocal interactions, episodes_finished, next_item
        obs_full, rewards_full, dones_full = step_result
        obs = obs_full[blk.sl]
        rewards = rewards_full[blk.sl].astype(np.float64)
        env_dones = dones_full[blk.sl]
        active = blk.active
        blk.iters += 1

        block_steps = steps_in_episode[blk.sl]  # view: writes land globally
        block_steps[active] += 1
        if np.any(block_steps[active] > 100_000):
            raise RuntimeError(
                "run_host_pipelined_rollout exceeded 100000 steps in one"
                " episode; the env likely never terminates — pass"
                " episode_length= or wrap it in a TimeLimit"
            )
        interactions += int(active.sum())
        dones = env_dones.copy()
        if episode_length is not None:
            dones |= active & (block_steps >= per_episode_cap)

        if decrease_rewards_by != 0.0:
            rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            # host loop, host step counters: pure-python bonus (the jnp form
            # would dispatch + sync one device scalar per lane per step)
            for j in np.flatnonzero(active & ~dones):
                rewards[j] += alive_bonus_for_step_host(
                    int(steps_in_episode[blk.lanes[j]]), alive_bonus_schedule
                )
        # lane items are distinct, so a fancy-indexed add is exact
        item_return[blk.item[active]] += rewards[active]

        finished = dones & active
        if finished.any():
            for j in np.flatnonzero(finished):
                lane = int(blk.lanes[j])
                item_steps[blk.item[j]] = steps_in_episode[lane]
                steps_in_episode[lane] = 0
                episodes_finished += 1
                if next_item < total_items:  # work-conserving refill
                    blk.item[j] = next_item
                    next_item += 1
                    lane_episodes[lane] += 1
                    if not env_dones[j]:
                        # truncated by episode_length: the env auto-resets
                        # only on its own terminal signal, so reseed manually
                        obs[j] = vec_env._reset_one(lane)
                    # (on env_dones the eager auto-reset obs in `obs[j]` IS
                    # the refilled item's fresh initial observation)
                else:
                    blk.item[j] = -1
                    blk.active[j] = False
            blk.sol_idx_dev = None  # lane->solution mapping changed
            blk.full_active[blk.lanes] = blk.active
            if blk.pending_states is not None:
                blk.states = reset_tensors(blk.pending_states, jnp.asarray(finished))
                blk.pending_states = None
        if blk.pending_states is not None:
            blk.states = blk.pending_states
            blk.pending_states = None
        blk.obs = obs
        if obs_stats is not None and update_stats and blk.active.any():
            obs_stats.update(obs[blk.active])

    # ---- the scheduler loop -------------------------------------------------
    # Round-robin over blocks in a FIXED order; `inflight` is the FIFO of
    # blocks whose physics is submitted but not yet retired. In pipelined
    # mode one submission stays in flight across the S2 of the next block, so
    # its physics (worker thread) overlaps that block's device forward; in
    # sync mode the depth is 0 and every submission retires immediately. The
    # S1/S2/S3 event sequence is identical in both modes — only the waiting
    # pattern differs — which is the determinism guarantee.
    worker = _PhysicsWorker(vec_env) if mode == "pipelined" else None
    depth = 1 if worker is not None else 0
    live = [blk for blk in blocks if blk.active.any()]
    inflight: deque = deque()
    try:
        for blk in live:
            s1_dispatch_forward(blk)
        while live:
            for blk in blocks:
                if blk in live and blk.fwd is not None:
                    result = s2_submit_physics(blk, worker)
                    inflight.append((blk, result))
            while inflight and (
                len(inflight) > depth
                or not any(b.fwd is not None for b in live)
            ):
                prev, result = inflight.popleft()
                if result is None:
                    # main-thread stall waiting on the worker: visible in a
                    # trace as the gap the pipeline exists to shrink
                    with span("physics_wait", "pipeline", block=prev.index):
                        result = worker.result()
                s3_bookkeep_and_refill(prev, result)
                if prev.active.any():
                    s1_dispatch_forward(prev)
                else:
                    live.remove(prev)
    finally:
        if worker is not None:
            worker.close()

    # lane-step slots executed = per-block width x lockstep iterations; the
    # fraction that were counted interactions is the host-path occupancy
    # (the same figure the on-device engines report — docs/observability.md)
    capacity = sum(len(blk.lanes) * blk.iters for blk in blocks)
    return {
        "scores": item_return.reshape(num_solutions, episodes_per_solution).mean(axis=1),
        "interactions": interactions,
        "episodes": episodes_finished,
        "episode_steps": item_steps.reshape(num_solutions, episodes_per_solution),
        "lane_episodes": lane_episodes,
        "block_iters": [blk.iters for blk in blocks],
        "occupancy": interactions / capacity if capacity else 0.0,
        # where the block split came from: "override" (explicit
        # num_blocks), "cache" (tuned_configs.json machine entry) or
        # "fallback" (the core-count heuristic)
        "tuned_config_source": tuned_config_source,
    }
