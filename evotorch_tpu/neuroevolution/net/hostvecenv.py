"""Host-side synchronous vector env + batched rollout for gym-API envs.

Parity: reference ``net/vecrl.py:1541-1912`` (``SyncVectorEnv``) and the
vectorized evaluation loop of ``vecgymne.py:744-916`` as applied to
``"gym::"`` environments: N gymnasium environments stepped in lockstep on the
host, eager auto-reset, per-env episode accounting with activity masking, and
a *batched* policy forward — one device call per timestep for the whole lane
block, instead of one per env (the reference's torch-policy-over-numpy-envs
pattern, jax-side here).

This is the capability class for environments that only exist as Python/gym
code. The TPU-native throughput path remains ``VecNE`` over pure-JAX envs
(``vecrl.run_vectorized_rollout``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .rl import alive_bonus_for_step_host
from .vecrl import reset_tensors

__all__ = ["SyncVectorEnv", "run_host_vectorized_rollout"]


# module-level jitted forwards with the policy as a static arg: the jit cache
# persists across rollout calls (a per-call jit wrapper would recompile every
# chunk of every generation)
@partial(jax.jit, static_argnames=("policy",))
def _forward_stateless(policy, params, obs):
    return jax.vmap(lambda p, o: policy(p, o))(params, obs)


@partial(jax.jit, static_argnames=("policy",))
def _forward_stateful(policy, params, obs, states):
    return jax.vmap(policy)(params, obs, states)


class SyncVectorEnv:
    """Steps ``num_envs`` gymnasium environments in lockstep.

    - ``reset()`` -> ``(num_envs, obs_dim)`` float32 observations.
    - ``step(actions, active=None)`` -> ``(obs, rewards, dones)``; an env
      whose episode ended is eagerly auto-reset (its returned observation is
      the fresh reset observation, matching the reference's eager-autoreset
      contract, ``vecrl.py:1541``); inactive lanes are skipped and yield NaN
      dummy observations (the reference's exhausted-lane marker).
    """

    def __init__(
        self,
        env_fn: Union[Callable, Sequence[Callable]],
        num_envs: Optional[int] = None,
    ):
        if callable(env_fn):
            if num_envs is None:
                raise ValueError("Give num_envs when env_fn is a single factory")
            fns: List[Callable] = [env_fn] * int(num_envs)
        else:
            fns = list(env_fn)
        self.envs = [fn() for fn in fns]
        first = self.envs[0]
        self.observation_space = first.observation_space
        self.action_space = first.action_space
        self._obs_dim = int(np.prod(first.observation_space.shape))

    @property
    def num_envs(self) -> int:
        return len(self.envs)

    @property
    def is_discrete(self) -> bool:
        return hasattr(self.action_space, "n")

    def _flat_obs(self, obs) -> np.ndarray:
        return np.asarray(obs, dtype=np.float32).reshape(-1)

    def _reset_one(self, i: int) -> np.ndarray:
        out = self.envs[i].reset()
        if isinstance(out, tuple):  # modern gym API: (obs, info)
            out = out[0]
        return self._flat_obs(out)

    def reset(self) -> np.ndarray:
        return np.stack([self._reset_one(i) for i in range(self.num_envs)])

    def step(self, actions, active: Optional[np.ndarray] = None):
        n = self.num_envs
        obs = np.full((n, self._obs_dim), np.nan, dtype=np.float32)
        rewards = np.zeros(n, dtype=np.float32)
        dones = np.zeros(n, dtype=bool)
        for i in range(n):
            if active is not None and not active[i]:
                continue
            result = self.envs[i].step(actions[i])
            if len(result) == 5:  # modern API: obs, r, terminated, truncated, info
                o, r, terminated, truncated, _ = result
                done = bool(terminated) or bool(truncated)
            else:  # classic API: obs, r, done, info
                o, r, done, _ = result
                done = bool(done)
            rewards[i] = float(r)
            dones[i] = done
            obs[i] = self._reset_one(i) if done else self._flat_obs(o)
        return obs, rewards, dones

    def seed(self, seeds: Sequence[int]):
        for env, s in zip(self.envs, seeds):
            if hasattr(env, "reset"):
                try:
                    env.reset(seed=int(s))
                except TypeError:
                    pass  # classic API without seed kwarg

    def close(self):
        for env in self.envs:
            if hasattr(env, "close"):
                env.close()


def run_host_vectorized_rollout(
    vec_env: SyncVectorEnv,
    policy,
    params_batch,
    *,
    num_episodes: int = 1,
    episode_length: Optional[int] = None,
    obs_stats=None,
    update_stats: bool = True,
    decrease_rewards_by: float = 0.0,
    alive_bonus_schedule: Optional[tuple] = None,
    action_noise_stdev: Optional[float] = None,
    rng: Optional[np.random.Generator] = None,
) -> dict:
    """Evaluate ``n <= num_envs`` policies, one per env lane, with a single
    batched device forward per timestep (the vectorized-evaluation loop of
    reference ``vecgymne.py:744-916`` over a host vector env).

    ``policy`` is a :class:`FlatParamsPolicy`; ``params_batch`` is ``(n, L)``.
    ``obs_stats`` is an optional ``RunningStat`` updated in place with every
    observation the policies consume (when ``update_stats``) and used for
    normalization. Returns ``{"scores", "interactions", "episodes"}``.
    """
    params_batch = jnp.asarray(params_batch)
    n = params_batch.shape[0]
    if n > vec_env.num_envs:
        raise ValueError(f"{n} solutions > {vec_env.num_envs} env lanes")
    rng = np.random.default_rng() if rng is None else rng

    lanes = np.arange(n)
    obs = vec_env.reset()[:n]
    if obs_stats is not None and update_stats:
        obs_stats.update(obs)

    proto = policy.initial_state()
    if proto is None:
        states = None
    else:
        states = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(leaf, (n,) + leaf.shape), proto
        )

    scores = np.zeros(n, dtype=np.float64)
    episodes_done = np.zeros(n, dtype=np.int64)
    steps_in_episode = np.zeros(n, dtype=np.int64)
    active = np.ones(n, dtype=bool)
    interactions = 0
    act_space = vec_env.action_space
    discrete = vec_env.is_discrete

    # hard iteration cap (ADVICE r2): with episode_length=None and an env
    # lacking its own TimeLimit the loop would otherwise never terminate;
    # 100k steps/episode is far beyond any gym episode horizon
    per_episode_cap = int(episode_length) if episode_length is not None else 100_000
    step_cap = per_episode_cap * int(num_episodes)
    total_loop_steps = 0

    while active.any():
        if total_loop_steps >= step_cap:
            raise RuntimeError(
                f"run_host_vectorized_rollout exceeded {step_cap} lockstep"
                " iterations without every lane finishing its episodes; the"
                " env likely never terminates — pass episode_length= or wrap"
                " it in a TimeLimit"
            )
        total_loop_steps += 1
        norm_obs = obs
        if obs_stats is not None and obs_stats.count >= 2:
            norm_obs = obs_stats.normalize(obs).astype(np.float32)
        norm_obs = np.nan_to_num(norm_obs)  # NaN dummy rows of inactive lanes
        if states is None:
            out, new_states = _forward_stateless(
                policy, params_batch, jnp.asarray(norm_obs)
            )
        else:
            out, new_states = _forward_stateful(
                policy, params_batch, jnp.asarray(norm_obs), states
            )
        out = np.asarray(out)

        if discrete:
            actions = np.argmax(out, axis=-1)
        else:
            actions = out.astype(np.float64).reshape((n,) + act_space.shape)
            if action_noise_stdev is not None:
                actions = actions + rng.normal(size=actions.shape) * float(
                    action_noise_stdev
                )
            actions = np.clip(actions, act_space.low, act_space.high)

        # lanes beyond n (shorter final chunk) stay permanently inactive
        pad = vec_env.num_envs - n
        if pad:
            actions = np.concatenate(
                [actions, np.zeros((pad,) + actions.shape[1:], actions.dtype)]
            )
            full_active = np.concatenate([active, np.zeros(pad, dtype=bool)])
        else:
            full_active = active
        new_obs, rewards, env_dones = vec_env.step(actions, active=full_active)
        new_obs, rewards, env_dones = new_obs[:n], rewards[:n], env_dones[:n]
        steps_in_episode[active] += 1
        interactions += int(active.sum())
        dones = env_dones.copy()
        if episode_length is not None:
            dones = dones | (active & (steps_in_episode >= int(episode_length)))

        rewards = rewards - decrease_rewards_by
        if alive_bonus_schedule is not None:
            # host loop, host step counters: pure-python bonus — the jnp form
            # would dispatch + sync one device scalar per active lane per step
            for i in lanes[active & ~dones]:
                rewards[i] += alive_bonus_for_step_host(
                    int(steps_in_episode[i]), alive_bonus_schedule
                )
        scores[active] += rewards[active]

        finished = dones & active
        episodes_done[finished] += 1
        steps_in_episode[finished] = 0
        if new_states is not None:
            new_states = reset_tensors(new_states, jnp.asarray(finished))
        states = new_states
        active = episodes_done < int(num_episodes)

        # lanes truncated by episode_length need a manual reset — the env
        # auto-resets only on its own terminal signal (env_dones)
        for i in lanes[finished & active & ~env_dones]:
            new_obs[i] = vec_env._reset_one(i)
        obs = new_obs

        if obs_stats is not None and update_stats and active.any():
            obs_stats.update(obs[active])

    return {
        "scores": scores / np.maximum(episodes_done, 1),
        "interactions": interactions,
        "episodes": int(episodes_done.sum()),
    }
