"""Fully-vectorized RL neuroevolution — the throughput path.

Parity: reference ``neuroevolution/vecgymne.py:95-1073`` (``VecGymNE``): one
sub-environment per solution, batched policies, masked episode accounting,
GPU-aware observation normalization, env-registry strings, alive bonus,
reward adjustment, ``to_policy``/``save_solution``.

TPU-first: the environment is a pure-JAX env (``evotorch_tpu.envs``; Brax via
the gated adapter), and the whole evaluate is ONE jitted program
(``net/vecrl.py:run_vectorized_rollout``) — no dlpack ping-pong, no Python
stepping. With ``use_sharded_evaluation()``-style meshes, the population axis
shards across devices via ``shard_map`` (the rollout being pure makes that a
one-liner; see ``evaluate_sharded``).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import SolutionBatch
from ..envs import Env, make_env
from ..observability.timings import canonical_env_label, resolve_knobs
from ..tools.lowrank import LowRankParamsBatch, is_factored
from ..parallel.mesh import default_mesh
from .neproblem import NEProblem
from .net.layers import Module
from .net.rl import ActClipLayer
from .net.runningnorm import RunningNorm
from .net.vecrl import (
    _params_popsize,
    run_vectorized_rollout,
    run_vectorized_rollout_compacting,
    run_vectorized_rollout_compacting_sharded,
)

__all__ = ["VecNE", "VecGymNE"]


class VecNE(NEProblem):
    """Vectorized neuroevolution over a pure-JAX env."""

    def __init__(
        self,
        env: Union[str, Env],
        network: Union[str, Module, Callable],
        *,
        env_config: Optional[dict] = None,
        max_num_envs: Optional[int] = None,
        network_args: Optional[dict] = None,
        observation_normalization: bool = False,
        decrease_rewards_by: Optional[float] = None,
        alive_bonus_schedule: Optional[tuple] = None,
        action_noise_stdev: Optional[float] = None,
        num_episodes: int = 1,
        episode_length: Optional[int] = None,
        eval_mode: str = "episodes",
        obs_norm_sync: str = "cohort",
        compact_config: Optional[dict] = None,
        refill_config: Optional[dict] = None,
        solution_groups=None,
        slo=None,
        health_telemetry: bool = True,
        nonfinite_quarantine: bool = True,
        nonfinite_penalty: Optional[float] = None,
        eval_backend=None,
        compute_dtype=None,
        initial_bounds=(-0.00001, 0.00001),
        seed: Optional[int] = None,
        num_actors=None,
        **kwargs,
    ):
        if isinstance(env, str):
            self._env: Env = make_env(env, **(env_config or {}))
        else:
            self._env = env
        self._observation_normalization = bool(observation_normalization)
        self._decrease_rewards_by = decrease_rewards_by
        self._alive_bonus_schedule = (
            tuple(alive_bonus_schedule) if alive_bonus_schedule is not None else None
        )
        self._action_noise_stdev = action_noise_stdev
        self._num_episodes = int(num_episodes)
        self._episode_length = None if episode_length is None else int(episode_length)
        # "episodes" = reference VecGymNE semantics (each lane runs
        # num_episodes episodes then idles); "episodes_compact" = the same
        # contract evaluated by the lane-compacting runner (finished lanes are
        # repacked out of the working set between chunks — see
        # net/vecrl.py:run_vectorized_rollout_compacting); "budget" = fixed
        # interaction budget with auto-reset — the throughput-optimal contract
        # where every computed step is a counted interaction.
        #
        # Reproducibility guarantee (user-facing): randomness is a PER-LANE
        # property (each lane carries its own PRNG chain seeded by its
        # original lane index — vecrl.py:_rollout_init), so in every config —
        # multi-episode, action noise — "episodes_compact" scores equal
        # "episodes" scores (bit-identical; with observation_normalization
        # the masked stat reductions may differ in float summation order
        # only), and WITHOUT observation normalization sharded evaluation is
        # bit-identical to unsharded. With observation normalization on,
        # sharding still changes scores semantically under the default
        # obs_norm_sync="cohort": each lane is normalized by its cohort's
        # running statistics, and sharding changes the cohort each shard's
        # stats see mid-rollout (deltas psum-merge only at the end, like the
        # reference's per-actor stats). obs_norm_sync="step" instead
        # psum-merges the stat deltas EVERY control step, so all shards
        # normalize by the mesh-global cohort and the divergence collapses to
        # float summation order — at the cost of one small collective per
        # step (measure before defaulting; test_vecrl characterizes both).
        # "episodes_refill" = the same contract again, evaluated by the
        # work-conserving lane-refill scheduler (a fixed lane width kept
        # saturated from an on-device pending-work queue — continuous
        # batching; see net/vecrl.py:_run_refill). One jitted program, so it
        # also runs INSIDE shard_map on the sharded path. At num_episodes=1
        # WITHOUT observation normalization its scores are bit-identical to
        # "episodes" (same per-lane seeding); with obs-norm on, the refill
        # schedule changes the running statistics each lane sees (late-
        # refilled lanes normalize by more history), so scores differ
        # semantically — schedule-dependent cohort statistics, like the
        # sharding caveat above.
        if eval_mode not in ("episodes", "episodes_compact", "episodes_refill", "budget"):
            raise ValueError(
                "eval_mode must be 'episodes', 'episodes_compact',"
                f" 'episodes_refill' or 'budget', got {eval_mode!r}"
            )
        self._eval_mode = str(eval_mode)
        # tuning knobs for the refill scheduler (width, period); width is the
        # GLOBAL lane count and divides by the shard count on the mesh path,
        # like compact_config's widths
        if refill_config is not None:
            allowed = {"width", "period"}
            unknown = set(refill_config) - allowed
            if unknown:
                raise ValueError(f"Unknown refill_config keys: {sorted(unknown)}")
        self._refill_config = dict(refill_config or {})
        # per-group telemetry (ISSUE 15): one small int id per solution maps
        # it to an accounting group (tenant, island, ...); the rollout
        # engines segment_sum env-steps/episodes/capacity/refill/queue-wait
        # per group INSIDE the same jitted programs, so multi-tenant
        # occupancy/fairness accounting costs no extra host syncs
        if solution_groups is not None:
            g = np.asarray(solution_groups, dtype=np.int32)
            if g.ndim != 1 or g.size == 0:
                raise ValueError(
                    "solution_groups must be a non-empty 1-D array of group ids"
                )
            if int(g.min()) < 0:
                raise ValueError("solution_groups ids must be >= 0")
            self._solution_groups = g
            self._num_groups = int(g.max()) + 1
        else:
            self._solution_groups = None
            self._num_groups = 1
        # non-finite quarantine (ISSUE 17, docs/resilience.md): inside the
        # compiled eval programs, solutions whose mean score came back
        # non-finite (diverged physics, overflowed bf16 reward sums) have
        # their credit replaced — by the worst finite score in the batch
        # (penalty=None) or a fixed penalty — BEFORE anything downstream
        # (centered ranking orders NaN "best") can be poisoned. Identity on
        # all-finite scores, so it defaults ON; quarantined counts surface
        # as eval_nonfinite / eval_nonfinite_share status keys and in the
        # per-group telemetry matrix (max_nonfinite_share SLO rule).
        self._nonfinite_quarantine = bool(nonfinite_quarantine)
        self._nonfinite_penalty = (
            None if nonfinite_penalty is None else float(nonfinite_penalty)
        )
        # search-health plane (docs/observability.md "Search health"): the
        # compiled eval programs append per-group float32 score statistics
        # (count/sum/sumsq/min/max) to the telemetry wire — schema v4.
        # health_telemetry=False compiles the v3 (health-free) programs,
        # the library form of the BENCH_HEALTH=0 byte-compat escape hatch
        self._health_telemetry = bool(health_telemetry)
        # SLO watchdog (observability/slo.py): declarative rules evaluated
        # against each generation's decoded telemetry; verdicts surface as
        # slo_ok / slo_violations status keys (logger columns for free)
        if slo is not None:
            from ..observability.slo import SLOWatchdog

            self._slo = slo if isinstance(slo, SLOWatchdog) else SLOWatchdog(slo)
        else:
            self._slo = None
        # tuned-config cache wiring (observability/timings.py): when the
        # refill / compaction knobs are NOT passed explicitly, eval setup
        # consults the checked-in tuned_configs.json for this
        # (env, popsize, episode length/count, params, dtype, machine) key — the autotuner's
        # measured winners — and falls back to the engines' built-in
        # defaults on a miss. Explicit knobs always win; the branch taken
        # is published as the `tuned_config_source` status key
        # (override / cache / fallback). An env_config-modified env is NOT
        # the env its cache label names (different dynamics, different
        # episode-length distribution), so the cache is skipped for it —
        # a pre-built Env instance with custom ctor args has the same
        # caveat, which the label cannot detect.
        self._env_label = canonical_env_label(env)
        self._tuned_cacheable = not (isinstance(env, str) and env_config)
        self._tuned_resolution: dict = {}
        self._tuned_config_source: Optional[str] = None
        if obs_norm_sync not in ("cohort", "step"):
            raise ValueError(
                f"obs_norm_sync must be 'cohort' or 'step', got {obs_norm_sync!r}"
            )
        self._obs_norm_sync = str(obs_norm_sync)
        # tuning knobs for the lane-compacting runner (chunk_size, min_width,
        # allowed_widths, prewarm); meaningful only with
        # eval_mode="episodes_compact". Widths are GLOBAL population widths:
        # on the sharded path they are divided by the shard count before
        # reaching the (per-shard) runner, so the same config means the same
        # thing whether or not a batch happens to take the mesh path.
        if compact_config is not None:
            allowed = {"chunk_size", "min_width", "allowed_widths", "prewarm"}
            unknown = set(compact_config) - allowed
            if unknown:
                raise ValueError(f"Unknown compact_config keys: {sorted(unknown)}")
        self._compact_config = dict(compact_config or {})
        # prewarm compiles the whole width-descent chain; re-armed per
        # population size so a small warm-up evaluation cannot consume the
        # flag that a later full-population evaluation needed
        self._compact_prewarm = bool(self._compact_config.pop("prewarm", False))
        self._compact_prewarmed_sizes: set = set()
        self._max_num_envs = None if max_num_envs is None else int(max_num_envs)
        # bfloat16 (etc.) policy compute for the MXU fast path
        self._compute_dtype = compute_dtype
        # shared evaluation service (docs/serving.md): with an eval_backend —
        # a serving.RemoteEvalBackend, or a serving.EvalServer to auto-admit
        # into — every rollout dispatch routes through the server's ONE
        # resident multi-tenant program instead of compiling this problem's
        # own; searchers and every consumer downstream of RolloutResult are
        # unaffected. The backend path owns the device program, so it is
        # mutually exclusive with the problem-local mesh request
        # (num_actors) and with solution_groups (the server's group axis IS
        # the tenant axis).
        if eval_backend is not None:
            from ..serving import EvalServer, RemoteEvalBackend

            if isinstance(eval_backend, EvalServer):
                eval_backend = RemoteEvalBackend(eval_backend)
            if not isinstance(eval_backend, RemoteEvalBackend):
                raise TypeError(
                    "eval_backend must be a serving.RemoteEvalBackend or"
                    f" serving.EvalServer, got {type(eval_backend).__name__}"
                )
            if self._solution_groups is not None:
                raise ValueError(
                    "solution_groups cannot combine with eval_backend: the"
                    " server's group axis is the tenant axis"
                )
        self._eval_backend = eval_backend

        self._obs_norm = RunningNorm(self._env.observation_size)
        self._interaction_count = 0
        self._episode_count = 0
        # zero-sync eval telemetry (observability.devicemetrics): the packed
        # device vector of the CURRENT evaluation is only enqueued here; the
        # PREVIOUS one — whose program has retired — is decoded lazily for
        # the status dict (the same lag-by-one device-scalar discipline as
        # basis_capture: the decode is a ~24-byte transfer, never a stall)
        self._pending_telemetry = None
        self._last_telemetry = None
        self._last_group_telemetry = None

        super().__init__(
            "max",
            network,
            network_args=network_args,
            initial_bounds=initial_bounds,
            seed=seed,
            num_actors=num_actors,
            **kwargs,
        )
        self.after_eval_hook.append(self._report_counters)

    # ---------------------------------------------------------------- wiring
    def _network_constants(self) -> dict:
        env = self._env
        return {
            "obs_length": env.observation_size,
            "act_length": env.action_size,
            "obs_shape": tuple(env.observation_space.shape),
            "obs_space": env.observation_space,
            "act_space": env.action_space,
        }

    @property
    def env(self) -> Env:
        return self._env

    @property
    def observation_normalization(self) -> bool:
        return self._observation_normalization

    @property
    def obs_norm(self) -> RunningNorm:
        return self._obs_norm

    @property
    def eval_backend(self):
        """The attached RemoteEvalBackend (None when evaluating locally)."""
        return self._eval_backend

    @property
    def last_group_telemetry(self):
        """The previous generation's decoded per-group telemetry
        (:class:`~evotorch_tpu.observability.GroupTelemetry`; lag-by-one,
        None until telemetry has flowed) — what MetricsHub consumers feed
        to ``emit(..., telemetry=...)``."""
        return self._last_group_telemetry

    def _take_prewarm(self, popsize: int) -> bool:
        """Prewarm once per population size (not once ever): a small warm-up
        evaluation must not consume the prewarm a full-population run needs."""
        if not self._compact_prewarm or popsize in self._compact_prewarmed_sizes:
            return False
        self._compact_prewarmed_sizes.add(popsize)
        return True

    def _tuned_knobs(
        self, group: str, explicit: dict, popsize: int, mesh_label: str = "none"
    ) -> dict:
        """One knob group resolved at eval-setup time with the shared
        precedence rule (``observability.timings.resolve_knobs``):
        explicit config > tuned-config cache hit for this
        (env, popsize, episode length/count, params, dtype, mesh label,
        machine) > the engine's built-in default. Memoized per
        (group, popsize, mesh); the provenance of the LAST resolution is
        what ``tuned_config_source`` reports (shapes are identical
        generation to generation, so it is stable in steady state)."""
        from ..observability.timings import dtype_label

        memo_key = (group, popsize, mesh_label)
        if memo_key not in self._tuned_resolution:
            shape = {
                "env": self._env_label,
                "popsize": popsize,
                # the FULL workload identity is the key: episode
                # length/count set the work-list size and refill
                # frequency; the policy's parameter count + compute dtype
                # set the per-step FLOPs/HBM balance; the mesh label pins
                # the device layout — a schedule tuned for one is not
                # evidence for another
                "episode_length": self._episode_length,
                "num_episodes": self._num_episodes,
                "params": self._policy.parameter_count,
                "dtype": dtype_label(self._compute_dtype),
                "mesh": mesh_label,
            }
            self._tuned_resolution[memo_key] = resolve_knobs(
                explicit, group, shape, use_cache=self._tuned_cacheable
            )
        config, source = self._tuned_resolution[memo_key]
        self._tuned_config_source = source
        return config

    def _compact_kwargs(self, popsize: int) -> dict:
        """The lane-compacting runner's kwargs: explicit compact_config,
        else the tuned cache's (chunk_size, min_width) for this shape."""
        return dict(self._tuned_knobs("compact", self._compact_config, popsize))

    def _sharded_compact_config(
        self, n_shards: int, popsize: int, mesh_label: str = "none"
    ) -> dict:
        """The per-shard form of the (global-width) compact config: widths
        divide by the shard count; chunk_size passes through."""
        cfg = dict(
            self._tuned_knobs("compact", self._compact_config, popsize, mesh_label)
        )
        if cfg.get("min_width") is not None:
            cfg["min_width"] = max(1, int(cfg["min_width"]) // n_shards)
        if cfg.get("allowed_widths") is not None:
            per_shard = sorted({int(w) // n_shards for w in cfg["allowed_widths"] if int(w) >= n_shards})
            cfg["allowed_widths"] = tuple(per_shard)
        return cfg

    def _refill_kwargs(self, popsize: int, n_shards: int = 1) -> dict:
        """Rollout-engine kwargs of the refill scheduler — explicit
        refill_config, else the tuned cache. The (global) lane width
        divides by the shard count, like compact_config's widths —
        flooring, by convention of the convenience knobs (the strict form,
        ``parallel.make_sharded_rollout_evaluator``, raises instead)."""
        cfg = self._tuned_knobs("refill", self._refill_config, popsize)
        kw = {}
        if cfg.get("width") is not None:
            kw["refill_width"] = max(1, int(cfg["width"]) // n_shards)
        if cfg.get("period") is not None:
            kw["refill_period"] = int(cfg["period"])
        return kw

    def _bump_counters(self, steps, episodes):
        # counters accumulate as device scalars: no device->host sync in the
        # hot loop (VERDICT r1 item 6); device_put pins them to one device so
        # rollouts executed on different meshes still add up (async d2d copy)
        dev = jax.devices()[0]
        self._interaction_count = self._interaction_count + jax.device_put(steps, dev)
        self._episode_count = self._episode_count + jax.device_put(episodes, dev)

    def _consume_telemetry(self, telemetry):
        """Enqueue this evaluation's packed telemetry vector and decode the
        previous one (already materialized — see the constructor note).

        A STACKED ``(K, G, C)`` matrix from a fused training span feeds the
        same swap row by row: by the time the span's host fetch happens the
        whole program has retired, so rows ``0..K-2`` decode immediately and
        only the FINAL row stays pending until the next consume — the
        lag-by-one discipline generalized to lag-by-span (docs/observability.md
        "Lag-by-span")."""
        if telemetry is None:
            return
        if getattr(telemetry, "ndim", 0) == 3:
            if telemetry.shape[-1] == 0:  # graftlint: allow(telemetry-schema): width-0 emptiness probe on .shape, not a column read
                return  # stacked telemetry-off wire
            for row in telemetry:
                self._consume_telemetry(row)
            return
        from ..observability import GroupTelemetry

        prev, self._pending_telemetry = self._pending_telemetry, telemetry
        if prev is not None:
            # ONE metered fetch per generation, whatever G is: the per-group
            # matrix is decoded once, and the global figures derive from it
            gt = GroupTelemetry.from_array(prev)
            self._last_group_telemetry = gt
            self._last_telemetry = gt.total()

    def _report_counters(self, batch) -> dict:
        status = {
            "total_interaction_count": self._interaction_count,
            "total_episode_count": self._episode_count,
        }
        if self._last_telemetry is not None:
            # eval_occupancy / eval_refill_events / eval_queue_wait: the
            # previous generation's figures (lag-by-one; shapes are identical
            # generation to generation, so the diagnostics are current)
            status.update(self._last_telemetry.as_status(prefix="eval_"))
            # exact quarantine share: quarantined solutions over the batch
            # size (the telemetry's own denominator is episodes, which
            # differs at num_episodes > 1) — what max_nonfinite_share reads
            status["eval_nonfinite_share"] = float(
                self._last_telemetry.nonfinite
            ) / max(1, len(batch))
        if self._last_group_telemetry is not None:
            # per-group keys (eval_g{g}_occupancy/...), emitted only at G>1
            status.update(self._last_group_telemetry.as_status(prefix="eval_"))
            if self._last_group_telemetry.has_health:
                # search-health plane: previous generation's global score
                # statistics (per-group keys come from as_status at G>1)
                stats = self._last_group_telemetry.score_stats()
                if stats["count"] > 0:
                    status["eval_score_mean"] = round(stats["mean"], 6)
                    status["eval_score_std"] = round(stats["std"], 6)
            if self._slo is not None:
                status.update(
                    self._slo.check(
                        self._last_group_telemetry, status=status
                    ).as_status()
                )
        if self._tuned_config_source is not None:
            # where the schedule knobs came from: "override" (explicit
            # config), "cache" (tuned_configs.json hit) or "fallback"
            # (engine default) — set on the tunable eval modes only
            status["tuned_config_source"] = self._tuned_config_source
        return status

    # ------------------------------------------------------------ evaluation
    def _rollout_batch(self, values: jnp.ndarray, key, groups=None) -> tuple:
        if self._eval_backend is not None:
            return self._eval_backend.evaluate(self, values, key, groups=groups)
        kwargs = dict(
            num_episodes=self._num_episodes,
            episode_length=self._episode_length,
            observation_normalization=self._observation_normalization,
            alive_bonus_schedule=self._alive_bonus_schedule,
            decrease_rewards_by=self._decrease_rewards_by,
            action_noise_stdev=self._action_noise_stdev,
            compute_dtype=self._compute_dtype,
            nonfinite_quarantine=self._nonfinite_quarantine,
            nonfinite_penalty=self._nonfinite_penalty,
            health=self._health_telemetry,
        )
        if groups is not None:
            # num_groups stays the problem-GLOBAL count: sub-batch matrices
            # share the row space, so they stay addable
            kwargs["groups"] = groups
            kwargs["num_groups"] = self._num_groups
        if self._eval_mode == "episodes_compact":
            return run_vectorized_rollout_compacting(
                self._env, self._policy, values, key, self._obs_norm.stats,
                prewarm=self._take_prewarm(_params_popsize(values)),
                **self._compact_kwargs(_params_popsize(values)), **kwargs,
            )
        if self._eval_mode == "episodes_refill":
            kwargs.update(self._refill_kwargs(_params_popsize(values)))
        return run_vectorized_rollout(
            self._env,
            self._policy,
            values,
            key,
            self._obs_norm.stats,
            eval_mode=self._eval_mode,
            **kwargs,
        )

    def _resolve_num_actors_request(self):
        """VecNE honors ``num_actors`` through its own sharded path (the
        generic resolver would warn: there is no plain objective_func)."""

    def _num_actors_mesh(self, popsize: int):
        """Mesh for a pending ``num_actors`` request. The GSPMD evaluator
        pads an indivisible popsize to the next mesh multiple (the padding
        lanes are masked), so the request is honored exactly; the paths
        that still require divisibility (``EVOTORCH_SHARD_MAP=1``, the
        sharded compact runner) step down to the largest dividing shard
        count, as before."""
        from ..parallel.evaluate import _use_shard_map

        request = self._num_actors_requested
        if request is None:
            return None
        if isinstance(request, str):
            if request in ("max", "num_devices", "num_gpus", "num_cpus"):
                n = jax.device_count()
            else:
                raise ValueError(f"Unrecognized num_actors request: {request!r}")
        else:
            n = min(int(request), jax.device_count())
        n = max(1, n)
        if _use_shard_map(None) or self._eval_mode == "episodes_compact":
            while popsize % n != 0:
                n -= 1
        if n <= 1:
            return None
        return default_mesh(("pop",), devices=jax.devices()[:n])

    def _evaluate_batch(self, batch: SolutionBatch):
        # the backend path owns the device program — the local mesh request
        # does not apply through it (the SERVER may be meshed instead)
        mesh = (
            None if self._eval_backend is not None else self._num_actors_mesh(len(batch))
        )
        if mesh is not None:
            self.evaluate_sharded(batch, mesh=mesh)
            return
        values = batch.values
        if not is_factored(values):
            # a factored population (low-rank or trunk-delta) stays factored
            # all the way into the rollout engine — the dense (N, L) matrix
            # is never built
            values = jnp.asarray(values)
        n = len(batch)
        groups = self._check_solution_groups(n)
        if self._max_num_envs is not None and n > self._max_num_envs:
            # workload splitting (reference vecgymne.py:440-455): evaluate in
            # sub-batches of at most max_num_envs environments
            scores = []
            for start in range(0, n, self._max_num_envs):
                stop = min(start + self._max_num_envs, n)
                piece = (
                    values.take(jnp.arange(start, stop))
                    if is_factored(values)
                    else values[start:stop]
                )
                result = self._rollout_batch(
                    piece,
                    self.next_rng_key(),
                    groups=None if groups is None else groups[start:stop],
                )
                scores.append(result.scores)
                self._consume_rollout_side_effects(result)
            batch.set_evals(self._maybe_inject_nonfinite(jnp.concatenate(scores)))
            return
        result = self._rollout_batch(values, self.next_rng_key(), groups=groups)
        self._consume_rollout_side_effects(result)
        batch.set_evals(self._maybe_inject_nonfinite(result.scores))

    def _maybe_inject_nonfinite(self, scores):
        """Deterministic score corruption (docs/resilience.md):
        ``EVOTORCH_FAULTS="eval.scores:nonfinite@G[:share]"`` NaNs a seeded
        share of this generation's scores at the host boundary — the
        reproducible stand-in for diverged physics that the quarantine
        acceptance tests drive. With quarantine enabled the same
        replacement rule the engines compile (worst-finite / fixed
        penalty) is applied to the corrupted vector, so an injected run
        shows exactly what a quarantined diverging run shows."""
        from ..resilience.faults import fault_point

        rule = fault_point("eval.scores")
        if rule is None or rule.kind != "nonfinite":
            return scores
        from ..observability.registry import counters
        from .net.vecrl import _quarantine_nonfinite

        scores = jnp.asarray(scores)
        n = int(scores.shape[0])
        k = max(1, int(round(rule.float_arg(0.25) * n)))
        idx = np.random.default_rng(1234 + rule.count).choice(n, size=min(k, n), replace=False)
        scores = scores.at[jnp.asarray(idx)].set(jnp.nan)
        counters.increment("faults.injected_nonfinite", len(idx))
        if self._nonfinite_quarantine:
            scores, _ = _quarantine_nonfinite(
                scores, penalty=self._nonfinite_penalty
            )
        return scores

    def _check_solution_groups(self, popsize: int):
        """The configured per-solution group ids, validated against the
        batch size (None when per-group accounting is off)."""
        groups = self._solution_groups
        if groups is not None and len(groups) != popsize:
            raise ValueError(
                f"solution_groups maps {len(groups)} solutions but the batch"
                f" holds {popsize}"
            )
        return groups

    def _consume_rollout_side_effects(self, result):
        # counters accumulate as device scalars: the addition enqueues a tiny
        # async op instead of forcing a device->host sync every generation
        # (VERDICT r1 "what's weak" #3); status readers convert lazily
        if self._observation_normalization:
            self._obs_norm.stats = result.stats
        self._bump_counters(result.total_steps, result.total_episodes)
        self._consume_telemetry(result.telemetry)

    # ------------------------------------------------------- policy exports
    def to_policy(self, solution) -> Module:
        """Wrap a solution as a deployable policy module **carrying the
        solution's evolved weights** (a FrozenModule): obs-norm layer (if any
        statistics were collected) + parameterized network + action clipping
        (reference ``gymne.py:646-672`` / ``vecgymne.py:949-1010``)."""
        from .net.layers import FrozenModule

        values = jnp.asarray(solution.values if hasattr(solution, "values") else solution)
        module: Module = FrozenModule(self._net_module, self._policy.unravel(values))
        if self._observation_normalization and self._obs_norm.count >= 2:
            module = self._obs_norm.to_layer() >> module
        space = self._env.action_space
        if not space.is_discrete and space.lb is not None:
            module = module >> ActClipLayer(space.lb, space.ub)
        return module

    def to_policy_callable(self, solution) -> Callable:
        """A ready closure over the solution's parameters (includes obs-norm
        and action clip)."""
        values = jnp.asarray(solution.values if hasattr(solution, "values") else solution)

        def apply(x, state=None):
            y = x
            if self._observation_normalization and self._obs_norm.count >= 2:
                y = self._obs_norm.normalize(y)
            out, new_state = self._policy(values, y, state)
            space = self._env.action_space
            if space.is_discrete:
                out = jnp.argmax(out, axis=-1)
            elif space.lb is not None:
                out = jnp.clip(out, space.lb, space.ub)
            return out, new_state

        return apply

    def save_solution(self, solution, fname: str):
        """Pickle a solution with its policy and obs stats
        (reference ``gymne.py:674-724``)."""
        import pickle

        values = np.asarray(solution.values if hasattr(solution, "values") else solution)
        payload = {
            "values": values,
            "obs_mean": np.asarray(self._obs_norm.mean) if self._obs_norm.count >= 2 else None,
            "obs_stdev": np.asarray(self._obs_norm.stdev) if self._obs_norm.count >= 2 else None,
            "network_spec": self._network_spec if isinstance(self._network_spec, str) else repr(self._network_spec),
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    # ------------------------------------------------- sharded evaluation ---
    def _sharded_rollout_evaluator(self, mesh, axis_name: str):
        """The memoized GSPMD evaluator for this problem on ``mesh``
        (``parallel.make_sharded_rollout_evaluator``). Per-mesh memoization
        matters: the helper's compiled-program cache lives in its closure,
        so rebuilding it every evaluation would retrace every generation."""
        from ..parallel.evaluate import make_sharded_rollout_evaluator

        memo = self.__dict__.setdefault("_sharded_evaluator_memo", {})
        evaluator = memo.get(mesh)
        if evaluator is None:
            kwargs = dict(
                num_episodes=self._num_episodes,
                episode_length=self._episode_length,
                observation_normalization=self._observation_normalization,
                alive_bonus_schedule=self._alive_bonus_schedule,
                decrease_rewards_by=self._decrease_rewards_by,
                action_noise_stdev=self._action_noise_stdev,
                compute_dtype=self._compute_dtype,
                eval_mode=self._eval_mode,
                nonfinite_quarantine=self._nonfinite_quarantine,
                nonfinite_penalty=self._nonfinite_penalty,
                health=self._health_telemetry,
            )
            if self._eval_mode == "episodes_refill":
                # explicit knobs pass through GLOBAL (the helper's
                # convention); with none, the helper consults the
                # tuned-config cache per popsize at this mesh label
                if self._refill_config.get("width") is not None:
                    kwargs["refill_width"] = int(self._refill_config["width"])
                if self._refill_config.get("period") is not None:
                    kwargs["refill_period"] = int(self._refill_config["period"])
            if self._solution_groups is not None:
                # the helper pads the ids alongside the population rows;
                # per-mesh memoization is safe — the mapping is fixed at
                # construction
                kwargs["groups"] = self._solution_groups
                kwargs["num_groups"] = self._num_groups
            evaluator = memo[mesh] = make_sharded_rollout_evaluator(
                self._env,
                self._policy,
                mesh=mesh,
                axis_name=axis_name,
                stats_sync=(
                    self._observation_normalization and self._obs_norm_sync == "step"
                ),
                **kwargs,
            )
        return evaluator

    def evaluate_sharded(self, batch: SolutionBatch, mesh=None, axis_name: str = "pop"):
        """Evaluate with the population axis sharded over the mesh
        (``parallel.make_sharded_rollout_evaluator``): the GSPMD form — one
        global program pinned to the mesh layout, bit-identical to the
        unsharded evaluation, popsizes that don't divide the mesh padded
        and masked, and the obs-norm cohort always mesh-GLOBAL (under
        ``EVOTORCH_SHARD_MAP=1`` the explicit per-shard form returns, with
        its strict divisibility and per-shard cohort semantics — the
        collective analog of the reference's actor delta-sync,
        ``gymne.py:524-573``, SURVEY.md §2.11). The host-orchestrated
        ``episodes_compact`` contract keeps its dedicated sharded runner."""
        if mesh is None:
            mesh = default_mesh((axis_name,))
        n_shards = mesh.shape[axis_name]
        values = batch.values
        is_lowrank = is_factored(values)
        if not is_lowrank:
            values = jnp.asarray(values)
        n = len(batch)

        stats = self._obs_norm.stats
        obsnorm = self._observation_normalization
        groups = self._check_solution_groups(n)
        if self._eval_mode == "episodes_compact":
            from ..parallel.mesh import mesh_label

            if n % n_shards != 0:
                raise ValueError(
                    f"Population size {n} must be divisible by mesh size {n_shards}"
                )
            # the sharded compacting runner: jitted chunks shard_mapped over
            # the mesh, host-side width decisions between chunks — each shard
            # narrows its working set as its lanes finish (VERDICT r3 #5)
            result = run_vectorized_rollout_compacting_sharded(
                self._env,
                self._policy,
                values,
                self.next_rng_key(),
                stats,
                mesh=mesh,
                axis_name=axis_name,
                num_episodes=self._num_episodes,
                episode_length=self._episode_length,
                observation_normalization=obsnorm,
                alive_bonus_schedule=self._alive_bonus_schedule,
                decrease_rewards_by=self._decrease_rewards_by,
                action_noise_stdev=self._action_noise_stdev,
                compute_dtype=self._compute_dtype,
                nonfinite_quarantine=self._nonfinite_quarantine,
                nonfinite_penalty=self._nonfinite_penalty,
                health=self._health_telemetry,
                prewarm=self._take_prewarm(n),
                stats_sync=(obsnorm and self._obs_norm_sync == "step"),
                groups=groups,
                num_groups=self._num_groups if groups is not None else 1,
                **self._sharded_compact_config(n_shards, n, mesh_label(mesh)),
            )
            if obsnorm:
                self._obs_norm.stats = result.stats
            self._bump_counters(result.total_steps, result.total_episodes)
            self._consume_telemetry(result.telemetry)
            batch.set_evals(self._maybe_inject_nonfinite(result.scores))
            self.update_status(self._report_counters(batch))
            return

        evaluator = self._sharded_rollout_evaluator(mesh, axis_name)
        result, _per_shard = evaluator(values, self.next_rng_key(), stats)
        if evaluator.tuned_config_source is not None:
            # the helper resolved the refill knobs (explicit config >
            # tuned cache at this mesh label > engine default): surface
            # its provenance through the usual status key
            self._tuned_config_source = evaluator.tuned_config_source
        if obsnorm:
            self._obs_norm.stats = result.stats
        self._bump_counters(result.total_steps, result.total_episodes)
        self._consume_telemetry(result.telemetry)
        batch.set_evals(self._maybe_inject_nonfinite(result.scores))
        self.update_status(self._report_counters(batch))

    # --------------------------------------------- fused training spans ---
    def make_training_span(
        self,
        *,
        ask,
        tell,
        popsize: int,
        span: int,
        mesh=None,
        donate_state: bool = True,
        state_metrics=None,
    ):
        """A fused K-generation training program for THIS problem
        (``parallel.make_training_span``): ``lax.scan`` over ``span``
        generations of ask → eval → tell in ONE donated GSPMD program,
        carrying the problem's full eval configuration — contract, episode
        shape, obs-norm, quarantine, per-group ids, health telemetry, and
        (for ``episodes_refill``) the tuned/explicit refill knobs resolved
        exactly as the per-generation path resolves them.

        ``ask``/``tell`` are functional-API callables (the OO searcher shells
        hold host state and cannot ride inside the scan). Feed each result
        back through :meth:`consume_span` so the interaction counters, the
        telemetry swap (lag-by-span) and the obs-norm stats keep flowing into
        the status keys. The host-orchestrated ``episodes_compact`` contract
        cannot be fused — the builder raises."""
        from ..parallel.evaluate import make_training_span as _make_span

        popsize = int(popsize)
        kwargs = dict(
            num_episodes=self._num_episodes,
            episode_length=self._episode_length,
            observation_normalization=self._observation_normalization,
            alive_bonus_schedule=self._alive_bonus_schedule,
            decrease_rewards_by=self._decrease_rewards_by,
            action_noise_stdev=self._action_noise_stdev,
            compute_dtype=self._compute_dtype,
            nonfinite_quarantine=self._nonfinite_quarantine,
            nonfinite_penalty=self._nonfinite_penalty,
            health=self._health_telemetry,
            eval_mode=self._eval_mode,
        )
        if self._eval_mode == "episodes_refill":
            kwargs.update(self._refill_kwargs(popsize))
        groups = self._check_solution_groups(popsize)
        if groups is not None:
            kwargs["groups"] = groups
            kwargs["num_groups"] = self._num_groups
        return _make_span(
            self._env,
            self._policy,
            ask=ask,
            tell=tell,
            popsize=popsize,
            span=span,
            mesh=mesh,
            donate_state=donate_state,
            state_metrics=state_metrics,
            **kwargs,
        )

    def consume_span(self, result):
        """Feed one :meth:`make_training_span` result back into the
        problem's host-side accounting: obs-norm statistics, the device-
        scalar interaction/episode counters (the per-generation step counts
        sum ON DEVICE; episodes come from the stacked telemetry's episodes
        column via ``device_episode_total`` — also on device — with a
        host-arithmetic fallback when telemetry is off), and the telemetry
        swap (rows 0..K-2 decode now, the final row stays pending —
        lag-by-span). Returns the stacked ``(span, popsize)`` scores."""
        state, scores, stats, total_steps, telemetry = result[:5]
        if self._observation_normalization:
            self._obs_norm.stats = stats
        span = int(scores.shape[0])
        if telemetry is not None and getattr(telemetry, "size", 0):
            from ..observability.devicemetrics import device_episode_total

            episodes = device_episode_total(telemetry)
        elif self._eval_mode == "budget":
            episodes = 0  # auto-reset episode counts live only in telemetry
        else:
            episodes = int(scores.shape[-1]) * self._num_episodes * span
        self._bump_counters(
            total_steps.sum() if hasattr(total_steps, "sum") else sum(total_steps),
            episodes,
        )
        self._consume_telemetry(telemetry)
        # refresh the status keys; _report_counters only reads len() of its
        # argument (the nonfinite-share denominator), so the final
        # generation's score row stands in for the batch
        self.update_status(self._report_counters(scores[-1]))
        return scores


# the reference's class name, for drop-in familiarity
VecGymNE = VecNE
