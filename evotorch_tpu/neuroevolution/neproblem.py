"""Generic neuroevolution problem: solutions are flat network parameters.

Parity: reference ``neuroevolution/neproblem.py:33-429`` (``NEProblem``) and
``baseneproblem.py:18-27`` (``BaseNEProblem`` marker). The solution length is
the network's parameter count (``neproblem.py:235``); the network spec may be
a string (-> ``str_to_net``), a layer ``Module``, or a callable returning one
(``_instantiate_net``, ``neproblem.py:292-315``), optionally decorated with
``@pass_info`` to receive problem info kwargs.

TPU-first: instead of ``parameterize_net`` filling a cached torch module
(``neproblem.py:342-363``), evaluation is pure — the flat vector is unraveled
inside jit, and when the user's ``network_eval_func`` is jax-pure the whole
population is evaluated in one vmapped program.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from ..core import Problem, SolutionBatch
from ..tools.lowrank import dense_values
from .net.functional import FlatParamsPolicy
from .net.layers import Module
from .net.parser import str_to_net

__all__ = ["BaseNEProblem", "NEProblem"]


class BaseNEProblem(Problem):
    """Marker base (reference ``baseneproblem.py:18``)."""


class NEProblem(BaseNEProblem):
    def __init__(
        self,
        objective_sense,
        network: Union[str, Module, Callable],
        network_eval_func: Optional[Callable] = None,
        *,
        network_args: Optional[dict] = None,
        initial_bounds=(-0.00001, 0.00001),
        eval_dtype=None,
        eval_data_length: int = 0,
        seed: Optional[int] = None,
        num_actors=None,
        vectorized_network_eval: bool = True,
        **kwargs,
    ):
        self._network_spec = network
        self._network_args = dict(network_args or {})
        self._network_eval_func = network_eval_func
        self._vectorized_network_eval = bool(vectorized_network_eval)

        net = self._instantiate_net(network)
        self._net_module = net
        self._policy = FlatParamsPolicy(net)

        super().__init__(
            objective_sense,
            initial_bounds=initial_bounds,
            solution_length=self._policy.parameter_count,
            eval_dtype=eval_dtype,
            eval_data_length=eval_data_length,
            seed=seed,
            num_actors=num_actors,
            **kwargs,
        )

    # ------------------------------------------------------------ networking
    def _network_constants(self) -> dict:
        """Constants injected into ``str_to_net`` strings and ``@pass_info``
        callables (reference ``neproblem.py:262-290``). Subclasses (GymNE
        etc.) extend this with ``obs_length``/``act_length``/..."""
        return {}

    def _instantiate_net(self, network) -> Module:
        constants = self._network_constants()
        if isinstance(network, str):
            return str_to_net(network, **{**constants, **self._network_args})
        if isinstance(network, Module):
            return network
        if callable(network):
            if getattr(network, "__evotorch_pass_info__", False):
                return network(**{**constants, **self._network_args})
            return network(**self._network_args) if self._network_args else network()
        raise TypeError(f"Cannot interpret network specification of type {type(network)}")

    @property
    def network_module(self) -> Module:
        return self._net_module

    @property
    def policy(self) -> FlatParamsPolicy:
        return self._policy

    def make_net(self, solution) -> tuple:
        """Structured parameters for one solution (the analog of the
        reference's instantiated-net copy, ``neproblem.py:323``): returns
        ``(module, params_pytree)``."""
        values = solution.values if hasattr(solution, "values") else solution
        return self._net_module, self._policy.unravel(jnp.asarray(values))

    def parameterize_net(self, values) -> Callable:
        """A ready-to-call ``f(x[, state]) -> y[, state]`` closure over one
        flat parameter vector (reference ``neproblem.py:342-363``)."""
        flat = jnp.asarray(values)

        def apply(x, state=None):
            return self._policy(flat, x, state)

        return apply

    # ------------------------------------------------------------ generation
    def _fill(self, num_solutions: int, key):
        """Initialize solutions near zero (the reference's tiny
        initial_bounds default) unless custom bounds were given."""
        return super()._fill(num_solutions, key)

    # ------------------------------------------------------------ evaluation
    def _evaluate_network(self, flat_params: jnp.ndarray):
        """Fitness of one network, given its flat parameters. Override this,
        or provide ``network_eval_func`` (reference ``neproblem.py:407-429``).
        Must be jax-pure when ``vectorized_network_eval`` (the default)."""
        if self._network_eval_func is None:
            raise NotImplementedError(
                "Provide network_eval_func or override _evaluate_network"
            )
        return self._network_eval_func(self._policy, flat_params)

    def _evaluate_batch(self, batch: SolutionBatch):
        # factored populations densify here: a per-network eval function
        # needs dense parameter vectors (VecNE keeps it factored instead)
        values = jnp.asarray(dense_values(batch.values))
        if self._vectorized_network_eval:
            results = jax.vmap(self._evaluate_network)(values)
            batch.set_evals(*self._split_eval_outputs(results))
        else:
            for sln in batch:
                result = self._evaluate_network(jnp.asarray(sln.values))
                sln.set_evals(result)
