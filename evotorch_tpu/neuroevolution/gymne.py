"""Classical (non-vectorized) gym RL neuroevolution.

Parity: reference ``neuroevolution/gymne.py:64-730`` (``GymNE``): one
gymnasium env, sequential ``_rollout`` loops (``gymne.py:361-414``), online
observation normalization via ``RunningStat`` (``gymne.py:524-573`` — the
actor delta-sync becomes a local update here; multi-device users should use
``VecNE`` instead), interaction/episode counters feeding adaptive popsize
(``gymne.py:594-595``), ``decrease_rewards_by`` / ``alive_bonus_schedule`` /
``action_noise_stdev`` / ``episode_length``, discrete-action argmax
(``gymne.py:343-347``), ``to_policy`` (``gymne.py:646-672``),
``save_solution`` (``gymne.py:674-724``), ``visualize`` (``gymne.py:477``).

This class is deliberately host-side: it exists for parity with gym-API
environments and for debugging policies; the TPU-native throughput path is
``VecNE`` over pure-JAX envs. With ``num_envs > 1`` the evaluation becomes
lane-vectorized (one batched device forward per timestep for a whole lane
block), and for real MuJoCo ``-v5`` envs the lanes are stepped by the batched
``envs.mujoco.MjVecEnv`` engine over ``mujoco.rollout``'s threaded API.
The default ``host_pipeline="pipelined"`` drives the lanes with the
Sebulba-style scheduler (``net.hostvecenv.run_host_pipelined_rollout``):
the whole batch is submitted at once, the device forward for one lane block
overlaps the host physics for the other, and finished lanes are immediately
re-seeded from the batch-wide pending queue — the Podracer split plus
host-side continuous batching (docs/eval_contracts.md, "The host pipeline").
"""

from __future__ import annotations

import pickle
from typing import Callable, Optional, Union

import jax.numpy as jnp
import numpy as np

from .neproblem import NEProblem
from .net.layers import Module
from .net.rl import alive_bonus_for_step_host, reset_env, take_step_in_env
from .net.runningnorm import RunningStat

__all__ = ["GymNE"]


class GymNE(NEProblem):
    def __init__(
        self,
        env: Optional[Union[str, Callable]] = None,
        network: Optional[Union[str, Module, Callable]] = None,
        *,
        env_name: Optional[str] = None,
        network_args: Optional[dict] = None,
        env_config: Optional[dict] = None,
        observation_normalization: bool = False,
        num_episodes: int = 1,
        num_envs: Optional[int] = None,
        episode_length: Optional[int] = None,
        decrease_rewards_by: Optional[float] = None,
        alive_bonus_schedule: Optional[tuple] = None,
        action_noise_stdev: Optional[float] = None,
        initial_bounds=(-0.00001, 0.00001),
        seed: Optional[int] = None,
        num_actors=None,
        vector_env_backend: str = "auto",
        host_pipeline: str = "pipelined",
        host_pipeline_blocks: Optional[int] = None,
        mj_nthread: Optional[int] = None,
        **kwargs,
    ):
        if env is None and env_name is None:
            raise ValueError("Provide `env` (or the legacy `env_name`)")
        self._env_spec = env if env is not None else env_name
        self._env_config = dict(env_config or {})
        self._gym_env = None
        self._observation_normalization = bool(observation_normalization)
        self._num_episodes = int(num_episodes)
        self._episode_length = episode_length
        self._decrease_rewards_by = 0.0 if decrease_rewards_by is None else float(decrease_rewards_by)
        self._alive_bonus_schedule = alive_bonus_schedule
        self._action_noise_stdev = action_noise_stdev
        self._obs_stats = RunningStat()
        self._interaction_count = 0
        self._episode_count = 0
        # num_envs > 1 turns on in-process vectorized evaluation: num_envs env
        # lanes stepped in lockstep with ONE batched device forward per
        # timestep (the reference's VecGymNE-over-"gym::" path,
        # vecgymne.py:744-916 + vecrl.py:1541-1912). The lane engine is
        # chosen by vector_env_backend: "auto" picks the real-MuJoCo batched
        # engine (envs.mujoco.MjVecEnv over mujoco.rollout's threaded API)
        # when the env is a supported -v5 family, else the generic
        # SyncVectorEnv; "mujoco"/"sync" force one or the other.
        self._num_envs = None if num_envs is None else int(num_envs)
        self._vector_env_backend = str(vector_env_backend)
        if self._vector_env_backend not in ("auto", "mujoco", "sync"):
            raise ValueError(
                "vector_env_backend must be 'auto', 'mujoco' or 'sync',"
                f" got {vector_env_backend!r}"
            )
        # host_pipeline picks the scheduler that drives the lanes:
        # "pipelined" (default) — the Sebulba-style two-lane-block scheduler
        # with work-conserving lane refill over the WHOLE batch (device
        # forward for block A overlaps host physics for block B);
        # "sync" — the same scheduler, same event order, no worker thread
        # (bit-identical scores/stats: the determinism baseline);
        # "chunked" — the legacy serial fixed-chunk loop (one
        # run_host_vectorized_rollout per num_envs-sized chunk), kept as the
        # A/B reference the pipeline is benched against.
        self._host_pipeline = str(host_pipeline)
        if self._host_pipeline not in ("pipelined", "sync", "chunked"):
            raise ValueError(
                "host_pipeline must be 'pipelined', 'sync' or 'chunked',"
                f" got {host_pipeline!r}"
            )
        # None = the scheduler's host-adaptive block split (2 when the box
        # has a second core to overlap on, else 1). NOTE: with observation
        # normalization on, the block count sets the obs-stat accumulation
        # grouping, so auto makes scores bitwise host-dependent — pass an
        # explicit count for cross-machine bit-reproducibility.
        self._host_pipeline_blocks = (
            None if host_pipeline_blocks is None else int(host_pipeline_blocks)
        )
        self._mj_nthread = None if mj_nthread is None else int(mj_nthread)
        # host-knob tuning (observability/timings.py): with no explicit
        # host_pipeline_blocks / mj_nthread (and no EVOTORCH_MJ_NTHREAD env
        # override), eval setup consults the machine-scoped "host_pipeline"
        # entry of the tuned-config cache — the autotuner's measured block
        # split / thread-pool width for THIS box — before falling back to
        # the built-in heuristics. Provenance lands in the
        # `tuned_config_source` status key.
        self._tuned_host = None
        self._vec_env = None

        self._make_gym_env()  # early, so network constants are available

        super().__init__(
            "max",
            network,
            network_args=network_args,
            initial_bounds=initial_bounds,
            seed=seed,
            num_actors=num_actors,
            vectorized_network_eval=False,
            **kwargs,
        )
        self.after_eval_hook.append(self._report_counters)

    # --------------------------------------------------------------- the env
    def _build_one_env(self):
        """Resolve the env spec (callable, plain name, or ``"gym::"`` string)
        into a fresh env instance — shared by the serial env and the
        vectorized lanes."""
        import gymnasium as gym

        if callable(self._env_spec):
            return self._env_spec(**self._env_config)
        name = str(self._env_spec)
        if name.startswith("gym::"):
            name = name[len("gym::") :]
        return gym.make(name, **self._env_config)

    def _make_gym_env(self):
        if self._gym_env is None:
            self._gym_env = self._build_one_env()
        return self._gym_env

    @property
    def _env(self):
        return self._make_gym_env()

    def _network_constants(self) -> dict:
        env = self._make_gym_env()
        obs_space = env.observation_space
        act_space = env.action_space
        obs_length = int(np.prod(obs_space.shape))
        if hasattr(act_space, "n"):
            act_length = int(act_space.n)
        else:
            act_length = int(np.prod(act_space.shape))
        return {
            "obs_length": obs_length,
            "act_length": act_length,
            "obs_space": obs_space,
            "act_space": act_space,
            "obs_shape": tuple(obs_space.shape),
        }

    @property
    def observation_normalization(self) -> bool:
        return self._observation_normalization

    def _report_counters(self, batch) -> dict:
        status = {
            "total_interaction_count": self._interaction_count,
            "total_episode_count": self._episode_count,
        }
        if self._tuned_host is not None:
            status["tuned_config_source"] = self._tuned_host[1]
        return status

    def _resolve_host_tuning(self) -> dict:
        """The host-path knobs, resolved once with the shared precedence
        rule (``observability.timings.resolve_knobs``): any explicit ctor
        knob — or the ``EVOTORCH_MJ_NTHREAD`` env override — wins for the
        whole group; else the machine-scoped ``"host_pipeline"`` cache
        entry; else ``{}`` (the scheduler / MjVecEnv heuristics)."""
        if self._tuned_host is None:
            import os

            from ..observability.timings import resolve_knobs

            env_nthread = os.environ.get("EVOTORCH_MJ_NTHREAD", "")
            explicit = {
                "num_blocks": self._host_pipeline_blocks,
                "mj_nthread": (
                    self._mj_nthread
                    if self._mj_nthread is not None
                    else (int(env_nthread) if env_nthread else None)
                ),
            }
            self._tuned_host = resolve_knobs(explicit, "host_pipeline", {})
        return self._tuned_host[0]

    # ------------------------------------------------------------- rollouts
    def _normalize_observation(self, obs, *, update_stats: bool = True) -> np.ndarray:
        obs = np.asarray(obs, dtype=np.float32).reshape(-1)
        if self._observation_normalization:
            if update_stats:
                self._obs_stats.update(obs)
            return np.asarray(self._obs_stats.normalize(obs), dtype=np.float32)
        return obs

    def _action_from_output(self, output: np.ndarray):
        env = self._make_gym_env()
        act_space = env.action_space
        if hasattr(act_space, "n"):
            return int(np.argmax(output))
        action = np.asarray(output, dtype=np.float64).reshape(act_space.shape)
        if self._action_noise_stdev is not None:
            action = action + np.random.randn(*action.shape) * self._action_noise_stdev
        return np.clip(action, act_space.low, act_space.high)

    def _rollout(
        self,
        policy_apply: Callable,
        *,
        update_stats: bool = True,
        visualize: bool = False,
        decrease_rewards_by: Optional[float] = None,
    ) -> dict:
        """One episode (reference ``gymne.py:361-414``)."""
        env = self._make_gym_env()
        decrease = self._decrease_rewards_by if decrease_rewards_by is None else float(decrease_rewards_by)
        obs = self._normalize_observation(reset_env(env), update_stats=update_stats)
        state = None
        cumulative = 0.0
        t = 0
        while True:
            out, state = policy_apply(jnp.asarray(obs), state)
            action = self._action_from_output(np.asarray(out))
            raw_obs, reward, done = take_step_in_env(env, action)
            t += 1
            self._interaction_count += 1
            reward = reward - decrease
            if self._alive_bonus_schedule is not None and not done:
                # host loop, host t: pure-python bonus — the jnp form would
                # dispatch + sync a device scalar every single env step
                reward += alive_bonus_for_step_host(t, self._alive_bonus_schedule)
            cumulative += reward
            if visualize and hasattr(env, "render"):
                env.render()
            obs = self._normalize_observation(raw_obs, update_stats=update_stats)
            if done or (self._episode_length is not None and t >= int(self._episode_length)):
                break
        self._episode_count += 1
        return {"cumulative_reward": cumulative, "interaction_count": t}

    def _evaluate_network(self, flat_params):
        apply = self.parameterize_net(flat_params)
        total = 0.0
        for _ in range(self._num_episodes):
            total += self._rollout(apply)["cumulative_reward"]
        return jnp.asarray(total / self._num_episodes)

    # --------------------------------------- in-process vectorized evaluation
    def _make_vector_env(self):
        if self._vec_env is not None:
            return self._vec_env
        # explicit mj_nthread / EVOTORCH_MJ_NTHREAD, else the tuned cache's
        # machine entry, else None (MjVecEnv's saturate-the-machine default)
        nthread = self._resolve_host_tuning().get("mj_nthread")
        backend = self._vector_env_backend
        if backend in ("auto", "mujoco"):
            try:
                from ..envs.mujoco import make_host_vector_env
                from ..envs.mujoco.mjvecenv import MjVecEnv

                if backend == "mujoco":
                    self._vec_env = MjVecEnv(
                        self._build_one_env, self._num_envs, nthread=nthread
                    )
                else:
                    self._vec_env = make_host_vector_env(
                        self._build_one_env, self._num_envs, nthread=nthread
                    )
                return self._vec_env
            except ImportError:
                if backend == "mujoco":
                    raise  # explicitly requested; don't silently degrade
        from .net.hostvecenv import SyncVectorEnv

        self._vec_env = SyncVectorEnv(self._build_one_env, self._num_envs)
        return self._vec_env

    def _evaluate_batch(self, batch):
        if self._num_envs is None or self._num_envs <= 1:
            return super()._evaluate_batch(batch)
        vec_env = self._make_vector_env()
        values = jnp.asarray(batch.values)
        obs_stats = self._obs_stats if self._observation_normalization else None
        common = dict(
            num_episodes=self._num_episodes,
            episode_length=self._episode_length,
            obs_stats=obs_stats,
            decrease_rewards_by=self._decrease_rewards_by,
            alive_bonus_schedule=self._alive_bonus_schedule,
            action_noise_stdev=self._action_noise_stdev,
        )
        if self._host_pipeline == "chunked":
            # legacy PR-2 path: serial fixed-size chunks, each padded to its
            # slowest episode — the A/B baseline for the pipelined scheduler
            from .net.hostvecenv import run_host_vectorized_rollout

            n = values.shape[0]
            scores = []
            for start in range(0, n, self._num_envs):
                result = run_host_vectorized_rollout(
                    vec_env, self._policy, values[start : start + self._num_envs], **common
                )
                scores.append(result["scores"])
                self._interaction_count += result["interactions"]
                self._episode_count += result["episodes"]
            batch.set_evals(jnp.asarray(np.concatenate(scores), dtype=jnp.float32))
            return
        # whole-batch submission: every (solution, episode) item goes into one
        # pending queue and freed lanes are re-seeded immediately, so a long
        # episode stalls one lane, not a whole chunk
        from .net.hostvecenv import HungPhysicsWorkerError, run_host_pipelined_rollout

        try:
            result = run_host_pipelined_rollout(
                vec_env,
                self._policy,
                values,
                mode=self._host_pipeline,
                num_blocks=self._resolve_host_tuning().get("num_blocks"),
                # the group was resolved HERE (explicit > cache > fallback,
                # one rule for blocks AND nthread together) — the scheduler
                # must not re-consult the cache at its own altitude, and the
                # result dict carries THIS resolution's provenance
                use_tuned_cache=False,
                tuned_config_source=self._tuned_host[1],
                **common,
            )
        except HungPhysicsWorkerError:
            # the physics worker thread is still alive inside this vec_env (a
            # hung native step): closing under a running thread could crash,
            # so just drop the reference and never reuse it
            self._vec_env = None
            raise
        except BaseException:
            # a failed evaluation leaves env lanes mid-episode: close the
            # vec_env (its worker exited cleanly) and build a fresh one next
            # time rather than leaking gym envs / native MuJoCo buffers
            self._vec_env = None
            try:
                vec_env.close()
            except Exception:  # graftlint: allow(swallow): best-effort cleanup while already re-raising the eval failure
                pass
            raise
        self._interaction_count += result["interactions"]
        self._episode_count += result["episodes"]
        batch.set_evals(jnp.asarray(result["scores"], dtype=jnp.float32))

    def run_solution(self, solution, *, num_episodes: int = 1, visualize: bool = False) -> float:
        """Deterministically run a solution (no stat updates)."""
        values = solution.values if hasattr(solution, "values") else solution
        apply = self.parameterize_net(jnp.asarray(values))
        total = 0.0
        for _ in range(int(num_episodes)):
            total += self._rollout(apply, update_stats=False, visualize=visualize, decrease_rewards_by=0.0)[
                "cumulative_reward"
            ]
        return total / num_episodes

    def visualize(self, solution, *, num_episodes: int = 1) -> float:
        """Render a solution's episodes (reference ``gymne.py:477``)."""
        return self.run_solution(solution, num_episodes=num_episodes, visualize=True)

    # ------------------------------------------------------- policy exports
    def to_policy(self, solution) -> Module:
        """Deployable module **carrying the solution's evolved weights**:
        obs-norm + parameterized network + action clip
        (reference ``gymne.py:646-672``)."""
        from .net.layers import FrozenModule
        from .net.rl import ActClipLayer, ObsNormLayer

        values = jnp.asarray(solution.values if hasattr(solution, "values") else solution)
        module: Module = FrozenModule(self._net_module, self._policy.unravel(values))
        if self._observation_normalization and self._obs_stats.count >= 2:
            module = (
                ObsNormLayer(mean=self._obs_stats.mean, stdev=self._obs_stats.stdev)
                >> module
            )
        env = self._make_gym_env()
        act_space = env.action_space
        if not hasattr(act_space, "n"):
            module = module >> ActClipLayer(act_space.low, act_space.high)
        return module

    def get_observation_stats(self) -> RunningStat:
        return self._obs_stats

    def set_observation_stats(self, stats: RunningStat):
        self._obs_stats = stats

    def save_solution(self, solution, fname: str):
        """Pickle the solution values + obs stats + network spec
        (reference ``gymne.py:674-724``)."""
        values = np.asarray(solution.values if hasattr(solution, "values") else solution)
        payload = {
            "values": values,
            "obs_count": self._obs_stats.count,
            "obs_mean": None if self._obs_stats.count < 2 else self._obs_stats.mean,
            "obs_stdev": None if self._obs_stats.count < 2 else self._obs_stats.stdev,
            "network_spec": self._network_spec if isinstance(self._network_spec, str) else repr(self._network_spec),
            "env_spec": self._env_spec if isinstance(self._env_spec, str) else repr(self._env_spec),
        }
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    # ------------------- host-pool sync protocol (reference gymne.py:524-573)
    def _make_sync_data_for_actors(self):
        if not self._observation_normalization:
            return None
        return {"obs_stats": self._obs_stats}

    def _use_sync_data_from_main(self, data: dict):
        # worker-side: adopt the broadcast stats and remember the baseline so
        # only the *delta* collected during this round is sent home
        import copy

        self._obs_stats = copy.deepcopy(data["obs_stats"])
        self._stats_at_sync = copy.deepcopy(self._obs_stats)

    def _make_sync_data_for_main(self) -> dict:
        data = {
            "interactions": self._interaction_count,
            "episodes": self._episode_count,
        }
        # worker-side counters reset after reporting: each round reports a delta
        self._interaction_count = 0
        self._episode_count = 0
        if self._observation_normalization:
            baseline = getattr(self, "_stats_at_sync", None)
            if baseline is None:
                data["obs_delta"] = self._obs_stats
            else:
                data["obs_delta"] = self._obs_stats.to_delta(baseline)
        return data

    def _use_sync_data_from_actors(self, data_list):
        for data in data_list:
            self._interaction_count += int(data.get("interactions", 0))
            self._episode_count += int(data.get("episodes", 0))
            delta = data.get("obs_delta")
            if delta is not None:
                self._obs_stats.update(delta)

    def _get_cloned_state(self, *, memo: dict) -> dict:
        state = super()._get_cloned_state(memo=memo)
        state["_gym_env"] = None  # env handles are not picklable
        state["_vec_env"] = None
        return state
