"""Supervised neuroevolution: minibatch loss as fitness.

Parity: reference ``neuroevolution/supervisedne.py:30-348`` (``SupervisedNE``):
the fitness of a network is its loss on the next minibatch; one common
minibatch is shared by the whole population per evaluation round
(``minibatch_size``, ``num_minibatches``).

TPU-first: the dataset lives on device as arrays; the per-population
evaluation is a single vmapped forward + loss, hitting the MXU with a
``(popsize, batch, features)`` batched matmul instead of a per-network loop.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ..core import SolutionBatch
from .neproblem import NEProblem

__all__ = ["SupervisedNE", "mse_loss", "cross_entropy_loss"]


def mse_loss(pred, target):
    return jnp.mean((pred - target) ** 2)


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    if labels.ndim == logits.ndim:
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))
    return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], axis=-1))


class SupervisedNE(NEProblem):
    def __init__(
        self,
        dataset: Union[Tuple, "object"],
        network,
        loss_func: Optional[Callable] = None,
        *,
        network_args: Optional[dict] = None,
        initial_bounds=(-0.00001, 0.00001),
        minibatch_size: Optional[int] = None,
        num_minibatches: Optional[int] = None,
        seed: Optional[int] = None,
        num_actors=None,
        common_minibatch: bool = True,
        **kwargs,
    ):
        # dataset: (inputs, targets) arrays, or any object with such a pair
        if isinstance(dataset, tuple) and len(dataset) == 2:
            inputs, targets = dataset
        else:
            raise TypeError(
                "dataset is expected as a pair (inputs, targets) of arrays "
                "(torch DataLoaders have no TPU-resident equivalent; convert "
                "your data to arrays first)"
            )
        self._inputs = jnp.asarray(inputs)
        self._targets = jnp.asarray(targets)
        if self._inputs.shape[0] != self._targets.shape[0]:
            raise ValueError("inputs and targets must have the same leading length")
        self._dataset_size = int(self._inputs.shape[0])
        self._minibatch_size = (
            int(minibatch_size) if minibatch_size is not None else min(64, self._dataset_size)
        )
        self._num_minibatches = int(num_minibatches) if num_minibatches is not None else 1
        self._common_minibatch = bool(common_minibatch)
        self._loss_func = loss_func if loss_func is not None else mse_loss

        super().__init__(
            "min",
            network,
            network_args=network_args,
            initial_bounds=initial_bounds,
            seed=seed,
            num_actors=num_actors,
            **kwargs,
        )

    @property
    def minibatch_size(self) -> int:
        return self._minibatch_size

    def _sample_minibatch(self, key):
        idx = jax.random.randint(key, (self._minibatch_size,), 0, self._dataset_size)
        return self._inputs[idx], self._targets[idx]

    def loss(self, pred, target):
        return self._loss_func(pred, target)

    def _evaluate_network_on(self, flat_params, x, y):
        pred, _ = self._policy(flat_params, x)
        return self._loss_func(pred, y)

    def _evaluate_batch(self, batch: SolutionBatch):
        values = jnp.asarray(batch.values)
        total = None
        for _ in range(self._num_minibatches):
            x, y = self._sample_minibatch(self.next_rng_key())
            losses = jax.vmap(lambda p: self._evaluate_network_on(p, x, y))(values)
            total = losses if total is None else total + losses
        batch.set_evals(total / self._num_minibatches)
