"""Observability: loggers attaching to a SearchAlgorithm's log hook.

Parity: reference ``logging.py`` (748 LoC) — ``Logger`` base
(``logging.py:92-107``), ``StdOutLogger`` (``logging.py:428``),
``PandasLogger`` (``logging.py:479``), ``PicklingLogger``
(``logging.py:110-417``), ``ScalarLogger`` filtering (``logging.py:419-426``),
and optional ``MlflowLogger``/``NeptuneLogger``/``SacredLogger``/
``WandbLogger`` (``logging.py:525-748``; import-gated here since those
packages are not baked into the TPU image).
"""

from __future__ import annotations

import os
import pickle
import weakref
from datetime import datetime
from numbers import Number
from typing import Any, Optional

import numpy as np

__all__ = [
    "Logger",
    "ScalarLogger",
    "StdOutLogger",
    "PandasLogger",
    "PicklingLogger",
    "MlflowLogger",
    "NeptuneLogger",
    "SacredLogger",
    "WandbLogger",
]


class Logger:
    """Base logger: attaches itself to ``searcher.log_hook``
    (reference ``logging.py:92``)."""

    def __init__(self, searcher, *, interval: int = 1, after_first_step: bool = False):
        searcher.log_hook.append(self)
        self._interval = int(interval)
        self._after_first_step = bool(after_first_step)
        self._steps_count = 0

    def __call__(self, status: dict):
        if self._after_first_step:
            n = self._steps_count
            self._steps_count += 1
        else:
            self._steps_count += 1
            n = self._steps_count
        if n % self._interval == 0:
            self._filtered_log(status)

    def _filter(self, status: dict) -> dict:
        return status

    def _filtered_log(self, status: dict):
        self._log(self._filter(status))

    def _log(self, status: dict):
        raise NotImplementedError


class ScalarLogger(Logger):
    """Keeps only scalar-valued status items (reference ``logging.py:419``)."""

    def _filter(self, status: dict) -> dict:
        result = {}
        for k, v in status.items():
            if isinstance(v, (Number, str, bool, type(None))):
                result[k] = v
            elif hasattr(v, "ndim") and getattr(v, "ndim", None) == 0:
                result[k] = float(v)
        return result


class StdOutLogger(ScalarLogger):
    """Prints the status to stdout (reference ``logging.py:428``)."""

    def __init__(
        self,
        searcher,
        *,
        interval: int = 1,
        after_first_step: bool = False,
        leading_keys: tuple = ("iter",),
    ):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        self._leading_keys = tuple(leading_keys)

    def _log(self, status: dict):
        max_key_len = max((len(str(k)) for k in status), default=0)
        parts = []
        for k in self._leading_keys:
            if k in status:
                parts.append((k, status[k]))
        for k, v in status.items():
            if k not in self._leading_keys:
                parts.append((k, v))
        for k, v in parts:
            print(f"{str(k):>{max_key_len}} : {v}")
        print()


class PandasLogger(ScalarLogger):
    """Accumulates the status into a pandas DataFrame
    (reference ``logging.py:479``)."""

    def __init__(self, searcher, *, interval: int = 1, after_first_step: bool = False):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        self._data = []

    def _log(self, status: dict):
        self._data.append(dict(status))

    def to_dataframe(self, *, index: Optional[str] = "iter"):
        import pandas as pd

        frame = pd.DataFrame(self._data)
        if index is not None and index in frame.columns:
            frame = frame.set_index(index)
        return frame


class PicklingLogger(Logger):
    """Periodically pickles the latest status (and optionally the searcher's
    decision-making state) to disk — the reference's checkpointing mechanism
    (``logging.py:110-417``)."""

    def __init__(
        self,
        searcher,
        *,
        interval: int,
        directory: Optional[str] = None,
        prefix: Optional[str] = None,
        zfill: int = 6,
        items_to_save: tuple = ("center", "best", "pop_best", "median_eval", "mean_eval"),
        make_policy_from: Optional[str] = None,
        after_first_step: bool = False,
        verbose: bool = True,
    ):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        self._searcher_ref = weakref.ref(searcher)
        self._directory = directory if directory is not None else os.getcwd()
        os.makedirs(self._directory, exist_ok=True)
        if prefix is None:
            prefix = "search_" + datetime.now().strftime("%Y%m%d_%H%M%S")
        self._prefix = prefix
        self._zfill = int(zfill)
        self._items_to_save = tuple(items_to_save)
        self._make_policy_from = make_policy_from
        self._verbose = bool(verbose)
        self._last_file: Optional[str] = None
        searcher.end_of_run_hook.append(self._final_save)

    @property
    def last_file_name(self) -> Optional[str]:
        return self._last_file

    def _log(self, status: dict):
        self.save(status)

    def _final_save(self, status: dict):
        self.save(status)

    def save(self, status: Optional[dict] = None) -> str:
        searcher = self._searcher_ref()
        if status is None and searcher is not None:
            status = dict(searcher.status.items())
        payload = {}
        for item in self._items_to_save:
            if status is not None and item in status:
                payload[item] = _picklable(status[item])
        if searcher is not None:
            payload["iter"] = searcher.step_count
            problem = searcher.problem
            # to_policy support (e.g. GymNE problems; reference logging.py:300)
            policy_source = self._make_policy_from
            if policy_source is None:
                for candidate in ("center", "best", "pop_best"):
                    if candidate in payload:
                        policy_source = candidate
                        break
            if (
                policy_source is not None
                and policy_source in payload
                and hasattr(problem, "to_policy")
            ):
                try:
                    payload["policy"] = problem.to_policy(payload[policy_source])
                except Exception:  # graftlint: allow(swallow): policy attachment is optional decoration of the pickle payload
                    pass
        fname = os.path.join(
            self._directory,
            f"{self._prefix}_generation{str(payload.get('iter', 0)).zfill(self._zfill)}.pickle",
        )
        with open(fname, "wb") as f:
            pickle.dump(payload, f)
        self._last_file = fname
        if self._verbose:
            print(f"[PicklingLogger] saved {fname}")
        return fname

    def unpickle_last_file(self):
        with open(self._last_file, "rb") as f:
            return pickle.load(f)


def _picklable(x: Any) -> Any:
    try:
        import jax

        if isinstance(x, jax.Array):
            return np.asarray(x)
    except Exception:  # graftlint: allow(swallow): probe: without a working jax the raw object is the right fallback
        pass
    return x


class MlflowLogger(ScalarLogger):
    """Logs scalars to MLflow (reference ``logging.py:525``)."""

    def __init__(self, searcher, client=None, run=None, *, interval: int = 1, after_first_step: bool = False):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        import mlflow  # noqa: F401 — gated import

        self._client = client
        self._run = run

    def _log(self, status: dict):
        import mlflow

        step = status.get("iter", self._steps_count)
        metrics = {k: float(v) for k, v in status.items() if isinstance(v, Number)}
        if self._client is not None and self._run is not None:
            for k, v in metrics.items():
                self._client.log_metric(self._run.info.run_id, k, v, step=step)
        else:
            mlflow.log_metrics(metrics, step=step)


class NeptuneLogger(ScalarLogger):
    """Logs scalars to Neptune (reference ``logging.py:585``)."""

    def __init__(self, searcher, run, *, interval: int = 1, after_first_step: bool = False, group: Optional[str] = None):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        self._run = run
        self._group = group

    def _log(self, status: dict):
        for k, v in status.items():
            if isinstance(v, Number):
                target = k if self._group is None else f"{self._group}/{k}"
                self._run[target].log(v)


class SacredLogger(ScalarLogger):
    """Logs scalars to a Sacred run (reference ``logging.py:645``)."""

    def __init__(self, searcher, run, result: Optional[str] = None, *, interval: int = 1, after_first_step: bool = False):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        self._run = run
        self._result = result

    def _log(self, status: dict):
        step = status.get("iter", self._steps_count)
        for k, v in status.items():
            if isinstance(v, Number):
                self._run.log_scalar(k, float(v), step)
        if self._result is not None and self._result in status:
            self._run.result = float(status[self._result])


class WandbLogger(ScalarLogger):
    """Logs scalars to Weights & Biases (reference ``logging.py:700``)."""

    def __init__(self, searcher, init: bool = True, *, interval: int = 1, after_first_step: bool = False, **wandb_kwargs):
        super().__init__(searcher, interval=interval, after_first_step=after_first_step)
        import wandb  # noqa: F401 — gated import

        self._wandb = wandb
        if init:
            self._wandb.init(**wandb_kwargs)

    def _log(self, status: dict):
        metrics = {k: float(v) for k, v in status.items() if isinstance(v, Number)}
        self._wandb.log(metrics)
