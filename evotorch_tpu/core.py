"""Core runtime (L2): ``Problem``, ``SolutionBatch``, ``Solution``,
``ProblemBoundEvaluator``.

Parity: reference ``core.py`` (5257 LoC) — the ``Problem`` abstraction
(``core.py:365-3410``), ``SolutionBatch`` population container
(``core.py:3590-4600``), ``Solution`` row view (``core.py:4742-5106``),
``SolutionBatchPieces`` (``core.py:4603-4727``) and the callable-evaluator
factory (``core.py:3309``, ``core.py:5109-5257``).

TPU-first redesign notes:

- **No Ray layer.** The reference's ``EvaluationActor`` / ``ActorPool``
  machinery (``core.py:115-356``, ``core.py:1977-2052``) is replaced by SPMD
  over the device mesh: see ``evotorch_tpu.parallel``. ``num_actors`` is
  accepted for API compatibility and interpreted as a request for sharded
  evaluation over the available devices. The actor RPC surface
  (``all_remote_problems``/``all_remote_envs``, ``core.py:273-356``) has no
  equivalent and is intentionally dropped (SURVEY.md §5).
- **Immutability discipline.** jax.Arrays cannot be mutated in place, so
  ``SolutionBatch`` is a host-side *container* of immutable arrays: slicing
  produces pieces that remember their parent and scatter evaluation results
  back by index (replacing the reference's shared-storage views,
  ``core.py:3641-3786``). ``access_values`` returns the values array and
  clears the evals (same invalidation semantics as ``core.py:4166-4194``);
  writing back goes through ``set_values``.
- **PRNG**: per-problem JAX key chain replaces torch Generators
  (``manual_seed``, ``core.py:1616``).
- Evaluation results are ``(N, n_obj + eval_data_length)`` with NaN meaning
  "not evaluated", exactly like the reference.
"""

from __future__ import annotations

import functools
import math
import pickle
from typing import Any, Callable, Iterable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .operators.functional import pareto_ranks, pareto_utility
from .tools.cloning import Serializable, deep_clone
from .tools.hook import Hook
from .tools.lazyreporter import LazyReporter
from .tools.lowrank import LowRankParamsBatch, dense_values, is_factored
from .tools.misc import (
    ensure_array_length_and_dtype,
    is_dtype_bool,
    is_dtype_object,
    to_jax_dtype,
)
from .tools.objectarray import ObjectArray
from .tools.ranking import rank
from .tools.recursiveprintable import RecursivePrintable
from .tools.tensormaker import TensorMakerMixin

__all__ = [
    "Problem",
    "Solution",
    "SolutionBatch",
    "SolutionBatchPieces",
    "ProblemBoundEvaluator",
]

ObjectiveSense = Union[str, Iterable[str]]
BoundsPair = Any


def _normalize_senses(objective_sense: ObjectiveSense) -> List[str]:
    if isinstance(objective_sense, str):
        senses = [objective_sense]
    else:
        senses = list(objective_sense)
    for s in senses:
        if s not in ("min", "max"):
            raise ValueError(f"Invalid objective sense: {s!r} (expected 'min' or 'max')")
    if len(senses) == 0:
        raise ValueError("At least one objective sense is required")
    return senses


def _as_int(x) -> int:
    """Host int from a (possibly device-resident) counter status value."""
    return int(x)


@functools.partial(jax.jit, static_argnames=("senses",))
def _batch_extremes(values, evdata, senses):
    """Per-objective best/worst rows of ONE batch, computed on the batch's
    own placement (sharded or not) so only ``K`` winner rows ever move
    between devices. Returns ``(K, L)``/``(K, W)`` stacks for best and worst;
    an all-NaN column yields a NaN eval row (ignored by the merge)."""
    bvs, bes, wvs, wes = [], [], [], []
    for i, sense in enumerate(senses):
        col = evdata[:, i]
        valid = ~jnp.isnan(col)
        any_valid = jnp.any(valid)
        for extreme_is_max, (vs, es) in (
            (sense == "max", (bvs, bes)),
            (sense != "max", (wvs, wes)),
        ):
            masked = jnp.where(valid, col, -jnp.inf if extreme_is_max else jnp.inf)
            idx = jnp.argmax(masked) if extreme_is_max else jnp.argmin(masked)
            vs.append(values[idx])
            es.append(jnp.where(any_valid, evdata[idx], jnp.full_like(evdata[idx], jnp.nan)))
    return jnp.stack(bvs), jnp.stack(bes), jnp.stack(wvs), jnp.stack(wes)


@functools.partial(jax.jit, static_argnames=("senses",))
def _merge_snapshots(bv, be, wv, we, cbv, cbe, cwv, cwe, senses):
    """Fold one batch's candidate extreme rows into the running snapshots —
    tiny ``(K, L)``/``(K, W)`` arrays, one fused program, no host round-trip."""

    def fold(cur_v, cur_e, cand_v, cand_e, i, higher_better):
        cand = cand_e[i]
        cur = cur_e[i]
        if higher_better:
            improved = jnp.isnan(cur) | (cand > cur)
        else:
            improved = jnp.isnan(cur) | (cand < cur)
        take = ~jnp.isnan(cand) & improved
        return jnp.where(take, cand_v, cur_v), jnp.where(take, cand_e, cur_e)

    for i, sense in enumerate(senses):
        hb = sense == "max"
        nbv, nbe = fold(bv[i], be[i], cbv[i], cbe[i], i, hb)
        nwv, nwe = fold(wv[i], we[i], cwv[i], cwe[i], i, not hb)
        bv = bv.at[i].set(nbv)
        be = be.at[i].set(nbe)
        wv = wv.at[i].set(nwv)
        we = we.at[i].set(nwe)
    return bv, be, wv, we


class Problem(TensorMakerMixin, LazyReporter, Serializable, RecursivePrintable):
    """The central problem abstraction (reference ``core.py:365``).

    A Problem declares objective sense(s), decision-variable dtype/length/
    bounds, and an evaluation procedure — either a fitness function passed as
    ``objective_func`` (mark it ``@vectorized``/``@rowwise`` for the fast
    batched path) or an overridden ``_evaluate``/``_evaluate_batch``.

    Status (``problem.status``) is LAZY: best/worst solutions are tracked as
    device arrays by a jitted merge and only materialized (device->host) when
    a status entry is actually read — the OO hot loop therefore runs without
    per-generation host syncs (VERDICT r1 "what's weak" #3).

    ``num_actors`` with a non-traceable objective spawns a host worker pool
    whose evaluations are bounded by a **per-piece inactivity timeout of
    1800 s by default** (a hung worker raises instead of deadlocking the
    generation; the clock resets on every completed piece). Evaluations
    whose single pieces legitimately exceed 30 minutes should construct
    ``parallel.hostpool.HostEvaluatorPool`` with a larger/None ``timeout``.
    """

    def __init__(
        self,
        objective_sense: ObjectiveSense,
        objective_func: Optional[Callable] = None,
        *,
        initial_bounds: Optional[BoundsPair] = None,
        bounds: Optional[BoundsPair] = None,
        solution_length: Optional[int] = None,
        dtype: Any = None,
        eval_dtype: Any = None,
        device: Any = None,
        eval_data_length: int = 0,
        seed: Optional[int] = None,
        num_actors: Optional[Union[int, str]] = None,
        num_gpus_per_actor: Optional[Union[int, float, str]] = None,
        num_subbatches: Optional[int] = None,
        subbatch_size: Optional[int] = None,
        store_solution_stats: Optional[bool] = None,
        vectorized: Optional[bool] = None,
    ):
        self._senses = _normalize_senses(objective_sense)
        self._objective_func = objective_func

        # dtype resolution (reference core.py:1001-1034)
        self._dtype = to_jax_dtype(dtype) if dtype is not None else jnp.float32
        if eval_dtype is not None:
            self._eval_dtype = to_jax_dtype(eval_dtype)
        else:
            self._eval_dtype = jnp.float32
        if is_dtype_object(self._eval_dtype):
            raise ValueError("eval_dtype cannot be object")

        self._eval_data_length = int(eval_data_length)
        self._device = device  # accepted for compatibility; placement is via shardings

        # solution length & bounds (reference core.py:1042-1158)
        if is_dtype_object(self._dtype):
            if solution_length is not None:
                raise ValueError("solution_length must be None when dtype is object")
            if initial_bounds is not None or bounds is not None:
                raise ValueError("bounds are not supported when dtype is object")
            self.solution_length = None
            self._bounds_are_strict = False
            self._lower_bounds = None
            self._upper_bounds = None
            self._initial_lower_bounds = None
            self._initial_upper_bounds = None
        else:
            if solution_length is None:
                raise ValueError("solution_length is required for non-object dtypes")
            self.solution_length = int(solution_length)
            self._bounds_are_strict = bounds is not None
            if bounds is not None and initial_bounds is None:
                initial_bounds = bounds
            self._lower_bounds, self._upper_bounds = self._process_bounds(bounds)
            self._initial_lower_bounds, self._initial_upper_bounds = self._process_bounds(initial_bounds)

        # evaluation vectorization flag
        if vectorized is None:
            vectorized = bool(
                objective_func is not None and getattr(objective_func, "__evotorch_vectorized__", False)
            )
        self._vectorized = bool(vectorized)

        # PRNG chain (replaces torch Generator; reference core.py:1616)
        self._seed = 0 if seed is None else int(seed)
        self._rng_key = jax.random.key(self._seed)

        # sharded-evaluation request (replaces actor config; reference core.py:1302-1595)
        self._num_actors_requested = num_actors
        if num_subbatches is not None and subbatch_size is not None:
            # mutual exclusion, matching the reference (core.py:1288-1293)
            raise ValueError("Provide at most one of num_subbatches / subbatch_size")
        if num_subbatches is not None and int(num_subbatches) < 1:
            raise ValueError(f"num_subbatches must be >= 1, got {num_subbatches}")
        if subbatch_size is not None and int(subbatch_size) < 1:
            raise ValueError(f"subbatch_size must be >= 1, got {subbatch_size}")
        self._num_subbatches = num_subbatches
        self._subbatch_size = subbatch_size
        self._sharded_evaluator = None
        self._eval_mesh = None  # mesh backing the sharded evaluator, if any
        self._eval_axis_name = "pop"
        self._sharded_grad_cache: dict = {}
        self._host_pool = None  # multiprocessing pool for host-side objectives
        self._is_main = True

        # solution stats (reference core.py:2334)
        self._store_solution_stats = True if store_solution_stats is None else bool(store_solution_stats)
        self._best: Optional[List[Optional["Solution"]]] = None  # object-dtype path
        self._worst: Optional[List[Optional["Solution"]]] = None
        self._best_snapshot = None  # device-side (values (K,L), evals (K,W))
        self._worst_snapshot = None

        # hooks (reference core.py:2176-2237)
        self.before_eval_hook: Hook = Hook()
        self.after_eval_hook: Hook = Hook()
        self.before_grad_hook: Hook = Hook()
        self.after_grad_hook: Hook = Hook()

        self._prepared = False
        LazyReporter.__init__(self)

    # ------------------------------------------------------------------ info
    @property
    def senses(self) -> List[str]:
        return list(self._senses)

    @property
    def objective_sense(self) -> Union[str, List[str]]:
        return self._senses[0] if len(self._senses) == 1 else list(self._senses)

    @property
    def is_multi_objective(self) -> bool:
        return len(self._senses) > 1

    @property
    def num_objectives(self) -> int:
        return len(self._senses)

    @property
    def dtype(self):
        return self._dtype

    @property
    def eval_dtype(self):
        return self._eval_dtype

    @property
    def device(self):
        return self._device

    @property
    def eval_data_length(self) -> int:
        return self._eval_data_length

    @property
    def lower_bounds(self):
        return self._lower_bounds

    @property
    def upper_bounds(self):
        return self._upper_bounds

    @property
    def initial_lower_bounds(self):
        return self._initial_lower_bounds

    @property
    def initial_upper_bounds(self):
        return self._initial_upper_bounds


    @property
    def is_main(self) -> bool:
        """False inside a host-pool worker process (reference actors'
        ``is_main`` semantics); True in the main program — the SPMD mesh path
        never leaves the main process."""
        return getattr(self, "_is_main", True)

    def _process_bounds(self, bounds: Optional[BoundsPair]):
        if bounds is None:
            return None, None
        lb, ub = bounds
        lb = ensure_array_length_and_dtype(lb, self.solution_length, self._dtype, about="lower bound")
        ub = ensure_array_length_and_dtype(ub, self.solution_length, self._dtype, about="upper bound")
        if bool(jnp.any(lb > ub)):
            raise ValueError("Some lower bounds exceed their upper bounds")
        return lb, ub

    # ------------------------------------------------------------------ PRNG
    def manual_seed(self, seed: Optional[int] = None):
        """Re-seed the problem's PRNG chain (reference ``core.py:1616``)."""
        self._seed = 0 if seed is None else int(seed)
        self._rng_key = jax.random.key(self._seed)

    def next_rng_key(self):
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    # ------------------------------------------------------------- solutions
    def generate_values(self, num_solutions: int, *, key=None) -> Union[jnp.ndarray, ObjectArray]:
        """Decision values for ``num_solutions`` new solutions
        (reference ``core.py:1840``); delegates to ``_fill``."""
        if key is None:
            key = self.next_rng_key()
        return self._fill(int(num_solutions), key)

    def _fill(self, num_solutions: int, key) -> Union[jnp.ndarray, ObjectArray]:
        """Default initialization: uniform within the initial bounds
        (reference ``core.py:1874``). Override for custom initialization."""
        if is_dtype_object(self._dtype):
            raise NotImplementedError(
                "Object-typed problems must override _fill (or generate_values)"
            )
        if self._initial_lower_bounds is None:
            raise RuntimeError(
                "Cannot generate solutions: no initial_bounds / bounds were given "
                "and _fill was not overridden"
            )
        if is_dtype_bool(self._dtype):
            u = jax.random.uniform(key, (num_solutions, self.solution_length))
            return u < 0.5
        return self.make_uniform(
            num_solutions=num_solutions,
            lb=self._initial_lower_bounds,
            ub=self._initial_upper_bounds,
            key=key,
        )

    def generate_batch(
        self,
        popsize: int,
        *,
        empty: bool = False,
        center: Optional[jnp.ndarray] = None,
        stdev: Optional[float] = None,
        symmetric: bool = False,
        key=None,
    ) -> "SolutionBatch":
        """A new ``SolutionBatch`` (reference ``core.py:1911``)."""
        if empty:
            return SolutionBatch(self, popsize, empty=True)
        if center is not None or stdev is not None:
            values = self.make_gaussian(
                num_solutions=popsize, center=center, stdev=stdev, symmetric=symmetric, key=key
            )
        else:
            values = self.generate_values(popsize, key=key)
        return SolutionBatch(self, popsize, values=values)

    # ------------------------------------------------------------- evaluation
    def _start_preparations(self):
        if not self._prepared:
            self._prepare()
            self._prepared = True

    def _prepare(self):
        """One-time preparation before the first evaluation
        (reference ``core.py:2555``)."""

    def evaluate(self, batch: Union["SolutionBatch", "Solution"]):
        """Evaluate every solution of the batch (reference ``core.py:2532``):
        run before-hooks, compute fitnesses, scatter them into the batch,
        track best/worst, run after-hooks (their dict results accumulate into
        ``problem.status``)."""
        if isinstance(batch, Solution):
            batch = batch.to_batch()
        if not isinstance(batch, SolutionBatch):
            raise TypeError(f"evaluate expects a SolutionBatch or Solution, got {type(batch)}")

        self._start_preparations()
        self.before_eval_hook(batch)
        # named trace region: shows up as "evotorch_tpu.evaluate" in
        # jax.profiler / xprof timelines (SearchAlgorithm.run(profile_dir=...))
        with jax.profiler.TraceAnnotation("evotorch_tpu.evaluate"):
            self._evaluate_all(batch)
            if self._store_solution_stats:
                self._update_best_and_worst(batch)
        hook_results = self.after_eval_hook.accumulate_dict(batch)
        if hook_results:
            self.update_status(hook_results)

    def _evaluate_all(self, batch: "SolutionBatch"):
        """Single-program evaluation (reference ``core.py:2573``). When a
        sharded evaluator has been installed (``use_sharded_evaluation``),
        the population axis is sharded over the mesh instead; when a host
        pool exists (``num_actors`` with a non-traceable objective), the
        batch fans out over worker processes."""
        self._resolve_num_actors_request()
        if self._host_pool is not None and len(batch) > 0:
            self._evaluate_with_host_pool(batch)
            return
        use_subbatches = (
            self._num_subbatches is not None or self._subbatch_size is not None
        ) and self._sharded_evaluator is None
        # with a sharded evaluator, sub-batching is skipped: the mesh already
        # bounds per-device rows, and pieces smaller than the device count
        # would only pad back up to it
        if use_subbatches and len(batch) > 0:
            # evaluation in pieces (reference core.py:1282-1295 + 2583-2600):
            # bounds per-evaluation memory; results scatter back into `batch`
            if self._num_subbatches is not None:
                pieces = batch.split(min(int(self._num_subbatches), len(batch)))
            else:
                pieces = batch.split(max_size=int(self._subbatch_size))
            for piece in pieces:
                self._eval_possibly_sharded(piece)
            return
        self._eval_possibly_sharded(batch)

    def _eval_possibly_sharded(self, batch: "SolutionBatch"):
        if self._sharded_evaluator is not None:
            values = dense_values(batch.values)
            try:
                evals = self._sharded_evaluator(values)
            except jax.errors.JAXTypeError as e:
                # the objective turned out not to be jax-traceable (tracer
                # leaked into host code — the reference runs arbitrary Python
                # in actors; we cannot): fall back to eager evaluation.
                # Genuine bugs (shape errors, NaN checks, ...) re-raise —
                # silently running them N-times slower would mask them
                from .tools.misc import set_default_logger_config

                set_default_logger_config().warning(
                    "sharded evaluation failed (%s: %s); falling back to "
                    "eager evaluation (honoring any sub-batching settings)",
                    type(e).__name__,
                    e,
                )
                self._drop_sharded_evaluation()
                # re-enter through _evaluate_all so the sub-batching knobs
                # (skipped while the sharded evaluator was active) apply
                self._evaluate_all(batch)
                return
            batch.set_evals(*self._split_eval_outputs(evals))
            return
        self._evaluate_batch(batch)

    def _resolve_num_actors_request(self):
        """Drop-in parity for ``num_actors`` (reference ``core.py:1302-1595``),
        resolved lazily at first evaluation like the reference's
        ``_parallelize``. Two forms, picked by the objective's nature:

        - jax-traceable ``@vectorized`` objective -> an N-device (or
          all-device, for "max"/"num_devices"/"num_gpus") mesh over which the
          population axis is sharded (zero processes, zero pickling);
        - anything else (per-solution Python objectives, ``GymNE`` rollouts)
          -> a pool of N worker *processes* each holding a problem clone, the
          direct analog of the reference's Ray actor pool
          (``core.py:1977-2052``).
        """
        if (
            self._num_actors_requested is None
            or self._sharded_evaluator is not None
            or self._host_pool is not None
        ):
            return
        request = self._num_actors_requested
        self._num_actors_requested = None  # resolve once
        if not self._vectorized or self._objective_func is None:
            # per-solution Python objectives and subclass `_evaluate*`
            # overrides (e.g. GymNE) -> worker processes; VecNE never gets
            # here (it overrides _resolve_num_actors_request with its own
            # sharded path)
            import multiprocessing as mp

            if isinstance(request, str):
                if request in ("max", "num_cpus", "num_devices", "num_gpus"):
                    n = mp.cpu_count()
                else:
                    raise ValueError(f"Unrecognized num_actors request: {request!r}")
            else:
                n = int(request)
            if n <= 1:
                return
            from .parallel.hostpool import HostEvaluatorPool

            # per-worker seeds derived from the problem's PRNG chain, like the
            # reference's per-actor derived seeds (core.py:133-141, 2043-2047)
            seeds = np.asarray(
                jax.random.randint(self.next_rng_key(), (n,), 0, 2**31 - 1)
            ).tolist()
            try:
                self._host_pool = HostEvaluatorPool(self, n, seeds=seeds)
            except (pickle.PicklingError, AttributeError, TypeError) as e:
                # lambdas/closures pickle under Ray's cloudpickle but not under
                # the stdlib; degrade to serial evaluation instead of crashing
                from .tools.misc import set_default_logger_config

                set_default_logger_config().warning(
                    "num_actors=%r: the problem could not be pickled for "
                    "worker processes (%s); evaluating serially instead. "
                    "Define the objective at module level to enable the pool.",
                    request,
                    e,
                )
            return

        if isinstance(request, str):
            if request in ("max", "num_devices", "num_gpus", "num_cpus"):
                n = jax.device_count()
            else:
                raise ValueError(f"Unrecognized num_actors request: {request!r}")
        else:
            n = min(int(request), jax.device_count())
        if n <= 1:
            return
        from .parallel import make_sharded_evaluator
        from .parallel.mesh import default_mesh

        mesh = default_mesh(("pop",), devices=jax.devices()[:n])
        self._sharded_evaluator = make_sharded_evaluator(self._objective_func, mesh=mesh)
        self._eval_mesh = mesh
        self._eval_axis_name = "pop"

    def _evaluate_with_host_pool(self, batch: "SolutionBatch"):
        """Split -> map over worker processes -> scatter back, with the sync
        protocol around it (reference ``core.py:2583-2600`` + ``2313-2332``)."""
        pool = self._host_pool
        if self._num_subbatches is not None:
            pieces = batch.split(min(int(self._num_subbatches), len(batch)))
        elif self._subbatch_size is not None:
            pieces = batch.split(max_size=int(self._subbatch_size))
        else:
            pieces = batch.split(min(pool.num_workers, len(batch)))
        sync = self._make_sync_data_for_actors()
        try:
            evals, sync_back = pool.evaluate_pieces(
                [dense_values(p.values) for p in pieces], sync
            )
        except Exception:
            # the pool shut itself down on failure; drop the dead handle so a
            # later evaluate does not enqueue into a pool with no workers
            self._host_pool = None
            raise
        for piece, piece_evals in zip(pieces, evals):
            piece.set_evals(jnp.asarray(piece_evals, dtype=self._eval_dtype))
        self._use_sync_data_from_actors(sync_back)

    # --------------------- main<->worker sync protocol (reference 2239-2332)
    def _make_sync_data_for_actors(self) -> Optional[dict]:
        """State broadcast to every worker before an evaluation round
        (e.g. obs-norm statistics). Default: nothing."""
        return None

    def _use_sync_data_from_main(self, data: dict):
        """Worker-side: apply the broadcast state."""

    def _make_sync_data_for_main(self) -> dict:
        """Worker-side: state deltas to send home after an evaluation round
        (e.g. obs-stat deltas, interaction counters). Default: nothing."""
        return {}

    def _use_sync_data_from_actors(self, data_list: List[dict]):
        """Merge the per-worker deltas into the main problem."""

    def _evaluate_batch(self, batch: "SolutionBatch"):
        """Vectorized objective call or per-solution loop
        (reference ``core.py:2602-2621``).

        A factored (low-rank) population is materialized at this boundary:
        plain fitness functions are functions of dense vectors. Problems
        whose evaluator understands the factored form natively (``VecNE``)
        override this method and keep it factored."""
        if self._vectorized and self._objective_func is not None:
            result = self._objective_func(dense_values(batch.values))
            batch.set_evals(*self._split_eval_outputs(result))
        elif self._objective_func is not None and not is_dtype_object(self._dtype):
            # per-solution loop, but accumulate host-side and scatter once —
            # avoids rebuilding the (N, W) eval matrix N times
            values = dense_values(batch.values)
            rows = []
            width = self.num_objectives + self._eval_data_length
            for i in range(len(batch)):
                result = self._objective_func(values[i])
                row = np.atleast_1d(np.asarray(result, dtype=np.float64))
                if row.shape[0] < width:
                    row = np.concatenate([row, np.full(width - row.shape[0], np.nan)])
                rows.append(row)
            batch.set_evals(jnp.asarray(np.stack(rows), dtype=self._eval_dtype))
        else:
            for sln in batch:
                self._evaluate(sln)

    def _evaluate(self, solution: "Solution"):
        """Per-solution evaluation (reference ``core.py:2613``)."""
        if self._objective_func is None:
            raise NotImplementedError(
                "Either provide objective_func, or override _evaluate/_evaluate_batch"
            )
        result = self._objective_func(solution.values)
        solution.set_evals(result)

    def _split_eval_outputs(self, result):
        """Split a fitness-function result into (fitnesses, eval_data)."""
        if isinstance(result, tuple):
            return result
        result = jnp.asarray(result)
        if self._eval_data_length > 0 and result.ndim == 2 and result.shape[-1] == (
            len(self._senses) + self._eval_data_length
        ):
            return result[:, : len(self._senses)], result[:, len(self._senses) :]
        return (result,)

    # --------------------------------------------------------- best tracking
    def _update_best_and_worst(self, batch: "SolutionBatch"):
        """Track per-objective best/worst solutions (reference ``core.py:2334``).

        Numeric problems merge entirely on-device (a jitted ``argmax`` +
        ``where`` select into ``(K, L)``/``(K, W)`` snapshots) so the hot loop
        never blocks on the host; Solutions and floats are materialized
        lazily by the status getters. Object-dtype problems keep a host-side
        merge (their values are not device arrays)."""
        if len(batch) == 0:
            return
        if is_dtype_object(self._dtype):
            self._update_best_and_worst_host(batch)
            return
        if self._best_snapshot is None:
            k, w = len(self._senses), len(self._senses) + self._eval_data_length
            length = int(self.solution_length)
            zeros_v = jnp.zeros((k, length), dtype=self._dtype)
            nans_e = jnp.full((k, w), jnp.nan, dtype=self._eval_dtype)
            self._best_snapshot = (zeros_v, nans_e)
            self._worst_snapshot = (zeros_v, nans_e)
            self._register_best_status_getters()
        bv, be = self._best_snapshot
        wv, we = self._worst_snapshot
        senses = tuple(self._senses)
        # reduce the batch to K winner rows on the batch's OWN placement
        # (keeps sharded populations sharded), then move only those tiny rows
        # to one pinned device for the running merge — batches may arrive
        # from programs compiled over different meshes, and mixing their
        # placements in one jit call is an error
        values = batch.values
        if is_factored(values):
            # find the winner COEFFICIENT rows, then densify only those K
            # rows — the full (N, L) population is never built
            cbv, cbe, cwv, cwe = _batch_extremes(values.coeffs, batch.evals, senses)
            cbv = values.materialize_rows(cbv)
            cwv = values.materialize_rows(cwv)
        else:
            cbv, cbe, cwv, cwe = _batch_extremes(values, batch.evals, senses)
        dev = jax.devices()[0]
        put = functools.partial(jax.device_put, device=dev)
        bv, be, wv, we = _merge_snapshots(
            put(bv), put(be), put(wv), put(we),
            put(cbv), put(cbe), put(cwv), put(cwe),
            senses,
        )
        self._best_snapshot = (bv, be)
        self._worst_snapshot = (wv, we)
        # invalidate memoized materializations of the lazy status entries
        for key in self._best_status_keys():
            self._computed.pop(key, None)

    def _best_status_keys(self):
        if len(self._senses) == 1:
            return ("best", "worst", "best_eval", "worst_eval")
        keys = []
        for i in range(len(self._senses)):
            keys += [f"obj{i}_best", f"obj{i}_worst"]
        return tuple(keys)

    def _register_best_status_getters(self):
        from functools import partial

        if len(self._senses) == 1:
            self.update_status_getters(
                {
                    "best": partial(self._materialize_extreme, "best", 0),
                    "worst": partial(self._materialize_extreme, "worst", 0),
                    "best_eval": partial(self._materialize_extreme_eval, "best", 0),
                    "worst_eval": partial(self._materialize_extreme_eval, "worst", 0),
                }
            )
        else:
            getters = {}
            for i in range(len(self._senses)):
                getters[f"obj{i}_best"] = partial(self._materialize_extreme, "best", i)
                getters[f"obj{i}_worst"] = partial(self._materialize_extreme, "worst", i)
            self.update_status_getters(getters)

    def _materialize_extreme(self, which: str, obj_index: int) -> "Solution":
        snap = self._best_snapshot if which == "best" else self._worst_snapshot
        if snap is None:
            raise KeyError(which)
        values, evals = snap
        if bool(jnp.isnan(evals[obj_index, obj_index])):
            # no valid evaluation seen yet for this objective: the status key
            # is "not ready" (old contract: key absent until a non-NaN eval)
            raise KeyError(which)
        batch = SolutionBatch(
            self, 1, values=values[obj_index][None, :], evals=evals[obj_index][None, :]
        )
        return batch[0]

    def _materialize_extreme_eval(self, which: str, obj_index: int) -> float:
        snap = self._best_snapshot if which == "best" else self._worst_snapshot
        if snap is None:
            raise KeyError(which)
        value = float(np.asarray(snap[1][obj_index, obj_index]))
        if math.isnan(value):
            raise KeyError(which)  # not ready: no valid evaluation yet
        return value

    def _update_best_and_worst_host(self, batch: "SolutionBatch"):
        if self._best is None:
            self._best = [None] * len(self._senses)
            self._worst = [None] * len(self._senses)
        evals = np.asarray(batch.evals)
        for i, sense in enumerate(self._senses):
            col = evals[:, i]
            if np.all(np.isnan(col)):
                continue
            best_idx = int(np.nanargmax(col) if sense == "max" else np.nanargmin(col))
            worst_idx = int(np.nanargmin(col) if sense == "max" else np.nanargmax(col))
            for attr, idx, better in (("_best", best_idx, True), ("_worst", worst_idx, False)):
                current = getattr(self, attr)[i]
                candidate_eval = float(col[idx])
                if current is None:
                    getattr(self, attr)[i] = batch[idx].clone()
                else:
                    current_eval = float(np.asarray(current.evals)[i])
                    if better == (sense == "max"):
                        improved = candidate_eval > current_eval
                    else:
                        improved = candidate_eval < current_eval
                    if improved:
                        getattr(self, attr)[i] = batch[idx].clone()
        if len(self._senses) == 1:
            if self._best[0] is not None:
                self.update_status(
                    {
                        "best": self._best[0],
                        "worst": self._worst[0],
                        "best_eval": float(np.asarray(self._best[0].evals)[0]),
                        "worst_eval": float(np.asarray(self._worst[0].evals)[0]),
                    }
                )
        else:
            # each objective publishes independently (one may be all-NaN so far)
            for i in range(len(self._senses)):
                if self._best[i] is not None:
                    self.update_status(
                        {f"obj{i}_best": self._best[i], f"obj{i}_worst": self._worst[i]}
                    )

    # ------------------------------------------------ sharded evaluation API
    def use_sharded_evaluation(self, mesh=None, *, axis_name: str = "pop", donate: bool = False):
        """Install a mesh-sharded evaluator (the TPU replacement for the Ray
        actor pool, reference ``core.py:1977-2052``): the population axis is
        sharded over the mesh and each shard evaluates locally. Requires a
        vectorized objective function."""
        from .parallel import make_sharded_evaluator

        if not self._vectorized or self._objective_func is None:
            raise ValueError("Sharded evaluation requires a @vectorized objective_func")
        if mesh is None:
            from .parallel.mesh import default_mesh

            mesh = default_mesh((axis_name,))
        self._sharded_evaluator = make_sharded_evaluator(
            self._objective_func, mesh=mesh, axis_name=axis_name
        )
        self._eval_mesh = mesh
        self._eval_axis_name = axis_name
        return self

    # ------------------------------------ distributed ES-gradient estimation
    def sample_and_compute_gradients(
        self,
        distribution,
        popsize: int,
        *,
        num_interactions: Optional[int] = None,
        popsize_max: Optional[int] = None,
        obj_index: int = 0,
        ranking_method: Optional[str] = None,
        key=None,
        lowrank_rank: Optional[int] = None,
    ) -> List[dict]:
        """Sample a population from ``distribution``, evaluate it, and return
        ES gradients (reference ``core.py:2762-3073``). The reference fans
        this out over Ray actors and gathers a list of gradient dicts; here a
        single SPMD program does the work (shard the evaluation via
        ``use_sharded_evaluation``) and the list has one entry. The
        weighted-average step in the algorithm layer then degenerates to the
        identity, exactly as a ``psum`` over one shard would.

        When a sharded evaluator is active (``use_sharded_evaluation`` or
        ``num_actors``) and no interaction budget is set, the pipeline runs
        as one GSPMD program over the mesh — global key, global ranking:
        the reference's single-process statistics at any mesh shape. Under
        ``EVOTORCH_SHARD_MAP=1`` it instead reproduces the reference's
        *exact* distributed statistics (``core.py:3156-3301`` +
        ``gaussian.py:199-272``): each mesh shard samples its own
        sub-population, ranks **locally**, computes local gradients, and a
        ``pmean`` replaces the main-process weighted average (shards are
        equal-sized, so both weighting conventions coincide).

        With ``lowrank_rank`` the population is sampled in factored (low-rank)
        form and gradients are computed from the factors in O(L * rank);
        evaluation materializes the dense matrix only at boundaries that need
        it (plain fitness functions — VecNE rolls the factors out directly).
        In the adaptive-popsize loop every round after the first samples fresh
        coefficients against the generation's basis, keeping the rounds
        concatenable."""
        if key is None:
            key = self.next_rng_key()
        if lowrank_rank is not None and not hasattr(type(distribution), "_sample_lowrank"):
            raise ValueError(
                f"{type(distribution).__name__} has no factored sampler; "
                "lowrank_rank requires SymmetricSeparableGaussian"
            )
        self._start_preparations()
        self.before_grad_hook()

        self._resolve_num_actors_request()
        if (
            self._eval_mesh is not None
            and self._eval_mesh.shape[self._eval_axis_name] > 1
            and num_interactions is None
            and self._vectorized
            and self._objective_func is not None
        ):
            try:
                result = self._sharded_sample_and_compute_gradients(
                    distribution, popsize, obj_index=obj_index,
                    ranking_method=ranking_method, key=key,
                    lowrank_rank=lowrank_rank,
                )
            except jax.errors.JAXTypeError as e:
                # the objective is not jax-traceable: degrade to the
                # single-program path, mirroring _eval_possibly_sharded
                from .tools.misc import set_default_logger_config

                set_default_logger_config().warning(
                    "sharded gradient estimation failed (%s: %s); falling "
                    "back to single-program sampling with global ranking",
                    type(e).__name__,
                    e,
                )
                self._drop_sharded_evaluation()
            else:
                # keep the hook payload to the reference's key set; the basis
                # (subspace-exhaustion diagnostic) re-attaches afterwards
                basis = result.pop("basis", None)
                hook_results = self.after_grad_hook.accumulate_dict(result)
                if hook_results:
                    self.update_status(hook_results)
                if basis is not None:
                    result["basis"] = basis
                return [result]

        def sample_and_eval(key, n, basis=None):
            if lowrank_rank is not None:
                samples = distribution.sample_lowrank(
                    int(n), int(lowrank_rank), key=key, basis=basis
                )
                batch = SolutionBatch(self, values=samples)
            else:
                samples = distribution.sample(int(n), key=key)
                batch = SolutionBatch(self, samples.shape[0], values=samples)
            self.evaluate(batch)
            return samples, batch.evals[:, obj_index]

        if num_interactions is None:
            all_samples, all_fitnesses = sample_and_eval(key, popsize)
        else:
            # adaptive sampling by interaction budget
            # (reference core.py:3239-3282): keep sampling sub-populations
            # until the problem reports enough simulator interactions
            first_count = _as_int(self.status.get("total_interaction_count", 0))
            sample_chunks = []
            fitness_chunks = []
            total = 0
            prev_made = -1
            gen_basis = None
            while True:
                key, sub = jax.random.split(key)
                s, f = sample_and_eval(sub, popsize, basis=gen_basis)
                if lowrank_rank is not None and gen_basis is None:
                    gen_basis = s.basis  # later rounds stay concatenable
                sample_chunks.append(s)
                fitness_chunks.append(f)
                total += f.shape[0]
                if popsize_max is not None and total >= int(popsize_max):
                    break
                made = _as_int(self.status.get("total_interaction_count", 0)) - first_count
                if made > int(num_interactions):
                    break
                if not self.has_status_key("total_interaction_count"):
                    break  # the problem does not report interactions
                if made <= prev_made:
                    # the problem stopped updating its interaction counter —
                    # without this guard (and with no popsize_max) the budget
                    # would never be reached and the loop would spin forever
                    break
                prev_made = made
            if lowrank_rank is not None:
                # _replace keeps the concrete factored class (low-rank or
                # trunk-delta): shared center/basis/factors ride along
                all_samples = sample_chunks[0]._replace(
                    coeffs=jnp.concatenate([c.coeffs for c in sample_chunks], axis=0)
                )
            else:
                all_samples = jnp.concatenate(sample_chunks, axis=0)
            all_fitnesses = jnp.concatenate(fitness_chunks, axis=0)

        grads = distribution.compute_gradients(
            all_samples,
            all_fitnesses,
            objective_sense=self._senses[obj_index],
            ranking_method=ranking_method if ranking_method is not None else "raw",
        )
        num_solutions = (
            all_samples.popsize
            if is_factored(all_samples)
            else int(all_samples.shape[0])
        )
        result = {
            "gradients": grads,
            "num_solutions": num_solutions,
            "mean_eval": jnp.mean(all_fitnesses),  # device scalar: stays lazy
        }
        hook_results = self.after_grad_hook.accumulate_dict(result)
        if hook_results:
            self.update_status(hook_results)
        if is_factored(all_samples):
            # the generation's basis, for the subspace-exhaustion diagnostic
            # (gaussian.py:_update_basis_capture); attached after the hook
            # pass so hook payloads keep the reference's key set
            result["basis"] = all_samples.basis
        return [result]

    def _drop_sharded_evaluation(self):
        """Forget the sharded evaluator AND everything derived from its mesh,
        so a fallback (or a later ``use_sharded_evaluation`` with a different
        mesh) never reuses stale sharded programs."""
        self._sharded_evaluator = None
        self._eval_mesh = None
        self._sharded_grad_cache.clear()

    def _sharded_sample_and_compute_gradients(
        self, distribution, popsize: int, *, obj_index: int, ranking_method, key,
        lowrank_rank: Optional[int] = None,
    ) -> dict:
        """Sampling/ranking/gradients over the eval mesh — GSPMD global
        ranking by default, the reference's per-actor local ranking
        (``core.py:3156-3301``) under ``EVOTORCH_SHARD_MAP=1``."""
        from .parallel.grad import make_sharded_grad_estimator

        mesh = self._eval_mesh
        axis = self._eval_axis_name
        n_shards = mesh.shape[axis]
        dist_cls = type(distribution)
        # round the shard-local popsize up so every shard gets the same
        # (and, for antithetic distributions, even) sub-population — the
        # analog of the reference's near-equal split_workload pieces
        local = -(-int(popsize) // n_shards)
        if dist_cls.SAMPLES_MUST_BE_EVEN and local % 2 != 0:
            local += 1
        total = local * n_shards
        ranking = ranking_method if ranking_method is not None else "raw"
        sense = self._senses[obj_index]

        cache_key = (dist_cls, ranking, obj_index, sense, mesh, axis, lowrank_rank)
        estimator = self._sharded_grad_cache.get(cache_key)
        if estimator is None:

            def fitness_for_grad(values):
                outputs = self._split_eval_outputs(self._objective_func(values))
                fitnesses = jnp.asarray(outputs[0])
                if fitnesses.ndim == 2:
                    fitnesses = fitnesses[:, obj_index]
                return fitnesses

            estimator = make_sharded_grad_estimator(
                dist_cls,
                fitness_for_grad,
                objective_sense=sense,
                ranking_method=ranking,
                mesh=mesh,
                axis_name=axis,
                with_aux=True,
                lowrank_rank=lowrank_rank,
            )
            self._sharded_grad_cache[cache_key] = estimator

        grads, aux = estimator(key, total, distribution.parameters)
        result = {
            "gradients": grads,
            "num_solutions": int(total),
            "mean_eval": aux["mean_eval"],  # device scalar: stays lazy
        }
        if "basis" in aux:
            # per-shard bases ride out stacked along the pop axis; shard 0's
            # rows are a representative iid draw for the subspace-exhaustion
            # diagnostic (every shard's basis is an independent draw at the
            # same rank, so the capture statistics are exchangeable)
            result["basis"] = aux["basis"][: self.solution_length]
        return result

    # ----------------------------------------------------------------- misc
    def ensure_numeric(self):
        """Raise if the problem is object-typed (reference ``core.py:1700``-ish
        guard used by distribution-based searchers)."""
        if is_dtype_object(self._dtype):
            raise ValueError("This operation requires a numeric (non-object) problem dtype")

    def ensure_unbounded(self):
        """Raise if the problem declares strict bounds (distribution-based
        searchers cannot respect them; reference guard)."""
        if self._bounds_are_strict:
            raise ValueError(
                "Distribution-based searchers require an unbounded problem; "
                "use initial_bounds (not bounds) to seed the search"
            )

    def normalize_obj_index(self, obj_index: Optional[int] = None) -> int:
        """Validate/normalize an objective index (reference ``core.py:1685``)."""
        if obj_index is None:
            if len(self._senses) > 1:
                raise ValueError(
                    "obj_index must be given explicitly for multi-objective problems"
                )
            return 0
        i = int(obj_index)
        if i < 0:
            i += len(self._senses)
        if not (0 <= i < len(self._senses)):
            raise IndexError(f"obj_index {obj_index} out of range")
        return i

    def ensure_tensor_length_and_dtype(self, x, *, about=None, allow_scalar=True):
        return ensure_array_length_and_dtype(
            x, self.solution_length, self._dtype, about=about, allow_scalar=allow_scalar
        )

    def make_callable_evaluator(self, *, obj_index: int = 0) -> "ProblemBoundEvaluator":
        """Wrap this problem as a pure callable ``f(values) -> fitnesses`` for
        the functional algorithms (reference ``core.py:3309``)."""
        return ProblemBoundEvaluator(self, obj_index=obj_index)

    def kill_actors(self):
        """Shut down the host evaluation pool, if one was spawned (reference
        ``core.py:2650``-ish actor teardown). The mesh path has nothing to
        kill."""
        if self._host_pool is not None:
            self._host_pool.shutdown()
            self._host_pool = None

    @property
    def is_remote(self) -> bool:
        return False

    def _printable_items(self):
        return {
            "objective_sense": self.objective_sense,
            "solution_length": self.solution_length,
            "dtype": self._dtype,
        }

    def _get_cloned_state(self, *, memo: dict) -> dict:
        state = {}
        for k, v in self.__dict__.items():
            if k in ("_sharded_evaluator", "_eval_mesh", "_host_pool"):
                # compiled executables, device meshes and worker processes
                # are not picklable (and must not leak into clones/workers)
                state[k] = None
            elif k == "_sharded_grad_cache":
                state[k] = {}
            else:
                state[k] = deep_clone(v, memo=memo)
        return state


class SolutionBatch(Serializable, RecursivePrintable):
    """Population container (reference ``core.py:3590``): decision values
    ``(N, L)`` (or ``ObjectArray`` for object dtype) and an eval matrix
    ``(N, n_obj + eval_data_length)`` where NaN means "not evaluated"."""

    def __init__(
        self,
        problem: Optional[Problem] = None,
        popsize: Optional[int] = None,
        *,
        device: Any = None,
        empty: bool = False,
        slice_of: Optional[tuple] = None,
        like: Optional["SolutionBatch"] = None,
        merging_of: Optional[Iterable["SolutionBatch"]] = None,
        values: Any = None,
        evals: Any = None,
    ):
        self._parent: Optional[tuple] = None  # (parent_batch, row_indices)

        if merging_of is not None:
            batches = list(merging_of)
            if not batches:
                raise ValueError("merging_of needs at least one batch")
            first = batches[0]
            self._problem = first._problem
            if any(is_factored(b._values) for b in batches):
                factored_cls = type(first._values)
                if not all(type(b._values) is factored_cls for b in batches):
                    raise TypeError(
                        "Cannot concatenate factored batches with dense ones "
                        "or with a different factored form; materialize first "
                        "(batch.values.materialize())"
                    )

                def _same_array(a, b):
                    # `is` catches the shared-per-generation-basis case with
                    # no device sync; the value comparison is the fallback
                    # for rebuilt-but-equal arrays (one tiny sync per cat)
                    return a is b or (
                        a.shape == b.shape and a.dtype == b.dtype and bool(jnp.all(a == b))
                    )

                fv = first._values
                if not all(
                    _same_array(b._values.center, fv.center)
                    and _same_array(b._values.basis, fv.basis)
                    for b in batches[1:]
                ):
                    raise TypeError(
                        "Factored (low-rank) batches concatenate only when "
                        "they share one generation's center and basis (sample "
                        "the later rounds with sample_lowrank(..., "
                        "basis=first_batch.values.basis)); batches drawn "
                        "against different bases have no shared factored "
                        "form — materialize first (batch.values.materialize())"
                    )
                # _replace keeps the concrete factored class; shared
                # center/basis (and trunk-delta factors) ride along
                self._values = fv._replace(
                    coeffs=jnp.concatenate([b._values.coeffs for b in batches], axis=0)
                )
                self._evdata = jnp.concatenate([b._evdata for b in batches], axis=0)
                return
            if isinstance(first._values, ObjectArray):
                merged = []
                for b in batches:
                    merged.extend(list(b._values))
                self._values = ObjectArray.from_values(merged)
            else:
                self._values = jnp.concatenate([b._values for b in batches], axis=0)
            self._evdata = jnp.concatenate([b._evdata for b in batches], axis=0)
            return

        if slice_of is not None:
            source, sl = slice_of
            self._problem = source._problem
            if isinstance(sl, slice):
                indices = np.arange(len(source))[sl]
            else:
                indices = np.asarray(sl)
            self._parent = (source, indices)
            if isinstance(source._values, ObjectArray):
                if isinstance(sl, slice):
                    # numpy-view slice: object-value writes share storage with
                    # the parent (reference shared-memory views, core.py:3641)
                    self._values = source._values[sl]
                else:
                    # fancy indexing copies; writes propagate via
                    # _scatter_object_values instead
                    self._values = source._values[list(indices)]
            elif is_factored(source._values):
                # gather coefficient lanes; center/basis/factors are shared
                self._values = source._values.take(jnp.asarray(indices))
            else:
                self._values = source._values[jnp.asarray(indices)]
            self._evdata = source._evdata[jnp.asarray(indices)]
            return

        if like is not None:
            problem = like._problem
            popsize = len(like) if popsize is None else popsize

        if problem is None:
            raise ValueError("SolutionBatch requires a problem (or slice_of/like/merging_of)")
        self._problem = problem

        n_evals = problem.num_objectives + problem.eval_data_length

        if values is not None:
            if isinstance(values, ObjectArray):
                self._values = values
                popsize = len(values)
            elif is_factored(values):
                # factored population: theta_i = center + basis @ coeffs[i]
                # stored as-is — the dense (N, L) matrix is never built here
                self._values = values
                popsize = values.popsize
            else:
                values = jnp.asarray(values, dtype=problem.dtype)
                if values.ndim != 2:
                    raise ValueError(f"values must be 2-D, got shape {values.shape}")
                self._values = values
                popsize = values.shape[0]
            self._evdata = (
                jnp.asarray(evals, dtype=problem.eval_dtype)
                if evals is not None
                else jnp.full((popsize, n_evals), jnp.nan, dtype=problem.eval_dtype)
            )
            return

        if popsize is None:
            raise ValueError("popsize is required")
        popsize = int(popsize)

        if is_dtype_object(problem.dtype):
            self._values = ObjectArray(popsize)
        elif empty:
            self._values = jnp.zeros((popsize, problem.solution_length), dtype=problem.dtype)
        else:
            self._values = problem.generate_values(popsize)
        self._evdata = jnp.full((popsize, n_evals), jnp.nan, dtype=problem.eval_dtype)

    # ------------------------------------------------------------ properties
    @property
    def problem(self) -> Problem:
        return self._problem

    def __len__(self) -> int:
        if isinstance(self._values, ObjectArray):
            return len(self._values)
        if is_factored(self._values):
            return self._values.popsize
        return int(self._values.shape[0])

    @property
    def values(self) -> Union[jnp.ndarray, ObjectArray, LowRankParamsBatch]:
        """Read-only view of decision values (reference ``core.py:4088``).
        For a factored population this is the ``LowRankParamsBatch`` itself
        (immutable by construction); call ``.materialize()`` on it if a dense
        matrix is genuinely needed."""
        if isinstance(self._values, ObjectArray):
            return self._values.get_read_only_view()
        return self._values

    @property
    def evals(self) -> jnp.ndarray:
        """Read-only eval matrix ``(N, n_obj + eval_data_length)``
        (reference ``core.py:4106``)."""
        return self._evdata

    @property
    def evdata(self) -> jnp.ndarray:
        return self._evdata[:, self._problem.num_objectives :]

    @property
    def is_evaluated(self) -> bool:
        return not bool(jnp.any(jnp.isnan(self._evdata[:, : self._problem.num_objectives])))

    def evals_of(self, obj_index: int = 0) -> jnp.ndarray:
        return self._evdata[:, obj_index]

    # -------------------------------------------------------------- mutation
    def access_values(self, *, keep_evals: bool = False) -> Union[jnp.ndarray, ObjectArray]:
        """Return the decision values for modification. Unless
        ``keep_evals=True``, all evaluation results are invalidated (NaN),
        mirroring reference ``core.py:4166-4194``. Since jax.Arrays are
        immutable, write the modified values back via ``set_values``
        (ObjectArray values are mutable in place)."""
        if not keep_evals:
            self.forget_evals()
        return self._values

    def forget_evals(self):
        self._set_evdata(jnp.full_like(self._evdata, jnp.nan))

    def set_values(self, values, *, keep_evals: bool = False):
        """Replace decision values (reference ``core.py:3950``)."""
        if is_factored(self._values):
            if type(values) is not type(self._values):
                raise TypeError(
                    "This batch holds a factored population; set_values "
                    f"expects another {type(self._values).__name__} of the "
                    "same popsize"
                )
            if values.popsize != len(self):
                raise ValueError(
                    f"set_values popsize mismatch: {values.popsize} vs {len(self)}"
                )
            if self._parent is not None:
                raise NotImplementedError(
                    "Writing values into a slice view of a factored batch is "
                    "not supported (coefficient scatter-back is ambiguous "
                    "across bases)"
                )
            self._values = values
            if not keep_evals:
                self.forget_evals()
            return
        if isinstance(self._values, ObjectArray):
            if len(values) != len(self):
                raise ValueError("Length mismatch in set_values")
            self._values[:] = list(values)
        else:
            values = jnp.asarray(values, dtype=self._problem.dtype)
            if values.shape != self._values.shape:
                raise ValueError(
                    f"set_values shape mismatch: {values.shape} vs {self._values.shape}"
                )
            self._set_values_array(values)
        if not keep_evals:
            self.forget_evals()

    def set_evals(self, evals, eval_data=None):
        """Store evaluation results (reference ``core.py:3966-4086``).
        ``evals`` may be ``(N,)`` (single objective), ``(N, n_obj)``, or the
        full ``(N, n_obj + eval_data_length)`` matrix."""
        n_obj = self._problem.num_objectives
        evals = jnp.asarray(evals, dtype=self._problem.eval_dtype)
        if evals.ndim == 1:
            evals = evals[:, None]
            if n_obj != 1:
                raise ValueError("1-D evals are only valid for single-objective problems")
        if evals.shape[0] != len(self):
            raise ValueError(f"evals row count {evals.shape[0]} != batch size {len(self)}")
        full_width = n_obj + self._problem.eval_data_length
        if evals.shape[1] == full_width:
            new_evdata = evals
            if eval_data is not None:
                raise ValueError("eval_data given although evals already contains it")
        elif evals.shape[1] == n_obj:
            if eval_data is not None:
                eval_data = jnp.asarray(eval_data, dtype=self._problem.eval_dtype)
                if eval_data.ndim == 1:
                    eval_data = eval_data[:, None]
                new_evdata = jnp.concatenate([evals, eval_data], axis=1)
            else:
                new_evdata = jnp.concatenate(
                    [
                        evals,
                        jnp.full(
                            (len(self), self._problem.eval_data_length),
                            jnp.nan,
                            dtype=self._problem.eval_dtype,
                        ),
                    ],
                    axis=1,
                ) if self._problem.eval_data_length else evals
        else:
            raise ValueError(
                f"evals has {evals.shape[1]} columns; expected {n_obj} or {full_width}"
            )
        self._set_evdata(new_evdata)

    def _set_evdata(self, new_evdata: jnp.ndarray):
        self._evdata = new_evdata
        if self._parent is not None:
            parent, indices = self._parent
            parent._scatter_evdata(indices, new_evdata)

    def _scatter_evdata(self, indices, evdata):
        self._evdata = self._evdata.at[jnp.asarray(indices)].set(evdata)
        if self._parent is not None:
            parent, parent_indices = self._parent
            parent._scatter_evdata(np.asarray(parent_indices)[np.asarray(indices)], evdata)

    def _set_values_array(self, values: jnp.ndarray):
        self._values = values
        if self._parent is not None:
            parent, indices = self._parent
            parent._scatter_values(indices, values)

    def _scatter_values(self, indices, values):
        if isinstance(self._values, ObjectArray):
            raise TypeError("Cannot scatter array values into an object-typed batch")
        self._values = self._values.at[jnp.asarray(indices)].set(values)
        if self._parent is not None:
            parent, parent_indices = self._parent
            parent._scatter_values(np.asarray(parent_indices)[np.asarray(indices)], values)

    def _scatter_object_values(self, indices, values):
        """Propagate object-dtype value writes up the parent chain (the
        numpy-view sharing of slice pieces covers plain slices; fancy-indexed
        pieces go through here)."""
        for i, v in zip(np.atleast_1d(indices), values):
            self._values[int(i)] = v
        if self._parent is not None:
            parent, parent_indices = self._parent
            parent._scatter_object_values(
                np.asarray(parent_indices)[np.atleast_1d(indices)], values
            )

    # ------------------------------------------------------------- selection
    def _utility_for_sort(self, obj_index: Optional[int]) -> jnp.ndarray:
        n_obj = self._problem.num_objectives
        if obj_index is None and n_obj > 1:
            return pareto_utility(
                self._evdata[:, :n_obj], objective_sense=self._problem.senses
            )
        i = 0 if obj_index is None else int(obj_index)
        col = self._evdata[:, i]
        return col if self._problem.senses[i] == "max" else -col

    def argsort(self, obj_index: Optional[int] = None) -> jnp.ndarray:
        """Indices sorted best-to-worst (reference ``core.py:3827``)."""
        return jnp.argsort(-self._utility_for_sort(obj_index))

    def argbest(self, obj_index: Optional[int] = None) -> jnp.ndarray:
        return jnp.argmax(self._utility_for_sort(obj_index))

    def argworst(self, obj_index: Optional[int] = None) -> jnp.ndarray:
        return jnp.argmin(self._utility_for_sort(obj_index))

    def take(self, indices) -> "SolutionBatch":
        """Sub-batch sharing eval scatter-back with this batch
        (reference ``core.py:4391``)."""
        return SolutionBatch(slice_of=(self, np.asarray(indices)))

    def take_best(self, n: Optional[int] = None, *, obj_index: Optional[int] = None) -> "SolutionBatch":
        """Best ``n`` solutions; NSGA-II pareto selection in multi-objective
        mode (reference ``core.py:4405-4429``)."""
        if n is None:
            idx = np.asarray(self.argbest(obj_index))[None]
        else:
            utilities = self._utility_for_sort(obj_index)
            idx = np.asarray(jnp.argsort(-utilities))[: int(n)]
        return self.take(idx)

    def compute_pareto_ranks(self) -> jnp.ndarray:
        """Front index per solution, 0 = best (reference ``core.py:3846``)."""
        n_obj = self._problem.num_objectives
        return pareto_ranks(self._evdata[:, :n_obj], objective_sense=self._problem.senses)

    def arg_pareto_sort(self) -> List[jnp.ndarray]:
        """Indices grouped by pareto front (reference ``core.py:3870``)."""
        ranks = np.asarray(self.compute_pareto_ranks())
        fronts = []
        for k in range(int(ranks.max()) + 1):
            fronts.append(jnp.asarray(np.nonzero(ranks == k)[0]))
        return fronts

    def utility(self, obj_index: int = 0, *, ranking_method: Optional[str] = None) -> jnp.ndarray:
        """Fitness-shaped utilities for one objective (reference ``core.py:4208``)."""
        col = self._evdata[:, int(obj_index)]
        method = "raw" if ranking_method is None else ranking_method
        return rank(col, method, higher_is_better=(self._problem.senses[int(obj_index)] == "max"))

    def utils(self, *, ranking_method: Optional[str] = None) -> jnp.ndarray:
        """Utilities for all objectives, shape ``(N, n_obj)``
        (reference ``core.py:4304``)."""
        cols = [
            self.utility(i, ranking_method=ranking_method)
            for i in range(self._problem.num_objectives)
        ]
        return jnp.stack(cols, axis=1)

    # ------------------------------------------------------------- structure
    def split(self, num_pieces: Optional[int] = None, *, max_size: Optional[int] = None) -> "SolutionBatchPieces":
        return SolutionBatchPieces(self, num_pieces=num_pieces, max_size=max_size)

    def concat(self, other: Union["SolutionBatch", Iterable["SolutionBatch"]]) -> "SolutionBatch":
        """This batch merged with other(s) (reference ``core.py:4371``)."""
        others = [other] if isinstance(other, SolutionBatch) else list(other)
        return SolutionBatch(merging_of=[self] + others)

    @classmethod
    def cat(cls, batches: Iterable["SolutionBatch"]) -> "SolutionBatch":
        """Concatenate batches (reference ``core.py:4580``)."""
        return cls(merging_of=list(batches))

    def to(self, device) -> "SolutionBatch":
        """Compatibility no-op: placement is controlled by shardings."""
        return self

    def __getitem__(self, i) -> Union["Solution", "SolutionBatch"]:
        if isinstance(i, slice):
            return SolutionBatch(slice_of=(self, i))
        # 0-d arrays (e.g. the result of argbest) index a single Solution
        if hasattr(i, "ndim"):
            if i.ndim == 0:
                return Solution(self, int(i))
            return SolutionBatch(slice_of=(self, i))
        if hasattr(i, "__len__") and not isinstance(i, str):
            return SolutionBatch(slice_of=(self, i))
        return Solution(self, int(i))

    def __iter__(self):
        for i in range(len(self)):
            yield Solution(self, i)

    def clone(self, *, memo: Optional[dict] = None) -> "SolutionBatch":
        if memo is None:
            memo = {}
        if id(self) in memo:
            return memo[id(self)]
        result = SolutionBatch(
            self._problem,  # batches share their problem (not deep-cloned)
            len(self),
            values=self._values.clone() if isinstance(self._values, ObjectArray) else self._values,
            evals=self._evdata,
        )
        memo[id(self)] = result
        return result

    def _get_cloned_state(self, *, memo: dict) -> dict:
        # the problem is kept by reference (pickle memoizes object identity;
        # deep-cloning it here would recurse problem -> best solutions ->
        # batches -> problem forever); parent links are detached, since a
        # pickled/cloned piece must not scatter into its old parent
        return {
            "_problem": self._problem,
            "_values": self._values.clone() if isinstance(self._values, ObjectArray) else self._values,
            "_evdata": self._evdata,
            "_parent": None,
        }

    def _printable_items(self):
        return {"size": len(self), "evaluated": self.is_evaluated}


class SolutionBatchPieces(RecursivePrintable):
    """Read-only list of slice views with scatter-back
    (reference ``core.py:4603-4727``)."""

    def __init__(self, batch: SolutionBatch, *, num_pieces: Optional[int] = None, max_size: Optional[int] = None):
        if (num_pieces is None) == (max_size is None):
            raise ValueError("Provide exactly one of num_pieces / max_size")
        n = len(batch)
        if max_size is not None:
            num_pieces = math.ceil(n / int(max_size))
        num_pieces = int(num_pieces)
        base = n // num_pieces
        rem = n % num_pieces
        self._bounds = []
        start = 0
        for i in range(num_pieces):
            size = base + (1 if i < rem else 0)
            self._bounds.append((start, start + size))
            start += size
        self._batch = batch
        self._pieces = [
            SolutionBatch(slice_of=(batch, slice(lo, hi))) for (lo, hi) in self._bounds
        ]

    def __getitem__(self, i) -> SolutionBatch:
        return self._pieces[i]

    def __len__(self) -> int:
        return len(self._pieces)

    def __iter__(self):
        return iter(self._pieces)

    def indices_of(self, i: int) -> tuple:
        """(row_begin, row_end) of piece ``i`` within the source batch."""
        return self._bounds[i]


class Solution(Serializable, RecursivePrintable):
    """A single row of a SolutionBatch, sharing its storage semantics
    (reference ``core.py:4742``)."""

    def __init__(self, batch: SolutionBatch, index: int):
        self._batch = batch
        self._index = int(index)

    @property
    def problem(self) -> Problem:
        return self._batch.problem

    @property
    def values(self):
        if is_factored(self._batch._values):
            # densify just this row: center + basis @ coeffs[i]
            lr = self._batch._values
            return lr.materialize_rows(lr.coeffs[self._index][None])[0]
        return self._batch._values[self._index]

    @property
    def evals(self) -> jnp.ndarray:
        return self._batch._evdata[self._index]

    @property
    def is_evaluated(self) -> bool:
        n_obj = self.problem.num_objectives
        return not bool(jnp.any(jnp.isnan(self.evals[:n_obj])))

    def set_values(self, values):
        if is_factored(self._batch._values):
            raise NotImplementedError(
                "Writing a single solution's values into a factored "
                "batch is not supported: an arbitrary dense row "
                "generally has no representation in the batch's basis"
            )
        if isinstance(self._batch._values, ObjectArray):
            self._batch._values[self._index] = values
            if self._batch._parent is not None:
                parent, parent_indices = self._batch._parent
                parent._scatter_object_values(
                    np.asarray(parent_indices)[[self._index]],
                    [self._batch._values[self._index]],
                )
        else:
            new = self._batch._values.at[self._index].set(
                jnp.asarray(values, dtype=self.problem.dtype)
            )
            self._batch._set_values_array(new)
        # changing a solution's values invalidates its evaluation results
        row_nan = jnp.full_like(self._batch._evdata[self._index], jnp.nan)
        self._batch._set_evdata(self._batch._evdata.at[self._index].set(row_nan))

    def set_evals(self, evals, eval_data=None):
        problem = self.problem
        n_obj = problem.num_objectives
        evals = jnp.atleast_1d(jnp.asarray(evals, dtype=problem.eval_dtype))
        if evals.shape[0] == n_obj + problem.eval_data_length:
            row = evals
        else:
            parts = [evals]
            if eval_data is not None:
                parts.append(jnp.atleast_1d(jnp.asarray(eval_data, dtype=problem.eval_dtype)))
            row = jnp.concatenate(parts)
            if row.shape[0] < n_obj + problem.eval_data_length:
                row = jnp.concatenate(
                    [
                        row,
                        jnp.full(
                            (n_obj + problem.eval_data_length - row.shape[0],),
                            jnp.nan,
                            dtype=problem.eval_dtype,
                        ),
                    ]
                )
        new_evdata = self._batch._evdata.at[self._index].set(row)
        self._batch._set_evdata(new_evdata)

    def set_evaluation(self, evaluation, eval_data=None):
        self.set_evals(evaluation, eval_data)

    def to_batch(self) -> SolutionBatch:
        return SolutionBatch(slice_of=(self._batch, slice(self._index, self._index + 1)))

    def clone(self, *, memo: Optional[dict] = None) -> "Solution":
        if memo is None:
            memo = {}
        if id(self) in memo:
            return memo[id(self)]
        problem = self.problem
        if isinstance(self._batch._values, ObjectArray):
            values = ObjectArray.from_values([self._batch._values[self._index]])
        elif is_factored(self._batch._values):
            values = self.values[None]
        else:
            values = self._batch._values[self._index][None]
        new_batch = SolutionBatch(problem, 1, values=values, evals=self._batch._evdata[self._index][None])
        result = Solution(new_batch, 0)
        memo[id(self)] = result
        return result

    def _get_cloned_state(self, *, memo: dict) -> dict:
        # keep the batch by reference: pickle memoizes it, and the chain
        # batch -> problem terminates there (see SolutionBatch._get_cloned_state)
        return {"_batch": self._batch, "_index": self._index}

    def _printable_items(self):
        return {"values": self.values, "evals": self.evals}


class ProblemBoundEvaluator:
    """Wraps a Problem as a pure-ish callable ``f(values) -> fitnesses`` for
    the functional algorithms (reference ``core.py:5109-5257``). Extra batch
    dims are handled by reshaping (explicitly not vmap-safe, mirroring
    ``core.py:3386-3392``, because evaluation may have host-side effects)."""

    def __init__(self, problem: Problem, *, obj_index: int = 0):
        self._problem = problem
        self._obj_index = int(obj_index)
        self._sense = problem.senses[self._obj_index]

    @property
    def problem(self) -> Problem:
        return self._problem

    @property
    def objective_sense(self) -> str:
        return self._sense

    def __call__(self, values) -> jnp.ndarray:
        values = jnp.asarray(values, dtype=self._problem.dtype)
        batch_shape = values.shape[:-2]
        if batch_shape:
            flat = values.reshape((-1, values.shape[-1]))
        else:
            flat = values
        batch = SolutionBatch(self._problem, flat.shape[0], values=flat)
        self._problem.evaluate(batch)
        fitnesses = batch.evals[:, self._obj_index]
        if batch_shape:
            fitnesses = fitnesses.reshape(batch_shape + (values.shape[-2],))
        return fitnesses
