"""Lazy, memoized status reporting.

Parity: reference ``algorithms/searchalgorithm.py:34-238`` (``LazyReporter``
and ``LazyStatusDict``). Lives in ``tools`` (not ``algorithms``) because on
TPU *Problems* report lazily too: best/worst solutions are tracked as device
arrays and must not be pulled to the host until someone actually reads the
status — otherwise every generation forces a device sync
(VERDICT r1 "what's weak" #3).
"""

from __future__ import annotations

__all__ = ["LazyReporter", "LazyStatusDict"]


class LazyReporter:
    """Lazy, memoized status providers (reference ``searchalgorithm.py:34``).

    Subclasses declare status items by passing ``name=getter_function`` pairs
    to ``__init__``; each getter runs at most once per step."""

    def __init__(self, **kwargs):
        self._getters: dict = {}
        self._computed: dict = {}
        self.update_status_getters(kwargs)

    def update_status_getters(self, getters: dict):
        self._getters.update(getters)

    # reference name (searchalgorithm.py uses add_status_getters)
    add_status_getters = update_status_getters

    def clear_status(self):
        self._computed = {}

    def update_status(self, additional_status: dict):
        for k, v in additional_status.items():
            if k not in self._getters:
                self._computed[k] = v

    def has_status_key(self, key: str) -> bool:
        return key in self._computed or key in self._getters

    def iter_status_keys(self):
        seen = set()
        for k in self._computed:
            seen.add(k)
            yield k
        for k in self._getters:
            if k not in seen:
                yield k

    def get_status_value(self, key: str):
        if key in self._computed:
            return self._computed[key]
        if key in self._getters:
            value = self._getters[key]()
            self._computed[key] = value
            return value
        raise KeyError(key)

    @property
    def status(self) -> "LazyStatusDict":
        return LazyStatusDict(self)


class LazyStatusDict:
    """Mapping view over a LazyReporter (reference ``searchalgorithm.py:180``)."""

    def __init__(self, reporter: LazyReporter):
        self._reporter = reporter

    def __getitem__(self, key):
        return self._reporter.get_status_value(key)

    def __contains__(self, key):
        return self._reporter.has_status_key(key)

    def __iter__(self):
        return self._reporter.iter_status_keys()

    def __len__(self):
        return sum(1 for _ in self._reporter.iter_status_keys())

    def keys(self):
        return list(iter(self))

    def items(self):
        # a lazy getter may declare its entry "not ready yet" by raising
        # KeyError (e.g. best-solution tracking before any valid evaluation);
        # iteration simply skips such entries
        for k in self:
            try:
                yield k, self[k]
            except KeyError:
                continue

    def values(self):
        for k, v in self.items():
            yield v

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __repr__(self):
        return f"<status {self.keys()}>"
