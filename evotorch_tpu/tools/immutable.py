"""Immutable containers — storage discipline for object-dtype solutions.

Parity: reference ``tools/immutable.py:27-289`` (``as_immutable``,
``mutable_copy``, ``ImmutableList/Set/Dict``). Object-dtype problems are
host-side in the TPU build (SURVEY.md §7, hard parts), so these containers are
plain Python, with jax/numpy arrays frozen on entry.
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence, Set as AbstractSet
from typing import Any, Iterable

import jax
import numpy as np

__all__ = [
    "ImmutableContainer",
    "ImmutableList",
    "ImmutableSet",
    "ImmutableDict",
    "as_immutable",
    "mutable_copy",
    "is_immutable",
]


class ImmutableContainer:
    """Marker base class."""


class ImmutableList(ImmutableContainer, Sequence):
    def __init__(self, iterable: Iterable = ()):
        self._data = tuple(as_immutable(x) for x in iterable)

    def __getitem__(self, i):
        if isinstance(i, slice):
            result = ImmutableList.__new__(ImmutableList)
            result._data = self._data[i]
            return result
        return self._data[i]

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        if isinstance(other, ImmutableList):
            return self._data == other._data
        if isinstance(other, (list, tuple)):
            return list(self._data) == list(other)
        return NotImplemented

    def __hash__(self):
        return hash(self._data)

    def __repr__(self):
        return f"ImmutableList({list(self._data)!r})"


class ImmutableSet(ImmutableContainer, AbstractSet):
    def __init__(self, iterable: Iterable = ()):
        self._data = frozenset(as_immutable(x) for x in iterable)

    def __contains__(self, x):
        return x in self._data

    def __iter__(self):
        return iter(self._data)

    def __len__(self):
        return len(self._data)

    def __repr__(self):
        return f"ImmutableSet({set(self._data)!r})"


class ImmutableDict(ImmutableContainer, Mapping):
    def __init__(self, mapping: Any = (), **kwargs):
        items = dict(mapping, **kwargs)
        self._data = {as_immutable(k): as_immutable(v) for k, v in items.items()}

    def __getitem__(self, k):
        return self._data[k]

    def __iter__(self):
        return iter(self._data)

    def __len__(self):
        return len(self._data)

    def __repr__(self):
        return f"ImmutableDict({self._data!r})"


def _frozen_numpy(arr: np.ndarray) -> np.ndarray:
    result = arr.copy()
    result.setflags(write=False)
    return result


def as_immutable(x: Any) -> Any:
    """Convert ``x`` into an immutable counterpart (reference
    ``immutable.py:137``): jax.Arrays pass through (already immutable), numpy
    arrays are frozen copies, containers become Immutable* containers, and
    ObjectArrays become read-only views."""
    from .objectarray import ObjectArray

    if isinstance(x, ObjectArray):
        return x.get_read_only_view()
    if isinstance(x, jax.Array):
        return x
    if isinstance(x, np.ndarray):
        if x.dtype == object:
            return ImmutableList(x.tolist())
        return _frozen_numpy(x)
    if isinstance(x, ImmutableContainer):
        return x
    if isinstance(x, Mapping):
        return ImmutableDict(x)
    if isinstance(x, (set, frozenset)):
        return ImmutableSet(x)
    if isinstance(x, (list, tuple)):
        return ImmutableList(x)
    if isinstance(x, (int, float, complex, bool, str, bytes, type(None), np.generic)):
        return x
    raise TypeError(f"Cannot make object of type {type(x)} immutable")


def mutable_copy(x: Any) -> Any:
    """Inverse of :func:`as_immutable` (reference ``immutable.py:100``)."""
    from .objectarray import ObjectArray

    if isinstance(x, ObjectArray):
        return x.clone()
    if isinstance(x, jax.Array):
        return np.asarray(x).copy()
    if isinstance(x, np.ndarray):
        return x.copy()
    if isinstance(x, ImmutableList):
        return [mutable_copy(v) for v in x]
    if isinstance(x, ImmutableSet):
        return {mutable_copy(v) for v in x}
    if isinstance(x, ImmutableDict):
        return {mutable_copy(k): mutable_copy(v) for k, v in x.items()}
    return x


def is_immutable(x: Any) -> bool:
    from .objectarray import ObjectArray

    if isinstance(x, ObjectArray):
        return x.is_read_only
    if isinstance(x, ImmutableContainer):
        return True
    if isinstance(x, jax.Array):
        return True
    if isinstance(x, np.ndarray):
        return not x.flags.writeable
    return isinstance(x, (int, float, complex, bool, str, bytes, type(None)))
