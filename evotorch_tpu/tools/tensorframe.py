"""``TensorFrame``: a pandas-like columnar table of device arrays.

Parity: reference ``tools/tensorframe.py:53-1338`` (columnar table of
tensors, vmap-compatible, with the ``Picker`` row indexer). Implemented as a
pytree dataclass of named equal-length columns, so whole frames pass through
``jit``/``vmap``/``scan``; mutating operations return new frames.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from .pytree import pytree_dataclass, static_field

__all__ = ["TensorFrame", "Picker"]


def _as_column(v, length: Optional[int]) -> jnp.ndarray:
    arr = jnp.asarray(v)
    if arr.ndim == 0 and length is not None:
        arr = jnp.broadcast_to(arr, (length,))
    return arr


@pytree_dataclass
class TensorFrame:
    columns: tuple = static_field()
    data: tuple = ()  # arrays aligned with `columns`

    # ------------------------------------------------------------- factories
    @staticmethod
    def create(data: Optional[Dict[str, Any]] = None, **kwargs) -> "TensorFrame":
        items = dict(data or {}, **kwargs)
        length = None
        for v in items.values():
            arr = jnp.asarray(v)
            if arr.ndim > 0:
                length = arr.shape[0]
                break
        cols = tuple(items.keys())
        arrays = tuple(_as_column(v, length) for v in items.values())
        lengths = {a.shape[0] for a in arrays if a.ndim > 0}
        if len(lengths) > 1:
            raise ValueError(f"Columns have differing lengths: {lengths}")
        return TensorFrame(columns=cols, data=arrays)

    # ------------------------------------------------------------ properties
    def __len__(self) -> int:
        for a in self.data:
            if a.ndim > 0:
                return int(a.shape[0])
        return 0

    @property
    def column_names(self) -> tuple:
        return self.columns

    def as_dict(self) -> Dict[str, jnp.ndarray]:
        return dict(zip(self.columns, self.data))

    # --------------------------------------------------------------- columns
    def __getitem__(self, key):
        if isinstance(key, str):
            try:
                return self.data[self.columns.index(key)]
            except ValueError:
                raise KeyError(f"No column named {key!r} (have {self.columns})") from None
        # boolean mask / index array / slice row selection
        return self.pick[key]

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self.columns:
            return self.data[self.columns.index(name)]
        raise AttributeError(name)

    def with_columns(self, **new_columns) -> "TensorFrame":
        """Frame with columns added or replaced (functional assignment)."""
        length = len(self) if self.data else None
        items = self.as_dict()
        for k, v in new_columns.items():
            items[k] = _as_column(v, length)
        return TensorFrame(columns=tuple(items.keys()), data=tuple(items.values()))

    def without_columns(self, *names) -> "TensorFrame":
        items = {k: v for k, v in self.as_dict().items() if k not in names}
        return TensorFrame(columns=tuple(items.keys()), data=tuple(items.values()))

    # ----------------------------------------------------------------- rows
    @property
    def pick(self) -> "Picker":
        """Row indexer (reference ``Picker``): ``frame.pick[mask_or_indices]``."""
        return Picker(self)

    def take(self, indices) -> "TensorFrame":
        indices = jnp.asarray(indices)
        return TensorFrame(
            columns=self.columns,
            data=tuple(a[indices] for a in self.data),
        )

    def sort_values(self, by: str, *, descending: bool = False) -> "TensorFrame":
        key = self[by]
        order = jnp.argsort(-key if descending else key)
        return self.take(order)

    def concat(self, other: "TensorFrame") -> "TensorFrame":
        if self.columns != other.columns:
            raise ValueError("Cannot concat frames with different columns")
        return TensorFrame(
            columns=self.columns,
            data=tuple(jnp.concatenate([a, b]) for a, b in zip(self.data, other.data)),
        )

    # ---------------------------------------------------------------- output
    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: np.asarray(v) for k, v in self.as_dict().items()})

    def __repr__(self) -> str:
        parts = ", ".join(f"{k}: {tuple(v.shape)}" for k, v in self.as_dict().items())
        return f"<TensorFrame len={len(self)} {{{parts}}}>"


class Picker:
    """Row indexer over a TensorFrame (reference ``tensorframe.py`` ``Picker``)."""

    def __init__(self, frame: TensorFrame):
        self._frame = frame

    def __getitem__(self, selector) -> TensorFrame:
        frame = self._frame
        if isinstance(selector, slice):
            return TensorFrame(
                columns=frame.columns, data=tuple(a[selector] for a in frame.data)
            )
        selector = jnp.asarray(selector)
        if selector.dtype == jnp.bool_:
            selector = jnp.nonzero(selector)[0]
        return frame.take(selector)
