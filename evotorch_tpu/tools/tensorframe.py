"""``TensorFrame``: a pandas-like columnar table of device arrays.

Parity: reference ``tools/tensorframe.py:53-1338`` (columnar table of
tensors, vmap-compatible, with the ``Picker`` row indexer supporting both
``frame.pick[rows]`` and ``frame.pick[rows, columns]`` addressing, row
assignment, ``hstack``/``vstack``/``join``, ``argsort``/``sort``/
``nlargest``/``nsmallest``, and the vmapped per-row ``each``). Implemented as
a pytree dataclass of named equal-length columns, so whole frames pass
through ``jit``/``vmap``/``scan``.

TPU-first deviation: frames are immutable pytrees, so the reference's
in-place ``frame.pick[rows] = values`` becomes the functional
``frame.pick_set(rows, values)`` (returning a new frame); boolean-mask
assignment lowers to ``jnp.where`` so it stays jit/vmap-traceable.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .pytree import pytree_dataclass, static_field

__all__ = ["TensorFrame", "Picker"]


def _as_column(v, length: Optional[int]) -> jnp.ndarray:
    arr = jnp.asarray(v)
    if arr.ndim == 0 and length is not None:
        arr = jnp.broadcast_to(arr, (length,))
    return arr


@pytree_dataclass
class TensorFrame:
    columns: tuple = static_field()
    data: tuple = ()  # arrays aligned with `columns`

    # ------------------------------------------------------------- factories
    @staticmethod
    def create(data: Optional[Dict[str, Any]] = None, **kwargs) -> "TensorFrame":
        items = dict(data or {}, **kwargs)
        length = None
        for v in items.values():
            arr = jnp.asarray(v)
            if arr.ndim > 0:
                length = arr.shape[0]
                break
        cols = tuple(items.keys())
        arrays = tuple(_as_column(v, length) for v in items.values())
        lengths = {a.shape[0] for a in arrays if a.ndim > 0}
        if len(lengths) > 1:
            raise ValueError(f"Columns have differing lengths: {lengths}")
        return TensorFrame(columns=cols, data=arrays)

    # ------------------------------------------------------------ properties
    def __len__(self) -> int:
        for a in self.data:
            if a.ndim > 0:
                return int(a.shape[0])
        return 0

    @property
    def column_names(self) -> tuple:
        return self.columns

    def as_dict(self) -> Dict[str, jnp.ndarray]:
        return dict(zip(self.columns, self.data))

    # --------------------------------------------------------------- columns
    def __getitem__(self, key):
        if isinstance(key, str):
            try:
                return self.data[self.columns.index(key)]
            except ValueError:
                raise KeyError(f"No column named {key!r} (have {self.columns})") from None
        # boolean mask / index array / slice row selection
        return self.pick[key]

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self.columns:
            return self.data[self.columns.index(name)]
        raise AttributeError(name)

    def with_columns(self, **new_columns) -> "TensorFrame":
        """Frame with columns added or replaced (functional assignment)."""
        length = len(self) if self.data else None
        items = self.as_dict()
        for k, v in new_columns.items():
            items[k] = _as_column(v, length)
        return TensorFrame(columns=tuple(items.keys()), data=tuple(items.values()))

    def without_columns(self, *names) -> "TensorFrame":
        items = {k: v for k, v in self.as_dict().items() if k not in names}
        return TensorFrame(columns=tuple(items.keys()), data=tuple(items.values()))

    # ----------------------------------------------------------------- rows
    @property
    def pick(self) -> "Picker":
        """Row indexer (reference ``Picker``): ``frame.pick[mask_or_indices]``."""
        return Picker(self)

    def take(self, indices) -> "TensorFrame":
        indices = jnp.asarray(indices)
        return TensorFrame(
            columns=self.columns,
            data=tuple(a[indices] for a in self.data),
        )

    def argsort(self, by: str, *, descending: bool = False) -> jnp.ndarray:
        """Indices that would sort the frame by column ``by``
        (reference ``tensorframe.py:807``)."""
        key = self[by]
        return jnp.argsort(-key if descending else key)

    def sort_values(self, by: str, *, descending: bool = False) -> "TensorFrame":
        return self.take(self.argsort(by, descending=descending))

    # the reference's shorter name
    def sort(self, by: str, *, descending: bool = False) -> "TensorFrame":
        return self.sort_values(by, descending=descending)

    def nlargest(self, n: int, by: str) -> "TensorFrame":
        """The ``n`` rows with the largest values under column ``by``
        (reference ``tensorframe.py:1060``)."""
        return self.take(self.argsort(by, descending=True)[: int(n)])

    def nsmallest(self, n: int, by: str) -> "TensorFrame":
        return self.take(self.argsort(by)[: int(n)])

    def concat(self, other: "TensorFrame") -> "TensorFrame":
        if self.columns != other.columns:
            raise ValueError("Cannot concat frames with different columns")
        return TensorFrame(
            columns=self.columns,
            data=tuple(jnp.concatenate([a, b]) for a, b in zip(self.data, other.data)),
        )

    # the reference's name for row-wise concatenation
    def vstack(self, other: "TensorFrame") -> "TensorFrame":
        return self.concat(other)

    def hstack(self, other: "TensorFrame", *, override: bool = False) -> "TensorFrame":
        """Column-wise join (reference ``tensorframe.py:881``). Overlapping
        column names raise unless ``override=True``, in which case ``other``'s
        values win."""
        overlap = set(self.columns) & set(other.columns)
        if overlap and not override:
            raise ValueError(
                f"Overlapping columns {sorted(overlap)}; pass override=True to"
                " let the right-hand frame's values take precedence"
            )
        return self.with_columns(**other.as_dict())

    def join(self, other: "TensorFrame") -> "TensorFrame":
        """pandas-style alias of :meth:`hstack`
        (reference ``tensorframe.py:1092``)."""
        return self.hstack(other)

    def drop(self, *, columns) -> "TensorFrame":
        """Frame without the given column(s)
        (reference ``tensorframe.py:1107``)."""
        if isinstance(columns, str):
            columns = [columns]
        missing = set(columns) - set(self.columns)
        if missing:
            raise ValueError(f"Cannot drop unknown columns: {sorted(missing)}")
        return self.without_columns(*columns)

    # ------------------------------------------------------------- row write
    def pick_set(self, rows, new_values, columns=None) -> "TensorFrame":
        """Functional row assignment — the immutable form of the reference's
        ``frame.pick[rows] = values`` (``tensorframe.py:1306-1338``).

        ``rows`` may be a slice, an integer index array, or a boolean mask
        (the mask form lowers to ``jnp.where``, so it is jit/vmap-safe).
        ``new_values`` may be an array (single target column), a mapping of
        column name -> values, or another ``TensorFrame``.
        """
        if isinstance(new_values, TensorFrame):
            updates = new_values.as_dict()
        elif isinstance(new_values, Mapping):
            updates = dict(new_values)
        else:
            if columns is None:
                raise ValueError(
                    "When new_values is a plain array, pass the target column"
                    " via `columns=`"
                )
            if isinstance(columns, str):
                columns = [columns]
            if len(columns) != 1:
                raise ValueError(
                    "A plain-array right-hand side updates exactly one column"
                )
            updates = {columns[0]: new_values}
        if columns is not None:
            target = [columns] if isinstance(columns, str) else list(columns)
            if set(target) != set(updates):
                raise ValueError(
                    f"Target columns {sorted(target)} do not match the"
                    f" right-hand side columns {sorted(updates)}"
                )
        unknown = set(updates) - set(self.columns)
        if unknown:
            raise KeyError(f"No such column(s): {sorted(unknown)}")

        def write(current, new):
            new = jnp.asarray(new, current.dtype)
            if isinstance(rows, slice):
                return current.at[rows].set(new)
            sel = jnp.asarray(rows)
            if sel.dtype == jnp.bool_:
                m = sel.reshape(sel.shape + (1,) * (current.ndim - 1))
                return jnp.where(m, jnp.broadcast_to(new, current.shape), current)
            return current.at[sel].set(new)

        out = {}
        for name, col in self.as_dict().items():
            out[name] = write(col, updates[name]) if name in updates else col
        return TensorFrame(columns=self.columns, data=tuple(out.values()))

    # ----------------------------------------------------------- row compute
    def each(
        self,
        fn: Callable[[dict], dict],
        *,
        join: bool = False,
        override: bool = False,
    ) -> "TensorFrame":
        """Apply ``fn`` (dict-of-scalars -> dict-of-scalars) to every row,
        vectorized with ``jax.vmap`` (reference ``tensorframe.py:953`` uses
        ``torch.vmap`` the same way). With ``join=True`` the input columns are
        kept alongside the outputs (``override=True`` lets new columns shadow
        same-named inputs)."""
        if (not join) and override:
            raise ValueError("override=True requires join=True")
        out = jax.vmap(fn)(self.as_dict())
        result = TensorFrame.create(out)
        if join:
            return self.hstack(result, override=override)
        return result

    # ---------------------------------------------------------------- output
    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: np.asarray(v) for k, v in self.as_dict().items()})

    def __repr__(self) -> str:
        parts = ", ".join(f"{k}: {tuple(v.shape)}" for k, v in self.as_dict().items())
        return f"<TensorFrame len={len(self)} {{{parts}}}>"


class Picker:
    """Row indexer over a TensorFrame (reference ``tensorframe.py:1270``):
    ``frame.pick[rows]`` or ``frame.pick[rows, columns]`` where ``columns``
    is a name, a list of names, or ``:``. Assignment is functional —
    use :meth:`TensorFrame.pick_set` (immutability deviation, see module
    docstring); ``pick[...] = ...`` raises with that pointer."""

    def __init__(self, frame: TensorFrame):
        self._frame = frame

    @staticmethod
    def _unpack(frame: TensorFrame, location):
        if isinstance(location, tuple):
            rows, columns = location
            if isinstance(columns, str):
                columns = [columns]
            elif isinstance(columns, slice):
                if columns != slice(None):
                    raise ValueError("For columns, only ':' is supported")
                columns = list(frame.columns)
            else:
                columns = [str(c) for c in columns]
        else:
            rows, columns = location, list(frame.columns)
        return rows, columns

    def __getitem__(self, location) -> TensorFrame:
        frame = self._frame
        rows, columns = self._unpack(frame, location)
        sub = {name: frame[name] for name in columns}
        if isinstance(rows, slice):
            data = {k: v[rows] for k, v in sub.items()}
        else:
            sel = jnp.asarray(rows)
            if sel.dtype == jnp.bool_:
                sel = jnp.nonzero(sel)[0]
            data = {k: v[sel] for k, v in sub.items()}
        return TensorFrame(columns=tuple(data.keys()), data=tuple(data.values()))

    def __setitem__(self, location, new_values):
        raise TypeError(
            "TensorFrames are immutable pytrees; use"
            " frame.pick_set(rows, values, columns=...) which returns the"
            " updated frame"
        )
