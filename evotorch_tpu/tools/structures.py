"""Vectorization-friendly batched data structures.

Parity: reference ``tools/structures.py`` (2457 LoC) — ``CMemory``
(``structures.py:60-786``), ``CDict`` (``structures.py:892``), ``CList``
(circular-buffer list, ``structures.py:1380``), ``CBag``
(``structures.py:2024``), ``do_where`` (``structures.py:33``). All contiguous
tensors with masked updates, usable under ``vmap``/``jit``.

Batching comes in two interchangeable forms, exactly as in the reference:

- **explicit batch shapes** — ``create(..., batch_shape=(B,))`` allocates a
  contiguous batch of structures; keys/values/``where`` masks then carry the
  batch shape on the left and every element addresses its own block;
- **vmap** — an unbatched structure is a pytree of arrays, so ``jax.vmap``
  over a stacked structure provides the same semantics (this is what the
  reference's ``expects_ndim`` machinery emulates; JAX gives it natively).

TPU-first deviations (documented, deliberate):

- jax arrays are immutable, so the reference's in-place methods (``set_``,
  ``add_``, ``append_``, ...) here RETURN the updated structure (pytree
  dataclasses) instead of mutating; the trailing-underscore names are kept so
  reference code maps 1:1 after adding an assignment.
- the reference's ``verify`` flag raises on invalid keys eagerly; under jit
  nothing can raise data-dependently, so invalid keys are always handled the
  masked way (ignored on write, ``default``-filled on read) — the
  reference's ``verify=False`` behavior.
- ``CBag`` keeps per-key *counts* instead of a shuffled slot array: sampling
  a random present element and decrementing its count IS sampling without
  replacement, with identical distribution, in O(num_keys) fully-vectorized
  work and without carrying a PRNG state inside the structure (keys are
  passed explicitly, the JAX way).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .pytree import pytree_dataclass, replace, static_field

__all__ = ["do_where", "CMemory", "CDict", "CList", "CBag"]


def do_where(mask, a: Any, b: Any) -> Any:
    """Pytree-wide ``where`` (reference ``structures.py:33``)."""

    def pick(x, y):
        m = jnp.reshape(mask, jnp.shape(mask) + (1,) * (jnp.ndim(x) - jnp.ndim(mask)))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(pick, a, b)


def _open_grid(batch_shape: tuple) -> tuple:
    """ogrid-style index arrays that broadcast to ``batch_shape`` (used to
    make every batch element address its own block in one gather/scatter)."""
    nb = len(batch_shape)
    out = []
    for i, d in enumerate(batch_shape):
        shape = [1] * nb
        shape[i] = d
        out.append(jnp.arange(d).reshape(shape))
    return tuple(out)


def _as_tuple(x, n: int, what: str) -> tuple:
    if isinstance(x, (tuple, list)):
        if len(x) != n:
            raise ValueError(f"{what} must have {n} element(s), got {x!r}")
        return tuple(int(v) for v in x)
    return (int(x),) * n


@pytree_dataclass
class CMemory:
    """Batched key -> tensor memory with masked updates
    (reference ``structures.py:60-786``).

    Keys are integers in ``[key_offset, key_offset + num_keys)`` — or, with a
    tuple-valued ``num_keys``, tuples of integers addressing a multi-dim key
    space. With a ``batch_shape``, the object is a contiguous batch of
    memories: keys, values and ``where`` masks carry the batch shape on the
    left and each batch element reads/writes its own block.
    """

    data: jnp.ndarray  # (*batch_shape, *key_shape, *value_shape)
    batch_ndim: int = static_field(default=0)
    key_ndim: int = static_field(default=1)
    key_offset: Optional[tuple] = static_field(default=None)

    @staticmethod
    def create(
        num_keys,
        *value_shape: int,
        dtype=jnp.float32,
        fill: float = 0.0,
        batch_shape: tuple = (),
        key_offset=None,
    ) -> "CMemory":
        if isinstance(num_keys, (tuple, list)):
            key_shape = tuple(int(n) for n in num_keys)
        else:
            key_shape = (int(num_keys),)
        batch_shape = tuple(int(b) for b in batch_shape)
        offset = (
            None
            if key_offset is None
            else _as_tuple(key_offset, len(key_shape), "key_offset")
        )
        shape = batch_shape + key_shape + tuple(int(s) for s in value_shape)
        return CMemory(
            data=jnp.full(shape, fill, dtype=dtype),
            batch_ndim=len(batch_shape),
            key_ndim=len(key_shape),
            key_offset=offset,
        )

    # ------------------------------------------------------------ properties
    @property
    def batch_shape(self) -> tuple:
        return self.data.shape[: self.batch_ndim]

    @property
    def is_batched(self) -> bool:
        return self.batch_ndim > 0

    @property
    def key_shape(self) -> tuple:
        return self.data.shape[self.batch_ndim : self.batch_ndim + self.key_ndim]

    @property
    def num_keys(self):
        ks = self.key_shape
        return ks[0] if self.key_ndim == 1 else ks

    @property
    def value_shape(self) -> tuple:
        return self.data.shape[self.batch_ndim + self.key_ndim :]

    @property
    def value_ndim(self) -> int:
        return len(self.value_shape)

    @property
    def dtype(self):
        return self.data.dtype

    # ------------------------------------------------------------ addressing
    def _normalize_keys(self, key) -> Tuple[tuple, jnp.ndarray]:
        """-> (per-dim key arrays broadcast to batch_shape, validity mask)."""
        ks = self.key_shape
        kd = self.key_ndim
        if kd > 1:
            if isinstance(key, (tuple, list)):
                parts = [jnp.asarray(k) for k in key]
                if len(parts) != kd:
                    raise ValueError(
                        f"Expected {kd} key components, got {len(parts)}"
                    )
            else:
                arr = jnp.asarray(key)  # trailing dim = key dims
                parts = [arr[..., i] for i in range(kd)]
        else:
            parts = [jnp.asarray(key)]
        if self.key_offset is not None:
            parts = [p - o for p, o in zip(parts, self.key_offset)]
        # keys broadcast against the batch shape, and may carry EXTRA leading
        # dims — an unbatched memory indexed with an array of keys gathers
        # (the reference's plain multi-element indexing), and a batched one
        # accepts (K, *batch_shape) key stacks
        common = self.batch_shape
        for p in parts:
            common = jnp.broadcast_shapes(common, p.shape)
        parts = [jnp.broadcast_to(p, common) for p in parts]
        valid = jnp.ones(common, dtype=bool)
        for p, d in zip(parts, ks):
            valid = valid & (p >= 0) & (p < d)
        return tuple(parts), valid

    def _address(self, parts: tuple) -> tuple:
        clipped = tuple(
            jnp.clip(p, 0, d - 1) for p, d in zip(parts, self.key_shape)
        )
        return _open_grid(self.batch_shape) + clipped

    # ------------------------------------------------------------ read/write
    def get(self, key, default=None) -> jnp.ndarray:
        parts, valid = self._normalize_keys(key)
        value = self.data[self._address(parts)]
        if default is not None:
            value = do_where(
                valid,
                value,
                jnp.broadcast_to(jnp.asarray(default, self.data.dtype), value.shape),
            )
        return value

    def __getitem__(self, key) -> jnp.ndarray:
        return self.get(key)

    def _apply(self, key, op, value, where) -> "CMemory":
        # Gather-style calls (extra leading key dims) with DUPLICATE keys
        # apply last-write-wins — each slot takes one read-modify-write, so
        # e.g. add_ with a key appearing twice adds once, matching torch's
        # non-accumulating index_put_ (the reference's write primitive). Use
        # one call per increment (or pre-reduce host-side) to accumulate.
        parts, valid = self._normalize_keys(key)
        idx = self._address(parts)
        current = self.data[idx]
        value = jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape)
        new = op(current, value)
        mask = valid
        if where is not None:
            mask = mask & jnp.broadcast_to(jnp.asarray(where), valid.shape)
        new = do_where(mask, new, current)
        return replace(self, data=self.data.at[idx].set(new))

    def set_(self, key, value, where=None) -> "CMemory":
        """Masked overwrite (reference ``structures.py:555``)."""
        return self._apply(key, lambda cur, v: v, value, where)

    def add_(self, key, value, where=None) -> "CMemory":
        return self._apply(key, lambda cur, v: cur + v, value, where)

    def subtract_(self, key, value, where=None) -> "CMemory":
        return self._apply(key, lambda cur, v: cur - v, value, where)

    def multiply_(self, key, value, where=None) -> "CMemory":
        return self._apply(key, lambda cur, v: cur * v, value, where)

    def divide_(self, key, value, where=None) -> "CMemory":
        return self._apply(key, lambda cur, v: cur / v, value, where)

    def add_circular_(self, key, value, mod, where=None) -> "CMemory":
        """``slot = (slot + value) % mod``, masked
        (reference ``structures.py:606``)."""
        mod = jnp.asarray(mod, self.data.dtype)
        return self._apply(key, lambda cur, v: (cur + v) % mod, value, where)


@pytree_dataclass
class CDict:
    """Batchable dictionary: a :class:`CMemory` plus per-key existence flags
    (reference ``structures.py:892``).

    Two key modes are supported:

    - **integer keys** (the reference's semantics): ``CDict.create(num_keys,
      *value_shape)`` — keys are integers (or tuples, with tuple-valued
      ``num_keys``), traceable under jit, and the dict can carry an explicit
      ``batch_shape``;
    - **named keys** (a host-side convenience this framework adds):
      ``CDict.create(["alpha", "beta"], *value_shape)`` — a static hashable
      namespace resolved to slot indices at trace time.

    ``set_`` flags existence; the arithmetic updates (``add_`` etc.) modify
    values but do not change existence (reference semantics); ``get`` with a
    ``default`` returns the default for missing keys; ``clear`` resets
    existence flags (not values), optionally masked per batch element.
    """

    memory: CMemory
    exist: jnp.ndarray  # (*batch_shape, *key_shape) bool
    names: Optional[tuple] = static_field(default=None)

    @staticmethod
    def create(
        keys_or_num_keys=None,
        *value_shape: int,
        dtype=jnp.float32,
        fill: float = 0.0,
        batch_shape: tuple = (),
        key_offset=None,
        names=None,
        num_keys=None,
    ) -> "CDict":
        """Positional dispatch: an int (or tuple of ints) is a key-space
        shape; any other iterable is a name list. A sequence of *integer
        names* is indistinguishable positionally — pass the explicit
        ``names=[...]`` / ``num_keys=...`` keywords to disambiguate."""
        if names is not None or num_keys is not None:
            if keys_or_num_keys is not None:
                raise TypeError(
                    "Pass either the positional keys_or_num_keys or the"
                    " explicit names=/num_keys= keywords, not both"
                )
            if names is not None and num_keys is not None:
                raise TypeError("names= and num_keys= are mutually exclusive")
            if names is not None:
                names = tuple(names)
                num_keys = len(names)
        elif keys_or_num_keys is None:
            raise TypeError("CDict.create needs keys_or_num_keys, names= or num_keys=")
        else:
            num_keys = keys_or_num_keys
            if not isinstance(keys_or_num_keys, int) and not (
                isinstance(keys_or_num_keys, (tuple, list))
                and all(isinstance(k, int) for k in keys_or_num_keys)
            ):
                names = tuple(keys_or_num_keys)
                num_keys = len(names)
        memory = CMemory.create(
            num_keys,
            *value_shape,
            dtype=dtype,
            fill=fill,
            batch_shape=batch_shape,
            key_offset=key_offset,
        )
        exist = jnp.zeros(memory.batch_shape + memory.key_shape, dtype=bool)
        return CDict(memory=memory, exist=exist, names=names)

    def _key(self, key):
        if self.names is None:
            return key
        try:
            return self.names.index(key)
        except ValueError:
            raise KeyError(f"Unknown key: {key!r} (known: {self.names})") from None

    # ------------------------------------------------------------ properties
    @property
    def batch_shape(self) -> tuple:
        return self.memory.batch_shape

    @property
    def is_batched(self) -> bool:
        return self.memory.is_batched

    @property
    def value_shape(self) -> tuple:
        return self.memory.value_shape

    @property
    def dtype(self):
        return self.memory.dtype

    @property
    def data(self) -> jnp.ndarray:
        return self.memory.data

    # ------------------------------------------------------------ read/write
    def contains(self, key) -> jnp.ndarray:
        """Existence flag(s) for the given key(s)
        (reference ``structures.py:1313``)."""
        key = self._key(key)
        parts, valid = self.memory._normalize_keys(key)
        return self.exist[self.memory._address(parts)] & valid

    def get(self, key, default=None) -> jnp.ndarray:
        """Value(s) at ``key``; where a ``default`` is given, missing or
        invalid keys yield the default instead of the stored filler."""
        key = self._key(key)
        if default is None:
            return self.memory.get(key)
        parts, valid = self.memory._normalize_keys(key)
        idx = self.memory._address(parts)
        present = valid & self.exist[idx]
        value = self.memory.data[idx]
        return do_where(
            present,
            value,
            jnp.broadcast_to(jnp.asarray(default, self.dtype), value.shape),
        )

    def __getitem__(self, key) -> jnp.ndarray:
        return self.get(key)

    def set_(self, key, value, where=None) -> "CDict":
        """Masked overwrite; flags the key as existing."""
        key = self._key(key)
        parts, valid = self.memory._normalize_keys(key)
        mask = valid
        if where is not None:
            mask = mask & jnp.broadcast_to(jnp.asarray(where), self.batch_shape)
        idx = self.memory._address(parts)
        new_exist = self.exist.at[idx].set(self.exist[idx] | mask)
        return CDict(
            memory=self.memory.set_(key, value, where),
            exist=new_exist,
            names=self.names,
        )

    def _arith(self, method, key, value, where) -> "CDict":
        key = self._key(key)
        return replace(self, memory=getattr(self.memory, method)(key, value, where))

    def add_(self, key, value, where=None) -> "CDict":
        """Adds onto stored values; existence flags are NOT changed
        (reference ``structures.py:1241``)."""
        return self._arith("add_", key, value, where)

    def subtract_(self, key, value, where=None) -> "CDict":
        return self._arith("subtract_", key, value, where)

    def multiply_(self, key, value, where=None) -> "CDict":
        return self._arith("multiply_", key, value, where)

    def divide_(self, key, value, where=None) -> "CDict":
        return self._arith("divide_", key, value, where)

    def clear(self, where=None) -> "CDict":
        """Flag all keys non-existent — values are kept, as in the reference
        (``structures.py:1349``); masked per batch element via ``where``."""
        if where is None:
            return replace(self, exist=jnp.zeros_like(self.exist))
        where = jnp.broadcast_to(jnp.asarray(where), self.batch_shape)
        m = where.reshape(where.shape + (1,) * self.memory.key_ndim)
        return replace(self, exist=jnp.where(m, False, self.exist))


@pytree_dataclass
class CList:
    """Fixed-capacity circular-buffer list (deque) with masked push/pop
    (reference ``structures.py:1380``); supports explicit batch shapes —
    every batch element carries its own begin/length cursor."""

    data: jnp.ndarray  # (*batch_shape, capacity, *value_shape)
    begin: jnp.ndarray  # (*batch_shape) int32
    length: jnp.ndarray  # (*batch_shape) int32
    batch_ndim: int = static_field(default=0)

    @staticmethod
    def create(
        capacity: int,
        *value_shape: int,
        dtype=jnp.float32,
        batch_shape: tuple = (),
    ) -> "CList":
        batch_shape = tuple(int(b) for b in batch_shape)
        return CList(
            data=jnp.zeros(
                batch_shape + (int(capacity),) + tuple(int(s) for s in value_shape),
                dtype=dtype,
            ),
            begin=jnp.zeros(batch_shape, jnp.int32),
            length=jnp.zeros(batch_shape, jnp.int32),
            batch_ndim=len(batch_shape),
        )

    # ------------------------------------------------------------ properties
    @property
    def batch_shape(self) -> tuple:
        return self.data.shape[: self.batch_ndim]

    @property
    def is_batched(self) -> bool:
        return self.batch_ndim > 0

    @property
    def capacity(self) -> int:
        return self.data.shape[self.batch_ndim]

    # the reference's name for the same number
    @property
    def max_length(self) -> int:
        return self.capacity

    @property
    def value_shape(self) -> tuple:
        return self.data.shape[self.batch_ndim + 1 :]

    @property
    def dtype(self):
        return self.data.dtype

    def __len__(self):
        raise TypeError("Use .length (a traced array) instead of len() on a CList")

    @property
    def is_empty(self) -> jnp.ndarray:
        return self.length == 0

    @property
    def is_full(self) -> jnp.ndarray:
        return self.length == self.capacity

    # ------------------------------------------------------------ addressing
    def _phys(self, i) -> jnp.ndarray:
        return (self.begin + jnp.asarray(i)) % self.capacity

    def _index(self, phys) -> tuple:
        return _open_grid(self.batch_shape) + (phys,)

    def _logical(self, i) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Logical index (negative = from the end, per element) -> physical
        position + validity. Extra leading dims on ``i`` gather multiple
        elements per list (array indexing on an unbatched list)."""
        i = jnp.asarray(i)
        common = jnp.broadcast_shapes(self.batch_shape, i.shape)
        i = jnp.broadcast_to(i, common)
        i = jnp.where(i < 0, i + self.length, i)
        valid = (i >= 0) & (i < self.length)
        return self._phys(jnp.clip(i, 0, self.capacity - 1)), valid

    # ------------------------------------------------------------ read/write
    def get(self, i, default=None) -> jnp.ndarray:
        phys, valid = self._logical(i)
        value = self.data[self._index(phys)]
        if default is not None:
            value = do_where(
                valid,
                value,
                jnp.broadcast_to(jnp.asarray(default, self.data.dtype), value.shape),
            )
        return value

    def __getitem__(self, i) -> jnp.ndarray:
        return self.get(i)

    def _apply(self, i, op, value, where) -> "CList":
        phys, valid = self._logical(i)
        idx = self._index(phys)
        current = self.data[idx]
        value = jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape)
        mask = valid
        if where is not None:
            mask = mask & jnp.broadcast_to(jnp.asarray(where), valid.shape)
        new = do_where(mask, op(current, value), current)
        return replace(self, data=self.data.at[idx].set(new))

    def set_(self, i, value, where=None) -> "CList":
        return self._apply(i, lambda cur, v: v, value, where)

    def add_(self, i, value, where=None) -> "CList":
        return self._apply(i, lambda cur, v: cur + v, value, where)

    def subtract_(self, i, value, where=None) -> "CList":
        return self._apply(i, lambda cur, v: cur - v, value, where)

    def multiply_(self, i, value, where=None) -> "CList":
        return self._apply(i, lambda cur, v: cur * v, value, where)

    def divide_(self, i, value, where=None) -> "CList":
        return self._apply(i, lambda cur, v: cur / v, value, where)

    # ------------------------------------------------------------ push/pop
    def _can(self, other, where):
        can = other
        if where is not None:
            can = can & jnp.broadcast_to(jnp.asarray(where), self.batch_shape)
        return can

    def append_(self, value, where=None) -> "CList":
        """Push to the end unless full (masked; reference ``append_``)."""
        can = self._can(~self.is_full, where)
        idx = self._index(self._phys(self.length % self.capacity))
        current = self.data[idx]
        new = do_where(
            can,
            jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape),
            current,
        )
        return replace(
            self,
            data=self.data.at[idx].set(new),
            length=self.length + can.astype(jnp.int32),
        )

    # the reference's alias
    push_ = append_

    def appendleft_(self, value, where=None) -> "CList":
        can = self._can(~self.is_full, where)
        new_begin = jnp.where(can, (self.begin - 1) % self.capacity, self.begin)
        idx = self._index(new_begin)
        current = self.data[idx]
        new = do_where(
            can,
            jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape),
            current,
        )
        return replace(
            self,
            data=self.data.at[idx].set(new),
            begin=new_begin,
            length=self.length + can.astype(jnp.int32),
        )

    def pop_(self, where=None) -> tuple:
        """Pop from the end (masked); returns ``(new_list, value)`` where the
        value is the popped item (stale data when the pop was masked out)."""
        can = self._can(~self.is_empty, where)
        phys = self._phys(jnp.maximum(self.length - 1, 0))
        value = self.data[self._index(phys)]
        return replace(self, length=self.length - can.astype(jnp.int32)), value

    def popleft_(self, where=None) -> tuple:
        can = self._can(~self.is_empty, where)
        value = self.data[self._index(self.begin)]
        new_begin = jnp.where(can, (self.begin + 1) % self.capacity, self.begin)
        return (
            replace(self, begin=new_begin, length=self.length - can.astype(jnp.int32)),
            value,
        )

    def clear(self, where=None) -> "CList":
        """Empty the list(s); masked per batch element via ``where``
        (reference ``structures.py:1976``)."""
        if where is None:
            return replace(self, length=jnp.zeros_like(self.length))
        where = jnp.broadcast_to(jnp.asarray(where), self.batch_shape)
        return replace(self, length=jnp.where(where, 0, self.length))


@pytree_dataclass
class CBag:
    """A bag (multiset) of integers in ``[0, num_keys)`` with random pop —
    sampling without replacement (reference ``structures.py:2024``).

    Implementation deviation (documented in the module docstring): the bag
    keeps per-key counts instead of shuffled slots; ``pop_`` draws a present
    key uniformly and decrements it, which has exactly the without-replacement
    sampling distribution of the reference's shuffle+popleft. ``capacity``
    optionally bounds the total number of contained elements (the reference's
    ``max_length``); pushes into a full bag are masked no-ops.
    """

    counts: jnp.ndarray  # (*batch_shape, num_keys) int32
    batch_ndim: int = static_field(default=0)
    capacity: Optional[int] = static_field(default=None)

    @staticmethod
    def create(
        num_keys: int, *, batch_shape: tuple = (), capacity: Optional[int] = None
    ) -> "CBag":
        batch_shape = tuple(int(b) for b in batch_shape)
        return CBag(
            counts=jnp.zeros(batch_shape + (int(num_keys),), dtype=jnp.int32),
            batch_ndim=len(batch_shape),
            capacity=None if capacity is None else int(capacity),
        )

    # ------------------------------------------------------------ properties
    @property
    def batch_shape(self) -> tuple:
        return self.counts.shape[: self.batch_ndim]

    @property
    def is_batched(self) -> bool:
        return self.batch_ndim > 0

    @property
    def num_keys(self) -> int:
        return self.counts.shape[-1]

    @property
    def total(self) -> jnp.ndarray:
        return jnp.sum(self.counts, axis=-1)

    # the reference's name for the same number
    @property
    def length(self) -> jnp.ndarray:
        return self.total

    @property
    def max_length(self) -> Optional[int]:
        return self.capacity

    # ------------------------------------------------------------ operations
    def push_(self, key, where=None) -> "CBag":
        """Push key(s). Like the CMemory/CList gathers, ``key`` may carry
        extra leading dims beyond ``batch_shape`` to push several elements in
        one call (duplicates accumulate — pushes are scatter-adds). With a
        ``capacity``, admission is checked against the *pre-call* total, so a
        single multi-key push may overshoot the capacity by up to the number
        of keys pushed together."""
        key = jnp.asarray(key)
        common = jnp.broadcast_shapes(self.batch_shape, key.shape)
        key = jnp.broadcast_to(key, common)
        ok = (key >= 0) & (key < self.num_keys)
        if self.capacity is not None:
            ok = ok & (self.total < self.capacity)
        if where is not None:
            ok = ok & jnp.broadcast_to(jnp.asarray(where), common)
        idx = _open_grid(self.batch_shape) + (jnp.clip(key, 0, self.num_keys - 1),)
        return replace(self, counts=self.counts.at[idx].add(ok.astype(jnp.int32)))

    def clear(self, where=None) -> "CBag":
        if where is None:
            return replace(self, counts=jnp.zeros_like(self.counts))
        where = jnp.broadcast_to(jnp.asarray(where), self.batch_shape)
        return replace(self, counts=jnp.where(where[..., None], 0, self.counts))

    def _pop_specific(self, key, where) -> tuple:
        # like push_, extra leading key dims pop several elements in one call;
        # presence (ok) is checked against the pre-call counts, so popping the
        # same key more times than its count in one call over-reports ok —
        # the clamp below keeps the counts themselves valid (>= 0) regardless
        key = jnp.asarray(key)
        common = jnp.broadcast_shapes(self.batch_shape, key.shape)
        key = jnp.broadcast_to(key, common)
        idx = _open_grid(self.batch_shape) + (jnp.clip(key, 0, self.num_keys - 1),)
        ok = (key >= 0) & (key < self.num_keys) & (self.counts[idx] > 0)
        if where is not None:
            ok = ok & jnp.broadcast_to(jnp.asarray(where), common)
        counts = jnp.maximum(self.counts.at[idx].add(-ok.astype(jnp.int32)), 0)
        return replace(self, counts=counts), key, ok

    def _pop_random(self, rng, where) -> tuple:
        def draw(key, counts):
            probs = counts.astype(jnp.float32)
            total = jnp.sum(probs)
            safe = jnp.where(
                total > 0,
                probs / jnp.maximum(total, 1.0),
                jnp.ones_like(probs) / probs.shape[0],
            )
            return jax.random.choice(key, probs.shape[0], p=safe)

        bs = self.batch_shape
        if bs:
            n = 1
            for d in bs:
                n *= d
            keys = jax.random.split(rng, n).reshape(bs)
            picked = jax.vmap(draw)(
                keys.reshape(n), self.counts.reshape(n, self.num_keys)
            ).reshape(bs)
        else:
            picked = draw(rng, self.counts)
        idx = _open_grid(bs) + (picked,)
        ok = self.counts[idx] > 0
        if where is not None:
            ok = ok & jnp.broadcast_to(jnp.asarray(where), bs)
        new = replace(self, counts=self.counts.at[idx].add(-ok.astype(jnp.int32)))
        return new, picked, ok

    def pop_(self, key_or_rng, where=None) -> tuple:
        """Pop a specific key (integer(s)) or a uniformly random present key
        (PRNG key, typed or legacy uint32). Returns
        ``(new_bag, popped_key, ok)`` with everything batch-shaped."""
        is_legacy_prng = (
            hasattr(key_or_rng, "dtype")
            and jnp.asarray(key_or_rng).dtype == jnp.uint32
            and jnp.asarray(key_or_rng).shape == (2,)
        )
        if is_legacy_prng:
            return self._pop_random(
                jax.random.wrap_key_data(jnp.asarray(key_or_rng)), where
            )
        if isinstance(key_or_rng, int) or (
            hasattr(key_or_rng, "dtype")
            and jnp.issubdtype(jnp.asarray(key_or_rng).dtype, jnp.integer)
        ):
            return self._pop_specific(key_or_rng, where)
        return self._pop_random(key_or_rng, where)
