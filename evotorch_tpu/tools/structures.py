"""Vectorization-friendly batched data structures.

Parity: reference ``tools/structures.py`` (2457 LoC) — ``CMemory``
(``structures.py:60-786``), ``CDict`` (``structures.py:892``), ``CList``
(circular-buffer list, ``structures.py:1380``), ``CBag``
(``structures.py:2024``), ``do_where`` (``structures.py:33``). All contiguous
tensors with masked updates, usable under ``vmap``/``jit``.

TPU-first deviation: jax arrays are immutable, so the reference's in-place
methods (``set_``, ``add_``, ``append_``, ...) here RETURN the updated
structure (pytree dataclasses) instead of mutating; the trailing-underscore
names are kept so reference code maps 1:1 after adding an assignment. Batch
dimensions come from ``vmap`` (every method is per-instance and pure) rather
than explicit batch shapes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .pytree import pytree_dataclass, replace, static_field

__all__ = ["do_where", "CMemory", "CDict", "CList", "CBag"]


def do_where(mask, a: Any, b: Any) -> Any:
    """Pytree-wide ``where`` (reference ``structures.py:33``)."""

    def pick(x, y):
        m = jnp.reshape(mask, jnp.shape(mask) + (1,) * (jnp.ndim(x) - jnp.ndim(mask)))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(pick, a, b)


@pytree_dataclass
class CMemory:
    """Batched key -> tensor memory with masked updates
    (reference ``structures.py:60``). Keys are integers in ``[0, num_keys)``."""

    data: jnp.ndarray  # (num_keys, *value_shape)

    @staticmethod
    def create(num_keys: int, *value_shape: int, dtype=jnp.float32, fill: float = 0.0) -> "CMemory":
        return CMemory(
            data=jnp.full((int(num_keys),) + tuple(int(s) for s in value_shape), fill, dtype=dtype)
        )

    @property
    def num_keys(self) -> int:
        return self.data.shape[0]

    @property
    def value_shape(self) -> tuple:
        return self.data.shape[1:]

    def get(self, key, default=None) -> jnp.ndarray:
        key = jnp.asarray(key)
        value = self.data[key]
        if default is not None:
            valid = (key >= 0) & (key < self.num_keys)
            value = do_where(valid, value, jnp.broadcast_to(jnp.asarray(default, self.data.dtype), value.shape))
        return value

    def __getitem__(self, key) -> jnp.ndarray:
        return self.get(key)

    def _masked_update(self, key, new_value, where) -> "CMemory":
        key = jnp.asarray(key)
        new_value = jnp.broadcast_to(jnp.asarray(new_value, self.data.dtype), self.value_shape)
        if where is None:
            return replace(self, data=self.data.at[key].set(new_value))
        current = self.data[key]
        masked = do_where(jnp.asarray(where), new_value, current)
        return replace(self, data=self.data.at[key].set(masked))

    def set_(self, key, value, where=None) -> "CMemory":
        """Masked overwrite (reference ``structures.py:300``-ish ``set_``)."""
        return self._masked_update(key, value, where)

    def add_(self, key, value, where=None) -> "CMemory":
        return self._masked_update(key, self.data[jnp.asarray(key)] + jnp.asarray(value, self.data.dtype), where)

    def subtract_(self, key, value, where=None) -> "CMemory":
        return self._masked_update(key, self.data[jnp.asarray(key)] - jnp.asarray(value, self.data.dtype), where)

    def multiply_(self, key, value, where=None) -> "CMemory":
        return self._masked_update(key, self.data[jnp.asarray(key)] * jnp.asarray(value, self.data.dtype), where)

    def divide_(self, key, value, where=None) -> "CMemory":
        return self._masked_update(key, self.data[jnp.asarray(key)] / jnp.asarray(value, self.data.dtype), where)


@pytree_dataclass
class CDict:
    """CMemory with a static hashable-key namespace
    (reference ``structures.py:892``)."""

    memory: CMemory
    keys: tuple = static_field()

    @staticmethod
    def create(keys, *value_shape: int, dtype=jnp.float32, fill: float = 0.0) -> "CDict":
        keys = tuple(keys)
        return CDict(
            memory=CMemory.create(len(keys), *value_shape, dtype=dtype, fill=fill),
            keys=keys,
        )

    def _index(self, key) -> int:
        try:
            return self.keys.index(key)
        except ValueError:
            raise KeyError(f"Unknown key: {key!r} (known: {self.keys})") from None

    def get(self, key, default=None) -> jnp.ndarray:
        return self.memory.get(self._index(key), default)

    def __getitem__(self, key) -> jnp.ndarray:
        return self.get(key)

    def set_(self, key, value, where=None) -> "CDict":
        return replace(self, memory=self.memory.set_(self._index(key), value, where))

    def add_(self, key, value, where=None) -> "CDict":
        return replace(self, memory=self.memory.add_(self._index(key), value, where))


@pytree_dataclass
class CList:
    """Fixed-capacity circular-buffer list with masked push/pop
    (reference ``structures.py:1380``)."""

    data: jnp.ndarray  # (capacity, *value_shape)
    begin: jnp.ndarray  # scalar int32
    length: jnp.ndarray  # scalar int32

    @staticmethod
    def create(capacity: int, *value_shape: int, dtype=jnp.float32) -> "CList":
        return CList(
            data=jnp.zeros((int(capacity),) + tuple(int(s) for s in value_shape), dtype=dtype),
            begin=jnp.zeros((), jnp.int32),
            length=jnp.zeros((), jnp.int32),
        )

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def __len__(self):
        raise TypeError("Use .length (a traced scalar) instead of len() on a CList")

    @property
    def is_empty(self) -> jnp.ndarray:
        return self.length == 0

    @property
    def is_full(self) -> jnp.ndarray:
        return self.length == self.capacity

    def _phys(self, i) -> jnp.ndarray:
        return (self.begin + jnp.asarray(i)) % self.capacity

    def get(self, i, default=None) -> jnp.ndarray:
        i = jnp.asarray(i)
        i = jnp.where(i < 0, i + self.length, i)
        value = self.data[self._phys(i)]
        if default is not None:
            valid = (i >= 0) & (i < self.length)
            value = do_where(valid, value, jnp.broadcast_to(jnp.asarray(default, self.data.dtype), value.shape))
        return value

    def __getitem__(self, i) -> jnp.ndarray:
        return self.get(i)

    def set_(self, i, value, where=None) -> "CList":
        i = jnp.asarray(i)
        i = jnp.where(i < 0, i + self.length, i)
        valid = (i >= 0) & (i < self.length)
        if where is not None:
            valid = valid & jnp.asarray(where)
        current = self.data[self._phys(i)]
        masked = do_where(valid, jnp.asarray(value, self.data.dtype), current)
        return replace(self, data=self.data.at[self._phys(i)].set(masked))

    def append_(self, value, where=None) -> "CList":
        """Push to the end unless full (masked; reference ``push_``)."""
        can = ~self.is_full
        if where is not None:
            can = can & jnp.asarray(where)
        pos = self._phys(self.length)
        current = self.data[pos]
        new_val = do_where(can, jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape), current)
        return replace(
            self,
            data=self.data.at[pos].set(new_val),
            length=self.length + can.astype(jnp.int32),
        )

    def appendleft_(self, value, where=None) -> "CList":
        can = ~self.is_full
        if where is not None:
            can = can & jnp.asarray(where)
        new_begin = jnp.where(can, (self.begin - 1) % self.capacity, self.begin)
        current = self.data[new_begin]
        new_val = do_where(can, jnp.broadcast_to(jnp.asarray(value, self.data.dtype), current.shape), current)
        return replace(
            self,
            data=self.data.at[new_begin].set(new_val),
            begin=new_begin,
            length=self.length + can.astype(jnp.int32),
        )

    def pop_(self, where=None) -> tuple:
        """Pop from the end (masked); returns ``(new_list, value)`` where the
        value is the popped item (stale data when the pop was masked out)."""
        can = ~self.is_empty
        if where is not None:
            can = can & jnp.asarray(where)
        pos = self._phys(jnp.maximum(self.length - 1, 0))
        value = self.data[pos]
        return replace(self, length=self.length - can.astype(jnp.int32)), value

    def popleft_(self, where=None) -> tuple:
        can = ~self.is_empty
        if where is not None:
            can = can & jnp.asarray(where)
        value = self.data[self.begin]
        new_begin = jnp.where(can, (self.begin + 1) % self.capacity, self.begin)
        return (
            replace(self, begin=new_begin, length=self.length - can.astype(jnp.int32)),
            value,
        )


@pytree_dataclass
class CBag:
    """A bag (multiset) of integers in ``[0, num_keys)`` with random pop
    (reference ``structures.py:2024``)."""

    counts: jnp.ndarray  # (num_keys,) int32

    @staticmethod
    def create(num_keys: int) -> "CBag":
        return CBag(counts=jnp.zeros(int(num_keys), dtype=jnp.int32))

    @property
    def num_keys(self) -> int:
        return self.counts.shape[0]

    @property
    def total(self) -> jnp.ndarray:
        return jnp.sum(self.counts)

    def push_(self, key, where=None) -> "CBag":
        key = jnp.asarray(key)
        inc = jnp.ones((), jnp.int32) if where is None else jnp.asarray(where).astype(jnp.int32)
        return replace(self, counts=self.counts.at[key].add(inc))

    def pop_(self, key_or_rng, where=None) -> tuple:
        """Pop a specific key (int) or a uniformly random present key (PRNG
        key, typed or legacy uint32). Returns ``(new_bag, popped_key, ok)``."""
        is_legacy_prng = (
            hasattr(key_or_rng, "dtype")
            and jnp.asarray(key_or_rng).dtype == jnp.uint32
            and jnp.asarray(key_or_rng).shape == (2,)
        )
        if is_legacy_prng:
            key_or_rng = jax.random.wrap_key_data(jnp.asarray(key_or_rng))
        if isinstance(key_or_rng, (int,)) or (
            hasattr(key_or_rng, "dtype")
            and jnp.issubdtype(jnp.asarray(key_or_rng).dtype, jnp.integer)
            and jnp.asarray(key_or_rng).ndim == 0
        ):
            key = jnp.asarray(key_or_rng)
            ok = self.counts[key] > 0
        else:
            probs = self.counts.astype(jnp.float32)
            total = jnp.sum(probs)
            safe = jnp.where(total > 0, probs / jnp.maximum(total, 1), jnp.ones_like(probs) / self.num_keys)
            key = jax.random.choice(key_or_rng, self.num_keys, p=safe)
            ok = total > 0
        if where is not None:
            ok = ok & jnp.asarray(where)
        dec = ok.astype(jnp.int32)
        return replace(self, counts=self.counts.at[key].add(-dec)), key, ok
