"""``Hook``: a list of callbacks whose dict/list returns are accumulated.

Parity: reference ``tools/hook.py:25-197`` (the basis of the SearchAlgorithm
status-merging machinery, ``searchalgorithm.py:380-397``).
"""

from __future__ import annotations

from collections.abc import MutableSequence
from typing import Callable, Iterable, Optional

__all__ = ["Hook"]


class Hook(MutableSequence):
    def __init__(
        self,
        callbacks: Optional[Iterable[Callable]] = None,
        *,
        args: Optional[Iterable] = None,
        kwargs: Optional[dict] = None,
    ):
        self._funcs = list(callbacks) if callbacks is not None else []
        self._args = list(args) if args is not None else []
        self._kwargs = dict(kwargs) if kwargs is not None else {}

    # -- invocation ---------------------------------------------------------
    def __call__(self, *args, **kwargs) -> Optional[dict]:
        """Call every callback. dict returns are merged into an accumulated
        dict (later callbacks win on key conflict); list returns extend an
        accumulated list; a mix of the two is an error. Returns None when no
        callback returned anything."""
        all_args = list(self._args) + list(args)
        all_kwargs = {**self._kwargs, **kwargs}
        acc_dict: Optional[dict] = None
        acc_list: Optional[list] = None
        for f in self._funcs:
            result = f(*all_args, **all_kwargs)
            if result is None:
                continue
            if isinstance(result, dict):
                if acc_list is not None:
                    raise TypeError(
                        "Hook callbacks returned a mix of dict and list results"
                    )
                acc_dict = {} if acc_dict is None else acc_dict
                acc_dict.update(result)
            elif isinstance(result, (list, tuple)):
                if acc_dict is not None:
                    raise TypeError(
                        "Hook callbacks returned a mix of dict and list results"
                    )
                acc_list = [] if acc_list is None else acc_list
                acc_list.extend(result)
            else:
                raise TypeError(
                    f"Hook callback {f} returned unsupported type {type(result)}"
                )
        return acc_dict if acc_dict is not None else acc_list

    def accumulate_dict(self, *args, **kwargs) -> dict:
        result = self(*args, **kwargs)
        if result is None:
            return {}
        if not isinstance(result, dict):
            raise TypeError(f"Expected dict accumulation, got {type(result)}")
        return result

    def accumulate_sequence(self, *args, **kwargs) -> list:
        result = self(*args, **kwargs)
        if result is None:
            return []
        if isinstance(result, dict):
            raise TypeError("Expected sequence accumulation, got dict")
        return list(result)

    # -- MutableSequence protocol ------------------------------------------
    def __getitem__(self, i):
        return self._funcs[i]

    def __setitem__(self, i, value):
        self._funcs[i] = value

    def __delitem__(self, i):
        del self._funcs[i]

    def __len__(self):
        return len(self._funcs)

    def insert(self, i, value):
        self._funcs.insert(i, value)

    def append(self, value):
        self._funcs.append(value)

    @property
    def args(self) -> list:
        return self._args

    @property
    def kwargs(self) -> dict:
        return self._kwargs

    def __repr__(self) -> str:
        return f"Hook({self._funcs!r})"
