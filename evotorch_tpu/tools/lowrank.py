"""Factored (low-rank) population representation.

``LowRankParamsBatch`` expresses a population as ``theta_i = center +
basis @ coeffs[i]`` — a shared per-generation basis with per-lane
coefficients — so the dense ``(N, L)`` population matrix is never
materialized. It is the population currency of the MXU path for wide
policies (see ``neuroevolution/net/lowrank.py`` for the policy-forward
machinery and ``distributions.py`` for the factored PGPE gradients).

The container lives here (L1 tools) because the layers above it all
speak it: ``core.SolutionBatch`` can hold one, ``distributions`` samples
and differentiates one, and ``neuroevolution.net`` rolls one out. It is
a NamedTuple, hence a JAX pytree: it passes through ``jit`` /
``shard_map`` boundaries like any array.

No reference counterpart: the reference evaluates dense populations only
(reference ``distributions.py:616-773`` samples full vectors); this is a
TPU-first framework feature (VERDICT r2 #2).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

__all__ = [
    "FACTORED_BATCH_TYPES",
    "LowRankParamsBatch",
    "TrunkDeltaParamsBatch",
    "basis_capture",
    "dense_values",
    "is_factored",
]


class LowRankParamsBatch(NamedTuple):
    """A population expressed as ``theta_i = center + basis @ coeffs[i]``.

    ``basis`` is the *effective* basis: per-generation direction matrix with
    any per-parameter scale (e.g. PGPE's sigma) already folded in.
    """

    center: jnp.ndarray  # (L,)
    basis: jnp.ndarray  # (L, k)
    coeffs: jnp.ndarray  # (N, k)

    @property
    def popsize(self) -> int:
        return self.coeffs.shape[0]

    @property
    def rank(self) -> int:
        return self.basis.shape[-1]

    def take(self, idx) -> "LowRankParamsBatch":
        """Gather lanes (the rollout engine's compaction); center/basis are
        shared across lanes and ride along untouched."""
        return LowRankParamsBatch(self.center, self.basis, self.coeffs[idx])

    def materialize(self) -> jnp.ndarray:
        """The dense ``(N, L)`` population (the correctness fallback — avoid
        on the hot path; this is exactly the matrix the representation
        exists to not build)."""
        return self.center + self.coeffs @ self.basis.T

    def materialize_rows(self, coeff_rows: jnp.ndarray) -> jnp.ndarray:
        """Densify specific coefficient rows ``(K, k)`` into parameter rows
        ``(K, L)`` — for cheaply extracting a handful of winners without
        building the full population."""
        return self.center + coeff_rows @ self.basis.T


class TrunkDeltaParamsBatch(NamedTuple):
    """A population expressed as ``theta_i = center + basis @ coeffs[i]``
    where every basis column is STRUCTURED: per 2-D weight block the column
    is ``vec(b_m a_m^T)`` (rank-1 over the block), so the policy forward
    needs only the shared-trunk matmul ``x @ W_c^T`` plus two thin shared
    GEMMs ``((x @ A) * z_i) @ B^T`` per layer — the MXU-efficient
    shared-trunk + per-lane delta form (docs/policies.md).

    ``basis`` is the MATERIALIZED effective basis (sigma folded), built from
    ``factors`` at sample time — gradients, the subspace-exhaustion
    guardrail, ``materialize`` and concatenation all reuse the
    :class:`LowRankParamsBatch` algebra through it, while the rollout
    forward reads ``factors`` (``neuroevolution/net/lowrank.py``'s trunk-
    delta path). The two views agree by construction; build batches through
    the samplers, not by hand.
    """

    center: jnp.ndarray  # (L,)
    basis: jnp.ndarray  # (L, k) materialized effective basis
    coeffs: jnp.ndarray  # (N, k)
    factors: Any  # per-layer factor tree (net/lowrank.py's _Factor nodes)

    @property
    def popsize(self) -> int:
        return self.coeffs.shape[0]

    @property
    def rank(self) -> int:
        return self.basis.shape[-1]

    def take(self, idx) -> "TrunkDeltaParamsBatch":
        """Gather lanes; center/basis/factors are shared and ride along."""
        return self._replace(coeffs=self.coeffs[idx])

    def materialize(self) -> jnp.ndarray:
        """The dense ``(N, L)`` population (correctness fallback only)."""
        return self.center + self.coeffs @ self.basis.T

    def materialize_rows(self, coeff_rows: jnp.ndarray) -> jnp.ndarray:
        """Densify specific coefficient rows ``(K, k)`` -> ``(K, L)``."""
        return self.center + coeff_rows @ self.basis.T


#: every factored population representation: ``theta_i = center +
#: basis @ coeffs[i]`` with per-lane state living ONLY in ``coeffs``.
#: Code that relies on exactly that algebra (gradients, compaction,
#: padding, dense boundaries) should test ``is_factored`` rather than
#: pinning one concrete class.
FACTORED_BATCH_TYPES = (LowRankParamsBatch, TrunkDeltaParamsBatch)


def is_factored(values) -> bool:
    """True for any factored population batch (low-rank or trunk-delta)."""
    return isinstance(values, FACTORED_BATCH_TYPES)


def basis_capture(basis: jnp.ndarray, vector: jnp.ndarray) -> jnp.ndarray:
    """Fraction of ``vector``'s norm captured by ``span(basis)``:
    ``||P_B v|| / ||v||`` in ``[0, 1]`` (returns 1.0 for a zero vector).

    The subspace-exhaustion diagnostic of factored search: a rank-``k``
    random basis in ``L`` dimensions captures ~``sqrt(k/L)`` of ANY fixed
    direction in expectation — every per-generation gradient estimate is
    confined to its basis's span, so when the (accumulated) dense gradient
    direction's capture stays far below ~1, most of the signal the dense
    estimator would follow is simply not expressible and progress stalls
    (measured: the HalfCheetah rank-32 stall,
    ``bench_curves/halfcheetah_lowrank_cpu_r5.jsonl``). Cost: one ``k x k``
    solve — O(L k^2).
    """
    v_sq = jnp.sum(vector * vector)
    gram = basis.T @ basis  # (k, k)
    proj = basis.T @ vector  # (k,)
    # ridge-regularized normal equations: the basis columns are random and
    # can be near-collinear at high rank
    eye = jnp.eye(gram.shape[0], dtype=gram.dtype)
    ridge = 1e-12 * jnp.maximum(jnp.trace(gram), 1e-30)
    coef = jnp.linalg.solve(gram + ridge * eye, proj)
    captured_sq = jnp.clip(proj @ coef, 0.0, None)
    frac = jnp.sqrt(captured_sq / jnp.maximum(v_sq, 1e-30))
    return jnp.where(v_sq > 0, jnp.clip(frac, 0.0, 1.0), jnp.asarray(1.0, frac.dtype))


def dense_values(values):
    """The dense-boundary rule in one place: materialize a factored
    population into its ``(N, L)`` matrix; pass anything else through.
    Evaluators that only understand dense parameter vectors (plain fitness
    functions, host pools, per-network evals) call this at their entry."""
    if is_factored(values):
        return values.materialize()
    return values
