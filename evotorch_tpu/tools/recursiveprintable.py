"""Pretty-printing mixin (reference ``tools/recursiveprintable.py:21-81``)."""

from __future__ import annotations

from collections.abc import Mapping, Sequence

__all__ = ["RecursivePrintable"]


class RecursivePrintable:
    def to_string(self, *, max_depth: int = 10) -> str:
        return _to_string(self, max_depth)

    def __repr__(self) -> str:
        return self.to_string()

    def __str__(self) -> str:
        return self.to_string()


def _to_string(x, depth: int) -> str:
    if depth <= 0:
        return "<...>"
    if isinstance(x, RecursivePrintable):
        items = getattr(x, "_printable_items", None)
        if callable(items):
            body = items()
        else:
            body = x.__dict__
        if isinstance(body, Mapping):
            inner = ", ".join(f"{k}={_to_string(v, depth - 1)}" for k, v in body.items())
        elif isinstance(body, Sequence) and not isinstance(body, (str, bytes)):
            inner = ", ".join(_to_string(v, depth - 1) for v in body)
        else:
            inner = _to_string(body, depth - 1)
        return f"<{type(x).__name__} {inner}>"
    if isinstance(x, Mapping):
        inner = ", ".join(f"{_to_string(k, depth - 1)}: {_to_string(v, depth - 1)}" for k, v in x.items())
        return "{" + inner + "}"
    if isinstance(x, (list, tuple)):
        inner = ", ".join(_to_string(v, depth - 1) for v in x)
        return ("[" + inner + "]") if isinstance(x, list) else ("(" + inner + ")")
    return repr(x)
