"""Batched constraint penalization kernels.

Parity: reference ``tools/constraints.py:22-281`` (``violation``,
``log_barrier``, ``penalty``), written row-wise and auto-batched with
``expects_ndim`` — extra leading dims on any argument vmap transparently.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..decorators import expects_ndim

__all__ = ["violation", "log_barrier", "penalty"]

_COMPARISONS = ("<=", ">=", "==")


def _check_comparison(comparison: str):
    if comparison not in _COMPARISONS:
        raise ValueError(f"comparison must be one of {_COMPARISONS}, got {comparison!r}")


@expects_ndim(0, None, 0)
def _violation(lhs, comparison, rhs):
    if comparison == "<=":
        return jnp.maximum(lhs - rhs, 0.0)
    if comparison == ">=":
        return jnp.maximum(rhs - lhs, 0.0)
    return jnp.abs(lhs - rhs)


def violation(lhs, comparison: str, rhs):
    """Amount by which ``lhs <comparison> rhs`` is violated; 0 when satisfied
    (reference ``constraints.py:22``)."""
    _check_comparison(comparison)
    return _violation(lhs, comparison, rhs)


@expects_ndim(0, None, 0, 0)
def _log_barrier(lhs, comparison, rhs, sharpness):
    if comparison == "<=":
        gap = rhs - lhs
    else:
        gap = lhs - rhs
    penalty_val = jnp.where(gap > 0, jnp.log(jnp.maximum(gap, 1e-30)) / sharpness, -jnp.inf)
    return jnp.minimum(penalty_val, 0.0)

def log_barrier(lhs, comparison: str, rhs, *, sharpness=1.0):
    """Logarithmic barrier penalty: 0-ish while well inside the feasible
    region, → -inf as the boundary is approached/crossed (reference
    ``constraints.py:108``). Returned values are <= 0; add to a fitness that
    is being maximized (negate for minimization)."""
    if comparison not in ("<=", ">="):
        raise ValueError(
            f"log_barrier requires an inequality comparison, got {comparison!r}"
        )
    return _log_barrier(lhs, comparison, rhs, sharpness)


@expects_ndim(0, None, 0, 0, 0)
def _penalty(lhs, comparison, rhs, linear, step):
    v = _violation.__wrapped__(lhs, comparison, rhs)
    result = -(linear * v)
    result = result - jnp.where(v > 0, step, 0.0)
    return result


def penalty(lhs, comparison: str, rhs, *, penalty_sign: str = "-", linear=1.0, step=0.0):
    """Linear + step penalty for a violated constraint (reference
    ``constraints.py:190``). ``penalty_sign='-'`` produces values <= 0 (for
    maximization problems); ``'+'`` produces values >= 0 (for minimization)."""
    _check_comparison(comparison)
    if penalty_sign not in ("+", "-"):
        raise ValueError(f"penalty_sign must be '+' or '-', got {penalty_sign!r}")
    result = _penalty(lhs, comparison, rhs, linear, step)
    if penalty_sign == "+":
        result = -result
    return result
