"""Tools layer (L1): pure data-structure and kernel utilities.

Parity target: reference ``src/evotorch/tools/`` (SURVEY.md §2.8).
"""

from . import cloning, constraints, hook, immutable, lowrank, misc, objectarray, pytree, ranking, readonlytensor, structures, tensorframe
from .cloning import Clonable, ReadOnlyClonable, Serializable, deep_clone
from .constraints import log_barrier, penalty, violation
from .hook import Hook
from .lowrank import LowRankParamsBatch, TrunkDeltaParamsBatch, is_factored
from .immutable import (
    ImmutableContainer,
    ImmutableDict,
    ImmutableList,
    ImmutableSet,
    as_immutable,
    is_immutable,
    mutable_copy,
)
from .misc import (
    Device,
    DType,
    ErroneousResult,
    cast_arrays_in_container,
    clip_tensor,
    clone,
    dtype_of_container,
    ensure_array_length_and_dtype,
    is_dtype_bool,
    is_dtype_float,
    is_dtype_integer,
    is_dtype_object,
    is_dtype_real,
    modify_tensor,
    modify_vector,
    split_workload,
    stdev_from_radius,
    to_jax_dtype,
    to_numpy_dtype,
    to_stdev_init,
)
from .objectarray import ObjectArray
from .pytree import pytree_dataclass, replace, static_field
from .structures import CBag, CDict, CList, CMemory, do_where
from .readonlytensor import ReadOnlyTensor, as_read_only_tensor, read_only_tensor
from .tensorframe import Picker, TensorFrame
from .ranking import rank, rankers
from .recursiveprintable import RecursivePrintable
from .tensormaker import TensorMakerMixin

__all__ = [
    "LowRankParamsBatch",
    "TrunkDeltaParamsBatch",
    "is_factored",
    "Clonable",
    "ReadOnlyClonable",
    "Serializable",
    "deep_clone",
    "log_barrier",
    "penalty",
    "violation",
    "Hook",
    "ImmutableContainer",
    "ImmutableDict",
    "ImmutableList",
    "ImmutableSet",
    "as_immutable",
    "is_immutable",
    "mutable_copy",
    "Device",
    "DType",
    "ErroneousResult",
    "cast_arrays_in_container",
    "clip_tensor",
    "clone",
    "dtype_of_container",
    "ensure_array_length_and_dtype",
    "is_dtype_bool",
    "is_dtype_float",
    "is_dtype_integer",
    "is_dtype_object",
    "is_dtype_real",
    "modify_tensor",
    "modify_vector",
    "split_workload",
    "stdev_from_radius",
    "to_jax_dtype",
    "to_numpy_dtype",
    "to_stdev_init",
    "ObjectArray",
    "pytree_dataclass",
    "replace",
    "static_field",
    "CBag",
    "CDict",
    "CList",
    "CMemory",
    "do_where",
    "Picker",
    "TensorFrame",
    "ReadOnlyTensor",
    "as_read_only_tensor",
    "read_only_tensor",
    "rank",
    "rankers",
    "RecursivePrintable",
    "TensorMakerMixin",
]
