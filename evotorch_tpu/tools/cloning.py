"""Cloning and serialization discipline.

Parity: reference ``tools/cloning.py`` (``deep_clone`` ``cloning.py:25``,
``Clonable/Serializable/ReadOnlyClonable`` ``cloning.py:203-340``). JAX arrays
are immutable, so cloning them is the identity; the machinery below exists for
host-side state (numpy arrays, dicts, object-dtype payloads) and to give every
core object a pickle-based checkpoint path (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import copy
from typing import Any, Optional

import jax
import numpy as np

__all__ = ["deep_clone", "Clonable", "Serializable", "ReadOnlyClonable"]


def deep_clone(
    x: Any,
    *,
    otherwise_deepcopy: bool = True,
    memo: Optional[dict] = None,
) -> Any:
    """Deep-clone ``x``. jax.Arrays are returned as-is (immutable); numpy
    arrays are copied; ``Clonable`` objects delegate to their ``clone``;
    containers recurse with memoization (reference ``cloning.py:25``)."""
    if memo is None:
        memo = {}
    key = id(x)
    if key in memo:
        return memo[key]

    if isinstance(x, jax.Array):
        result = x
    elif isinstance(x, np.ndarray):
        result = x.copy()
    elif isinstance(x, Clonable):
        result = x.clone(memo=memo)
    elif isinstance(x, dict):
        result = type(x)()
        memo[key] = result
        for k, v in x.items():
            result[deep_clone(k, memo=memo)] = deep_clone(v, memo=memo)
        return result
    elif isinstance(x, list):
        result = type(x)()
        memo[key] = result
        for v in x:
            result.append(deep_clone(v, memo=memo))
        return result
    elif isinstance(x, tuple):
        cloned = [deep_clone(v, memo=memo) for v in x]
        result = tuple(cloned) if type(x) is tuple else type(x)(*cloned)
    elif isinstance(x, set):
        result = {deep_clone(v, memo=memo) for v in x}
    elif isinstance(x, (int, float, complex, str, bytes, bool, type(None))):
        result = x
    elif otherwise_deepcopy:
        result = copy.deepcopy(x, memo)
    else:
        result = x
    memo[key] = result
    return result


class Clonable:
    """Objects that know how to clone themselves (reference ``cloning.py:203``)."""

    def _get_cloned_state(self, *, memo: dict) -> dict:
        return {k: deep_clone(v, memo=memo) for k, v in self.__dict__.items()}

    def clone(self, *, memo: Optional[dict] = None) -> "Clonable":
        if memo is None:
            memo = {}
        if id(self) in memo:
            return memo[id(self)]
        new = object.__new__(type(self))
        memo[id(self)] = new
        new.__dict__.update(self._get_cloned_state(memo=memo))
        return new

    def __copy__(self):
        return self.clone()

    def __deepcopy__(self, memo):
        return self.clone(memo=memo)


class Serializable(Clonable):
    """Clonable + pickling via cloned state (reference ``cloning.py:258``)."""

    def __getstate__(self) -> dict:
        return self._get_cloned_state(memo={id(self): self})

    def __setstate__(self, state: dict):
        self.__dict__.update(state)


class ReadOnlyClonable(Clonable):
    """Clonable whose default clone is a *mutable* copy of read-only data
    (reference ``cloning.py:300``). Subclasses implement
    ``_get_mutable_clone``."""

    def clone(self, *, memo: Optional[dict] = None, preserve_read_only: bool = False):
        if preserve_read_only:
            return super().clone(memo=memo)
        return self._get_mutable_clone(memo=memo if memo is not None else {})

    def _get_mutable_clone(self, *, memo: dict):
        raise NotImplementedError
