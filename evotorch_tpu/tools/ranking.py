"""Fitness-shaping (ranking) kernels.

Parity with the reference's ``tools/ranking.py:24-216`` (methods ``centered``,
``linear``, ``nes``, ``normalized``, ``raw`` and the dispatcher ``rank``), but
written as pure jnp functions over the *last* axis so they are `jit`/`vmap`
friendly by construction. All methods return utilities where **higher is
better**, regardless of the objective sense of the raw fitnesses.
"""

from __future__ import annotations

import os
from typing import Callable, Dict

import jax.numpy as jnp

__all__ = [
    "centered",
    "linear",
    "nes",
    "normalized",
    "raw",
    "rank",
    "rankers",
]


def _ascending_ranks(fitnesses: jnp.ndarray) -> jnp.ndarray:
    """Integer ranks along the last axis: 0 for the lowest fitness, n-1 for the
    highest. Ties receive distinct ranks (argsort-of-argsort), matching the
    reference's torch ``argsort`` behavior."""
    order = jnp.argsort(fitnesses, axis=-1)
    idx = jnp.broadcast_to(jnp.arange(fitnesses.shape[-1]), fitnesses.shape)
    return jnp.put_along_axis(jnp.zeros_like(order), order, idx, axis=-1, inplace=False)


def _float_dtype_like(x: jnp.ndarray):
    return x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32


def _use_fused_centered(n: int) -> bool:
    """Dispatch ``centered`` to the fused Pallas kernel (``ops/ranking.py``)?
    Default: **off** — the kernel ships opt-in until an on-chip micro-bench
    (``bench_ops.py``, captured by ``scripts/tpu_window.sh``) records a win
    over ``centered_xla`` at representative population sizes; an unmeasured
    default in every TPU PGPE generation is risk with no evidence. Opt in
    with ``EVOTORCH_TPU_FUSED_RANK=1`` (any backend, any n that fits VMEM);
    ``=0`` pins it off. Read at trace time: jitted callers bake the decision
    into their compiled executable."""
    flag = os.environ.get("EVOTORCH_TPU_FUSED_RANK", "auto")
    if flag != "1":
        return False
    # 1024^2 * (4B f32 + 1B bool + 8B iotas) comparison block stays well
    # inside the ~16 MB/core VMEM budget; 2048 would already exceed it
    return 2 <= n <= 1024


def centered_xla(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """The plain double-argsort implementation of :func:`centered` — the
    non-dispatching form the fused kernel falls back to."""
    x = fitnesses if higher_is_better else -fitnesses
    n = x.shape[-1]
    ranks = _ascending_ranks(x).astype(_float_dtype_like(jnp.asarray(fitnesses)))
    if n == 1:
        return jnp.zeros_like(ranks)
    return ranks / (n - 1) - 0.5


def centered(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """Centered ranks in ``[-0.5, +0.5]`` (reference ``ranking.py:24``)."""
    if _use_fused_centered(jnp.asarray(fitnesses).shape[-1]):
        from ..ops.ranking import fused_centered_rank

        return fused_centered_rank(
            jnp.asarray(fitnesses), higher_is_better=higher_is_better, use_pallas=True
        )
    return centered_xla(fitnesses, higher_is_better=higher_is_better)


def linear(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """Linearly spaced ranks in ``[0, 1]`` (reference ``ranking.py:56``)."""
    return centered(fitnesses, higher_is_better=higher_is_better) + 0.5


def nes(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """NES utility weights (reference ``ranking.py:84``): for the k-th best of n
    solutions, ``u_k = max(0, ln(n/2+1) - ln(k))``, normalized to sum 1, then
    shifted by ``-1/n`` so the weights sum to 0."""
    x = fitnesses if higher_is_better else -fitnesses
    n = x.shape[-1]
    asc = _ascending_ranks(x)
    # k = 1 for the best solution, n for the worst
    k = (n - asc).astype(_float_dtype_like(jnp.asarray(fitnesses)))
    u = jnp.maximum(0.0, jnp.log(n / 2.0 + 1.0) - jnp.log(k))
    u = u / jnp.sum(u, axis=-1, keepdims=True)
    return u - 1.0 / n


def normalized(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """Z-score normalization (reference ``ranking.py:127``; unbiased stdev,
    ddof=1, matching torch.std)."""
    x = fitnesses if higher_is_better else -fitnesses
    mean = jnp.mean(x, axis=-1, keepdims=True)
    std = jnp.std(x, axis=-1, keepdims=True, ddof=1) if x.shape[-1] > 1 else jnp.ones_like(mean)
    return (x - mean) / jnp.where(std == 0, 1.0, std)


def raw(fitnesses: jnp.ndarray, *, higher_is_better: bool = True) -> jnp.ndarray:
    """Raw fitnesses, sign-adjusted so higher is better (reference ``ranking.py:163``)."""
    x = jnp.asarray(fitnesses)
    x = x if higher_is_better else -x
    return x.astype(_float_dtype_like(x))


rankers: Dict[str, Callable] = {
    "centered": centered,
    "linear": linear,
    "nes": nes,
    "normalized": normalized,
    "raw": raw,
}


def _nonfinite_to_worst(x: jnp.ndarray, *, higher_is_better: bool) -> jnp.ndarray:
    """Non-finite fitnesses replaced by the worst finite one (per batch row).

    Without this, argsort's total order places NaN LAST — i.e. a diverged
    rollout ranks "best" and every utility-weighted update chases it; under
    ``normalized``/``raw`` a single NaN poisons the whole utility vector.
    Defense in depth behind the engines' score quarantine
    (docs/resilience.md): identity on all-finite input, so guarded ranking
    is bit-identical to unguarded whenever nothing is wrong. An
    all-non-finite row falls back to 0 utility everywhere.
    """
    finite = jnp.isfinite(x)
    big = jnp.asarray(jnp.finfo(x.dtype).max, dtype=x.dtype)
    if higher_is_better:
        worst = jnp.min(jnp.where(finite, x, big), axis=-1, keepdims=True)
        worst = jnp.where(worst >= big, jnp.zeros((), x.dtype), worst)
    else:
        worst = jnp.max(jnp.where(finite, x, -big), axis=-1, keepdims=True)
        worst = jnp.where(worst <= -big, jnp.zeros((), x.dtype), worst)
    return jnp.where(finite, x, worst)


def rank(
    fitnesses,
    ranking_method: str = "raw",
    *,
    higher_is_better: bool,
    guard_nonfinite: bool = True,
) -> jnp.ndarray:
    """Dispatcher (reference ``ranking.py:189``). Works along the last axis so
    leading batch dimensions (batched searches) are supported natively.

    ``guard_nonfinite`` (default on) sanitizes NaN/Inf fitnesses to the
    worst finite value before shaping — see :func:`_nonfinite_to_worst`;
    pass False for the reference's unguarded argsort semantics."""
    try:
        fn = rankers[ranking_method]
    except KeyError:
        raise ValueError(
            f"Unknown ranking method {ranking_method!r}; expected one of {sorted(rankers)}"
        )
    x = jnp.asarray(fitnesses)
    if guard_nonfinite and jnp.issubdtype(x.dtype, jnp.floating):
        x = _nonfinite_to_worst(x, higher_is_better=higher_is_better)
    return fn(x, higher_is_better=higher_is_better)
