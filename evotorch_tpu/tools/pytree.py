"""Pytree-dataclass helpers for algorithm states.

The reference's functional states are ``NamedTuple``s of torch tensors
(``funccem.py:24``, ``funcpgpe.py:54``); here they are frozen dataclasses
registered as JAX pytrees, with hyper-flags (optimizer name, ranking method,
objective sense, ...) marked *static* so whole states pass through ``jit`` /
``vmap`` / ``lax.scan`` unchanged.
"""

from __future__ import annotations

import dataclasses

import jax

__all__ = ["pytree_dataclass", "static_field", "field", "replace"]


def static_field(**kwargs):
    """A dataclass field excluded from pytree leaves (compile-time constant)."""
    metadata = dict(kwargs.pop("metadata", {}))
    metadata["static"] = True
    return dataclasses.field(metadata=metadata, **kwargs)


def field(**kwargs):
    return dataclasses.field(**kwargs)


def pytree_dataclass(cls):
    """Decorator: frozen dataclass registered as a JAX pytree node."""
    return jax.tree_util.register_dataclass(dataclasses.dataclass(frozen=True)(cls))


replace = dataclasses.replace
