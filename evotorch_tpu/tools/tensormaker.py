"""``TensorMakerMixin``: per-object array factories.

Parity: reference ``tools/tensormaker.py:27-920`` (``make_empty``,
``make_zeros/ones/nan/I``, ``make_uniform/gaussian/randint``) and the factory
kernels of ``tools/misc.py:1138-1815``. Dtype and shape defaults come from the
owning object (Problem / Distribution); torch ``Generator`` awareness becomes
JAX PRNG-key plumbing: owners expose ``next_rng_key()`` (stateful convenience
on the host), while purely functional call-sites pass ``key=`` explicitly.
"""

from __future__ import annotations

from numbers import Number
from typing import Iterable, Optional, Union

import jax
import jax.numpy as jnp

from .misc import to_jax_dtype

__all__ = ["TensorMakerMixin"]

Size = Union[int, Iterable[int]]


def _as_shape(num_solutions: Optional[int], length: Optional[int], size: Optional[Size]) -> tuple:
    if size is not None:
        if isinstance(size, Number):
            return (int(size),)
        return tuple(int(s) for s in size)
    shape = ()
    if num_solutions is not None:
        shape = shape + (int(num_solutions),)
    if length is not None:
        shape = shape + (int(length),)
    return shape


class TensorMakerMixin:
    """Owners must provide ``dtype``/``eval_dtype`` attributes, a
    ``solution_length`` (may be None for object-typed problems), and
    ``next_rng_key()``."""

    def _make_dtype(self, dtype=None, use_eval_dtype=False):
        if dtype is not None:
            return to_jax_dtype(dtype)
        if use_eval_dtype:
            return to_jax_dtype(getattr(self, "eval_dtype", jnp.float32))
        return to_jax_dtype(getattr(self, "dtype", jnp.float32))

    def _make_shape(self, *size: Size, num_solutions=None) -> tuple:
        if len(size) == 1 and not isinstance(size[0], Number):
            size = tuple(size[0])
        if len(size) > 0:
            shape = tuple(int(s) for s in size)
            if num_solutions is not None:
                shape = (int(num_solutions),) + shape
            return shape
        length = getattr(self, "solution_length", None)
        return _as_shape(num_solutions, length, None)

    def _make_key(self, key=None):
        if key is not None:
            return key
        return self.next_rng_key()

    # -- deterministic fills -------------------------------------------------
    def make_empty(self, *size: Size, num_solutions=None, dtype=None, use_eval_dtype=False):
        return self.make_zeros(*size, num_solutions=num_solutions, dtype=dtype, use_eval_dtype=use_eval_dtype)

    def make_zeros(self, *size: Size, num_solutions=None, dtype=None, use_eval_dtype=False):
        return jnp.zeros(self._make_shape(*size, num_solutions=num_solutions), dtype=self._make_dtype(dtype, use_eval_dtype))

    def make_ones(self, *size: Size, num_solutions=None, dtype=None, use_eval_dtype=False):
        return jnp.ones(self._make_shape(*size, num_solutions=num_solutions), dtype=self._make_dtype(dtype, use_eval_dtype))

    def make_nan(self, *size: Size, num_solutions=None, dtype=None, use_eval_dtype=False):
        return jnp.full(self._make_shape(*size, num_solutions=num_solutions), jnp.nan, dtype=self._make_dtype(dtype, use_eval_dtype))

    def make_I(self, size: Optional[int] = None, dtype=None, use_eval_dtype=False):
        if size is None:
            size = getattr(self, "solution_length", None)
            if size is None:
                raise ValueError("make_I needs a size when the owner has no solution_length")
        return jnp.eye(int(size), dtype=self._make_dtype(dtype, use_eval_dtype))

    def make_tensor(self, data, *, dtype=None, use_eval_dtype=False, read_only: bool = False):
        """Convert ``data`` to an array in the owner's dtype — or to an
        :class:`ObjectArray` when ``dtype=object`` (reference
        ``tensormaker.py:142`` -> ``misc.py:1138``). JAX arrays are immutable,
        so ``read_only`` is accepted for API familiarity and is a no-op for
        the numeric case."""
        if dtype is not None and to_jax_dtype(dtype) is object:
            from .objectarray import ObjectArray

            out = ObjectArray.from_values(data)
            return out.get_read_only_view() if read_only else out
        return jnp.asarray(data, dtype=self._make_dtype(dtype, use_eval_dtype))

    def make_uniform_shaped_like(self, t, *, lb=None, ub=None, key=None):
        """Uniform random array with ``t``'s shape and dtype (reference
        ``tensormaker.py:866``)."""
        t = jnp.asarray(t)
        # 0-d inputs must yield 0-d outputs (an empty *shape would fall back
        # to the owner's solution_length default)
        shape = t.shape if t.ndim else (1,)
        out = self.make_uniform(*shape, lb=lb, ub=ub, dtype=t.dtype, key=key)
        return out.reshape(t.shape)

    def make_gaussian_shaped_like(self, t, *, center=None, stdev=None, key=None):
        """Gaussian random array with ``t``'s shape and dtype (reference
        ``tensormaker.py:893``)."""
        t = jnp.asarray(t)
        shape = t.shape if t.ndim else (1,)
        out = self.make_gaussian(*shape, center=center, stdev=stdev, dtype=t.dtype, key=key)
        return out.reshape(t.shape)

    # -- random fills --------------------------------------------------------
    def make_uniform(self, *size: Size, num_solutions=None, lb=None, ub=None, dtype=None, use_eval_dtype=False, key=None):
        dtype = self._make_dtype(dtype, use_eval_dtype)
        shape = self._make_shape(*size, num_solutions=num_solutions)
        key = self._make_key(key)
        lb = 0.0 if lb is None else lb
        ub = 1.0 if ub is None else ub
        lb = jnp.asarray(lb, dtype=dtype)
        ub = jnp.asarray(ub, dtype=dtype)
        if jnp.issubdtype(dtype, jnp.integer):
            return jax.random.randint(key, shape, minval=lb, maxval=ub + 1, dtype=dtype)
        return jax.random.uniform(key, shape, dtype=dtype, minval=0.0, maxval=1.0) * (ub - lb) + lb

    def make_gaussian(self, *size: Size, num_solutions=None, center=None, stdev=None, symmetric=False, dtype=None, use_eval_dtype=False, key=None):
        dtype = self._make_dtype(dtype, use_eval_dtype)
        shape = self._make_shape(*size, num_solutions=num_solutions)
        key = self._make_key(key)
        if symmetric:
            if len(shape) == 0 or shape[0] % 2 != 0:
                raise ValueError(f"symmetric gaussian requires an even leading dimension, got shape {shape}")
            half = (shape[0] // 2,) + shape[1:]
            eps = jax.random.normal(key, half, dtype=dtype)
            # interleave antithetic pairs: [+e0, -e0, +e1, -e1, ...]
            # (reference distributions.py:649-668 direction layout)
            noise = jnp.stack([eps, -eps], axis=1).reshape(shape)
        else:
            noise = jax.random.normal(key, shape, dtype=dtype)
        if stdev is not None:
            noise = noise * jnp.asarray(stdev, dtype=dtype)
        if center is not None:
            noise = noise + jnp.asarray(center, dtype=dtype)
        return noise

    def make_randint(self, *size: Size, n: int, num_solutions=None, dtype=None, key=None):
        dtype = self._make_dtype(dtype) if dtype is not None else jnp.int32
        if jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.int32
        shape = self._make_shape(*size, num_solutions=num_solutions)
        key = self._make_key(key)
        return jax.random.randint(key, shape, minval=0, maxval=int(n), dtype=dtype)
