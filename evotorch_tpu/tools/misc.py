"""General-purpose tool kernels and helpers.

Parity with the subset of the reference's ``tools/misc.py`` that matters on
TPU: dtype/device coercion (``misc.py:75-118``), bounded updates
``modify_tensor``/``modify_vector`` (``misc.py:711-909``), workload splitting
(``misc.py:1113``), radius→stdev (``misc.py:1879-1925``) and an
``ErroneousResult`` marker (``misc.py:1006``). Tensor factories live in
``tensormaker.py``; torch ``Generator`` plumbing is replaced by explicit JAX
PRNG keys throughout the package.
"""

from __future__ import annotations

import math
from numbers import Number
from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Device",
    "DType",
    "to_jax_dtype",
    "to_numpy_dtype",
    "is_dtype_object",
    "is_dtype_bool",
    "is_dtype_integer",
    "is_dtype_float",
    "is_dtype_real",
    "cast_arrays_in_container",
    "dtype_of_container",
    "clone",
    "ensure_array_length_and_dtype",
    "modify_tensor",
    "modify_vector",
    "clip_tensor",
    "split_workload",
    "stdev_from_radius",
    "to_stdev_init",
    "ErroneousResult",
    "pass_through",
    "expect_none",
    "message_from",
    "set_default_logger_config",
]

Device = Any
DType = Any

_DTYPE_ALIASES = {
    "float": jnp.float32,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "int": jnp.int32,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "int16": jnp.int16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
}


def to_jax_dtype(dtype: DType):
    """Coerce a dtype-like (str, np dtype, jnp dtype, ``object``) to a jnp dtype.

    ``object`` is passed through unchanged: object-typed problems live host-side
    (reference ``tools/misc.py:118`` ``is_dtype_object``).
    """
    if dtype is object or dtype == "object":
        return object
    if isinstance(dtype, str):
        key = dtype.replace("torch.", "").replace("jnp.", "")
        if key in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[key]
    return jnp.dtype(dtype)


def to_numpy_dtype(dtype: DType):
    d = to_jax_dtype(dtype)
    if d is object:
        return np.dtype(object)
    return np.dtype(d)


def is_dtype_object(dtype: DType) -> bool:
    return to_jax_dtype(dtype) is object


def is_dtype_bool(dtype: DType) -> bool:
    d = to_jax_dtype(dtype)
    return d is not object and jnp.issubdtype(d, jnp.bool_)


def is_dtype_integer(dtype: DType) -> bool:
    d = to_jax_dtype(dtype)
    return d is not object and jnp.issubdtype(d, jnp.integer)


def is_dtype_float(dtype: DType) -> bool:
    d = to_jax_dtype(dtype)
    return d is not object and jnp.issubdtype(d, jnp.floating)


def is_dtype_real(dtype: DType) -> bool:
    return is_dtype_float(dtype) or is_dtype_integer(dtype)


def cast_arrays_in_container(container: Any, *, dtype: Optional[DType] = None) -> Any:
    """Cast every array leaf of a pytree to ``dtype`` (reference
    ``misc.py:347`` ``cast_tensors_in_container``; device moves are not needed —
    placement is controlled by shardings in JAX)."""
    if dtype is None:
        return container
    d = to_jax_dtype(dtype)

    def cast(leaf):
        if isinstance(leaf, (jnp.ndarray, jax.Array, np.ndarray)):
            return jnp.asarray(leaf, dtype=d)
        return leaf

    return jax.tree_util.tree_map(cast, container)


def dtype_of_container(container: Any):
    """Common dtype of the array leaves of a pytree (reference ``misc.py:422``)."""
    leaves = [l for l in jax.tree_util.tree_leaves(container) if hasattr(l, "dtype")]
    if not leaves:
        return None
    dtypes = {np.dtype(l.dtype) for l in leaves}
    if len(dtypes) > 1:
        raise ValueError(f"Container has multiple dtypes: {dtypes}")
    return leaves[0].dtype


def clone(x: Any, *, memo: Optional[dict] = None) -> Any:
    """Clone a value (reference ``misc.py:588``). JAX arrays are immutable, so
    they are returned as-is; numpy arrays and containers are deep-copied via
    ``tools.cloning.deep_clone``."""
    from .cloning import deep_clone

    return deep_clone(x, memo=memo)


def ensure_array_length_and_dtype(
    x: Any,
    length: int,
    dtype: DType,
    *,
    about: Optional[str] = None,
    allow_scalar: bool = True,
) -> jnp.ndarray:
    """Coerce ``x`` to a 1-D array of ``length`` with ``dtype``; scalars are
    broadcast (reference ``misc.py:610`` ``ensure_tensor_length_and_dtype``).
    For ``dtype=object`` the result is a host-side :class:`ObjectArray`."""
    d = to_jax_dtype(dtype)
    if d is object:
        from collections.abc import Mapping

        from .objectarray import ObjectArray

        if isinstance(x, ObjectArray):
            if len(x) != length:
                raise ValueError(
                    f"{about or 'value'}: expected length {length}, got {len(x)}"
                )
            return x
        # strings, mappings, and non-iterables count as single object payloads
        is_scalar_payload = isinstance(x, (str, bytes, Mapping)) or not hasattr(x, "__iter__")
        if is_scalar_payload:
            if not allow_scalar and length != 1:
                raise ValueError(f"{about or 'value'}: expected a sequence, got {x!r}")
            values = [x] * length
        else:
            values = list(x)
            if len(values) == 1 and length != 1 and allow_scalar:
                values = values * length
        if len(values) != length:
            raise ValueError(
                f"{about or 'value'}: expected length {length}, got {len(values)}"
            )
        return ObjectArray.from_values(values)
    if isinstance(x, Number):
        if not allow_scalar:
            raise ValueError(f"{about or 'value'}: expected a sequence, got scalar {x}")
        return jnp.full((length,), x, dtype=d)
    arr = jnp.asarray(x, dtype=d)
    if arr.ndim == 0:
        return jnp.full((length,), arr, dtype=d)
    if arr.ndim != 1 or arr.shape[0] != length:
        raise ValueError(
            f"{about or 'value'}: expected shape ({length},), got {tuple(arr.shape)}"
        )
    return arr


def _as_opt_array(x):
    return None if x is None else jnp.asarray(x)


def modify_tensor(
    original: jnp.ndarray,
    target: jnp.ndarray,
    lb: Optional[Union[float, jnp.ndarray]] = None,
    ub: Optional[Union[float, jnp.ndarray]] = None,
    max_change: Optional[Union[float, jnp.ndarray]] = None,
    *,
    in_place: bool = False,  # accepted for API parity; arrays are immutable
) -> jnp.ndarray:
    """Move ``original`` towards ``target`` subject to bounds.

    ``max_change`` limits the per-element change relative to
    ``|original|`` (e.g. ``0.2`` allows a 20% change — the reference's
    controlled-stdev-update mechanism, ``misc.py:711-909`` /
    ``gaussian.py:369-419``); ``lb``/``ub`` are absolute clamps.
    """
    original = jnp.asarray(original)
    target = jnp.asarray(target, dtype=original.dtype)
    result = target
    if max_change is not None:
        allowed = jnp.abs(original) * jnp.asarray(max_change, dtype=original.dtype)
        result = original + jnp.clip(target - original, -allowed, allowed)
    lb = _as_opt_array(lb)
    ub = _as_opt_array(ub)
    if lb is not None:
        result = jnp.maximum(result, lb)
    if ub is not None:
        result = jnp.minimum(result, ub)
    return result


def modify_vector(
    original: jnp.ndarray,
    target: jnp.ndarray,
    lb=None,
    ub=None,
    max_change=None,
) -> jnp.ndarray:
    """1-D counterpart of :func:`modify_tensor` (reference ``misc.py:880``)."""
    return modify_tensor(original, target, lb=lb, ub=ub, max_change=max_change)


def clip_tensor(
    x: jnp.ndarray,
    lb: Optional[Union[float, jnp.ndarray]] = None,
    ub: Optional[Union[float, jnp.ndarray]] = None,
) -> jnp.ndarray:
    x = jnp.asarray(x)
    if lb is not None:
        x = jnp.maximum(x, jnp.asarray(lb, dtype=x.dtype))
    if ub is not None:
        x = jnp.minimum(x, jnp.asarray(ub, dtype=x.dtype))
    return x


def split_workload(workload: int, num_pieces: int) -> List[int]:
    """Split ``workload`` items into ``num_pieces`` near-equal pieces
    (reference ``misc.py:1113``)."""
    base = workload // num_pieces
    rem = workload % num_pieces
    return [base + (1 if i < rem else 0) for i in range(num_pieces)]


def stdev_from_radius(radius: float, solution_length: int) -> float:
    """Initial stdev from a hypersphere radius: ``σ = radius / sqrt(n)``
    (reference ``misc.py:1879``)."""
    return float(radius) / math.sqrt(solution_length)


def to_stdev_init(
    *,
    solution_length: int,
    stdev_init=None,
    radius_init=None,
):
    """Resolve the ``stdev_init`` / ``radius_init`` constructor pair
    (reference ``misc.py:1925``): exactly one must be given."""
    if (stdev_init is None) == (radius_init is None):
        raise ValueError("Exactly one of stdev_init / radius_init must be provided")
    if stdev_init is not None:
        return stdev_init
    return stdev_from_radius(float(radius_init), solution_length)


class ErroneousResult:
    """Value-carrying error marker (reference ``misc.py:1006-1041``)."""

    def __init__(self, error: Exception):
        self.error = error

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:
        return f"<ErroneousResult: {self.error!r}>"

    @staticmethod
    def call(f, *args, **kwargs):
        try:
            return f(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — marker deliberately captures all  # graftlint: allow(swallow): ErroneousResult deliberately captures the failure as a value
            return ErroneousResult(e)


def pass_through(x):
    return x


def expect_none(msg_prefix: str, **kwargs):
    """Raise if any given kwarg is not None (reference ``misc.py`` helper used
    by constructors that forbid option combinations)."""
    for k, v in kwargs.items():
        if v is not None:
            raise ValueError(f"{msg_prefix}: unexpected argument {k}={v!r}")


def message_from(sender: Any, message: str) -> str:
    return f"[{type(sender).__name__}] {message}"


def set_default_logger_config(level: Optional[Union[int, str]] = None):
    """Configure the "evotorch_tpu" python logging channel (reference
    ``misc.py:2072-2142`` ``set_default_logger_config``; verbosity also
    settable via the ``EVOTORCH_TPU_VERBOSE_LEVEL`` env var, the analog of
    ``EVOTORCH_VERBOSE_LEVEL``, reference ``__init__.py:42-53``)."""
    import logging as _logging
    import os as _os

    logger = _logging.getLogger("evotorch_tpu")
    if level is None:
        level = _os.environ.get("EVOTORCH_TPU_VERBOSE_LEVEL", "INFO")
    if isinstance(level, str) and level.isdigit():
        level = int(level)
    logger.setLevel(level)
    if not logger.handlers:
        handler = _logging.StreamHandler()
        handler.setFormatter(_logging.Formatter("[%(asctime)s] %(levelname)s <%(name)s> %(message)s"))
        logger.addHandler(handler)
    return logger
