"""``ObjectArray``: 1-D container of arbitrary objects with array-like indexing.

Parity: reference ``tools/objectarray.py:39-534``. Object-dtype solutions
(variable-length genomes, trees, …) cannot live in TPU HBM; this container is
deliberately host-side (numpy object array underneath) and enforces the same
storage discipline as the reference: values are stored as immutable clones
(``as_immutable``) so views can be shared safely.
"""

from __future__ import annotations

from collections.abc import Sequence
from typing import Iterable, Optional

import numpy as np

from .immutable import as_immutable, mutable_copy

__all__ = ["ObjectArray"]


def _elements_equal(a, b) -> bool:
    """Scalar equality that tolerates array-valued elements."""
    try:
        import jax

        if isinstance(a, (np.ndarray, jax.Array)) or isinstance(b, (np.ndarray, jax.Array)):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        result = a == b
        if isinstance(result, np.ndarray):
            return bool(result.all())
        return bool(result)
    except (TypeError, ValueError):
        return False


class ObjectArray(Sequence):
    dtype = object

    def __init__(self, size: Optional[int] = None, *, slice_of=None):
        if slice_of is not None:
            source, sl = slice_of
            if size is not None:
                raise ValueError("Cannot give both size and slice_of")
            if not isinstance(source, ObjectArray):
                raise TypeError("slice_of must reference an ObjectArray")
            self._data = source._data[sl]  # numpy view: shares storage
            self._read_only = source._read_only
        else:
            if size is None:
                size = 0
            self._data = np.empty(int(size), dtype=object)
            self._read_only = False

    # -- factory ------------------------------------------------------------
    @classmethod
    def from_values(cls, values: Iterable) -> "ObjectArray":
        values = list(values)
        result = cls(len(values))
        for i, v in enumerate(values):
            result[i] = v
        return result

    @staticmethod
    def from_numpy(ndarray: np.ndarray) -> "ObjectArray":
        """New ObjectArray from a 1-D numpy object array
        (reference ``objectarray.py:512``)."""
        if ndarray.ndim != 1:
            raise ValueError(f"Expected a 1-D array, got ndim={ndarray.ndim}")
        return ObjectArray.from_values(ndarray)

    # -- tensor-like introspection (reference objectarray.py:204-311) --------
    @property
    def shape(self) -> tuple:
        return (len(self._data),)

    def size(self, dim: Optional[int] = None):
        """The shape tuple, or the size along ``dim`` (torch-style)."""
        if dim is None:
            return self.shape
        if dim not in (0, -1):
            raise IndexError(f"ObjectArray is 1-D; no dimension {dim}")
        return len(self._data)

    @property
    def ndim(self) -> int:
        return 1

    def dim(self) -> int:
        return 1

    def numel(self) -> int:
        return len(self._data)

    @property
    def device(self) -> str:
        """Always host-side (reference ``objectarray.py:299``: always cpu) —
        object dtype never lives in device HBM."""
        return "cpu"

    def repeat(self, *sizes: int) -> "ObjectArray":
        """Tile the array (torch ``repeat`` semantics for a 1-D tensor:
        exactly one repeat count; reference ``objectarray.py:244``)."""
        if len(sizes) != 1:
            raise ValueError(
                "ObjectArray is 1-D: repeat expects exactly one repeat count"
            )
        (n,) = sizes
        result = ObjectArray(len(self._data) * int(n))
        for rep in range(int(n)):
            base = rep * len(self._data)
            for i, v in enumerate(self._data):
                result._data[base + i] = v  # elements are immutable: share
        return result

    # -- element access ------------------------------------------------------
    def __getitem__(self, i):
        if isinstance(i, slice):
            return ObjectArray(slice_of=(self, i))
        if isinstance(i, (list, np.ndarray)) and not np.isscalar(i):
            idx = np.asarray(i)
            if idx.dtype == bool:
                idx = np.nonzero(idx)[0]
            picked = ObjectArray(len(idx))
            picked._data[:] = self._data[idx]
            picked._read_only = self._read_only
            return picked
        return self._data[int(i)]

    def __setitem__(self, i, value):
        if self._read_only:
            raise ValueError("Cannot modify a read-only ObjectArray")
        if isinstance(i, slice):
            values = [as_immutable(v) for v in value]
            indices = list(range(*i.indices(len(self._data))))
            if len(indices) != len(values):
                raise ValueError("Slice assignment length mismatch")
            # assign one-by-one to avoid numpy flattening sequence values
            for j, v in zip(indices, values):
                self._data[j] = v
        else:
            self._data[int(i)] = as_immutable(value)

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self):
        for i in range(len(self)):
            yield self._data[i]

    def set_item(self, i, value, *, memo: Optional[dict] = None):
        """Explicit-name form of ``self[i] = value``
        (reference ``objectarray.py:344``)."""
        del memo  # immutable storage: no cycle bookkeeping needed
        self[i] = value

    # -- semantics -----------------------------------------------------------
    def clone(
        self, *, preserve_read_only: bool = False, memo: Optional[dict] = None
    ) -> "ObjectArray":
        if memo is None:
            memo = {}
        existing = memo.get(id(self))
        if existing is not None:
            return existing
        result = ObjectArray(len(self))
        memo[id(self)] = result
        for i in range(len(self)):
            result._data[i] = mutable_copy(self._data[i])
        if preserve_read_only and self._read_only:
            result = result.get_read_only_view()
        return result

    def __copy__(self) -> "ObjectArray":
        return self.clone(preserve_read_only=True)

    def __deepcopy__(self, memo: Optional[dict]) -> "ObjectArray":
        return self.clone(preserve_read_only=True, memo=memo)

    def get_read_only_view(self) -> "ObjectArray":
        view = ObjectArray(slice_of=(self, slice(None)))
        view._read_only = True
        return view

    @property
    def is_read_only(self) -> bool:
        return self._read_only

    def numpy(self) -> np.ndarray:
        return self._data.copy()

    def storage_ptr(self) -> int:
        """Address of the underlying buffer — identical for views sharing
        storage (the reference's ``storage().data_ptr()`` shared-memory
        check, ``objectarray.py:31-36, 479``)."""
        base = self._data
        while base.base is not None:
            base = base.base
        return base.__array_interface__["data"][0]

    def __eq__(self, other):
        if isinstance(other, ObjectArray):
            other = list(other)
        if isinstance(other, (list, tuple)):
            if len(self) != len(other):
                return np.zeros(len(self), dtype=bool)
            return np.array(
                [_elements_equal(a, b) for a, b in zip(list(self), other)], dtype=bool
            )
        return NotImplemented

    __hash__ = None

    def __repr__(self) -> str:
        return f"ObjectArray({list(self._data)!r})"
