"""``ObjectArray``: 1-D container of arbitrary objects with array-like indexing.

Parity: reference ``tools/objectarray.py:39-534``. Object-dtype solutions
(variable-length genomes, trees, …) cannot live in TPU HBM; this container is
deliberately host-side (numpy object array underneath) and enforces the same
storage discipline as the reference: values are stored as immutable clones
(``as_immutable``) so views can be shared safely.
"""

from __future__ import annotations

from collections.abc import Sequence
from typing import Iterable, Optional

import numpy as np

from .immutable import as_immutable, mutable_copy

__all__ = ["ObjectArray"]


def _elements_equal(a, b) -> bool:
    """Scalar equality that tolerates array-valued elements."""
    try:
        import jax

        if isinstance(a, (np.ndarray, jax.Array)) or isinstance(b, (np.ndarray, jax.Array)):
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        result = a == b
        if isinstance(result, np.ndarray):
            return bool(result.all())
        return bool(result)
    except (TypeError, ValueError):
        return False


class ObjectArray(Sequence):
    dtype = object

    def __init__(self, size: Optional[int] = None, *, slice_of=None):
        if slice_of is not None:
            source, sl = slice_of
            if size is not None:
                raise ValueError("Cannot give both size and slice_of")
            if not isinstance(source, ObjectArray):
                raise TypeError("slice_of must reference an ObjectArray")
            self._data = source._data[sl]  # numpy view: shares storage
            self._read_only = source._read_only
        else:
            if size is None:
                size = 0
            self._data = np.empty(int(size), dtype=object)
            self._read_only = False

    # -- factory ------------------------------------------------------------
    @classmethod
    def from_values(cls, values: Iterable) -> "ObjectArray":
        values = list(values)
        result = cls(len(values))
        for i, v in enumerate(values):
            result[i] = v
        return result

    # -- element access ------------------------------------------------------
    def __getitem__(self, i):
        if isinstance(i, slice):
            return ObjectArray(slice_of=(self, i))
        if isinstance(i, (list, np.ndarray)) and not np.isscalar(i):
            idx = np.asarray(i)
            if idx.dtype == bool:
                idx = np.nonzero(idx)[0]
            picked = ObjectArray(len(idx))
            picked._data[:] = self._data[idx]
            picked._read_only = self._read_only
            return picked
        return self._data[int(i)]

    def __setitem__(self, i, value):
        if self._read_only:
            raise ValueError("Cannot modify a read-only ObjectArray")
        if isinstance(i, slice):
            values = [as_immutable(v) for v in value]
            indices = list(range(*i.indices(len(self._data))))
            if len(indices) != len(values):
                raise ValueError("Slice assignment length mismatch")
            # assign one-by-one to avoid numpy flattening sequence values
            for j, v in zip(indices, values):
                self._data[j] = v
        else:
            self._data[int(i)] = as_immutable(value)

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self):
        for i in range(len(self)):
            yield self._data[i]

    # -- semantics -----------------------------------------------------------
    def clone(self, *, memo: Optional[dict] = None) -> "ObjectArray":
        result = ObjectArray(len(self))
        for i in range(len(self)):
            result._data[i] = mutable_copy(self._data[i])
        return result

    def get_read_only_view(self) -> "ObjectArray":
        view = ObjectArray(slice_of=(self, slice(None)))
        view._read_only = True
        return view

    @property
    def is_read_only(self) -> bool:
        return self._read_only

    def numpy(self) -> np.ndarray:
        return self._data.copy()

    def __eq__(self, other):
        if isinstance(other, ObjectArray):
            other = list(other)
        if isinstance(other, (list, tuple)):
            if len(self) != len(other):
                return np.zeros(len(self), dtype=bool)
            return np.array(
                [_elements_equal(a, b) for a, b in zip(list(self), other)], dtype=bool
            )
        return NotImplemented

    __hash__ = None

    def __repr__(self) -> str:
        return f"ObjectArray({list(self._data)!r})"
