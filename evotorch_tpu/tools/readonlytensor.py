"""Read-only tensor shims.

Parity: reference ``tools/readonlytensor.py:27-226`` (``ReadOnlyTensor``,
``read_only_tensor``, ``as_read_only_tensor``). The reference subclasses
``torch.Tensor`` to block in-place mutation; **jax.Arrays are immutable by
construction**, so the read-only discipline holds for every array in this
framework and these helpers reduce to coercions (numpy inputs are returned as
write-protected views).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ReadOnlyTensor", "read_only_tensor", "as_read_only_tensor", "is_read_only"]

# every jax.Array is already read-only
ReadOnlyTensor = jax.Array


def read_only_tensor(x: Any, *, dtype=None) -> jax.Array:
    """A read-only (jax) array holding a copy of ``x``."""
    return jnp.asarray(x, dtype=dtype)


def as_read_only_tensor(x: Any, *, dtype=None) -> Any:
    """Coerce to a read-only view: jax arrays pass through; numpy arrays are
    returned as non-writeable views; others are converted to jax arrays."""
    if isinstance(x, jax.Array):
        return x
    if isinstance(x, np.ndarray) and (dtype is None or x.dtype == np.dtype(dtype)):
        view = x.view()
        view.setflags(write=False)
        return view
    return jnp.asarray(x, dtype=dtype)


def is_read_only(x: Any) -> bool:
    if isinstance(x, jax.Array):
        return True
    if isinstance(x, np.ndarray):
        return not x.flags.writeable
    return False
