"""Alias module: ``evotorch_tpu.utils`` is ``evotorch_tpu.tools``.

The reference names this layer ``tools`` (``src/evotorch/tools/``); both the
symbols and the submodules resolve under either name.
"""

from . import tools as _tools
from .tools import *  # noqa: F401,F403
from .tools import (  # noqa: F401 — submodules reachable via the alias too
    cloning,
    constraints,
    hook,
    immutable,
    misc,
    objectarray,
    pytree,
    ranking,
    readonlytensor,
    structures,
    tensorframe,
)

__all__ = _tools.__all__
