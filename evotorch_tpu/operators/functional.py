"""Pure-functional variation operators and pareto kernels.

Parity: reference ``operators/functional.py`` (2193 LoC) — ``tournament``
(``functional.py:817-990``), k-point crossover (``functional.py:1091-1387``),
SBX (``functional.py:1411-1510``), ``utility`` (``functional.py:1580-1634``),
``cosyne_permutation`` (``functional.py:1737-1792``), ``combine``
(``functional.py:1852-2011``), ``take_best`` (``functional.py:2111-2193``),
domination utilities (``functional.py:240-497``) and crowding distances
(``functional.py:357-447``) — plus the pareto-rank kernels of
``core.py:3423-3587``.

TPU-first notes:
- Functions that use randomness take an explicit leading PRNG ``key``
  (the reference relies on torch global RNG).
- Pareto front peeling is a ``lax.while_loop`` with a data-independent body,
  so the whole NSGA-II selection path jits (the reference's Python
  ``while unranked.any()`` loop, ``core.py:3529-3549``, does not).
- Everything operates on the last one/two axes; extra leftmost dims are batch
  dims (via ``expects_ndim``).
- Object-dtype populations (``ObjectArray``) take host-side numpy paths.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..decorators import expects_ndim
from ..tools.objectarray import ObjectArray
from ..tools.ranking import rank

__all__ = [
    "TournamentResult",
    "dominates",
    "domination_matrix",
    "domination_counts",
    "pareto_ranks",
    "crowding_distances",
    "pareto_utility",
    "utility",
    "tournament",
    "multi_point_cross_over",
    "one_point_cross_over",
    "two_point_cross_over",
    "simulated_binary_cross_over",
    "gaussian_mutation",
    "polynomial_mutation",
    "cosyne_permutation",
    "combine",
    "take_best",
]


# ---------------------------------------------------------------------------
# Pareto kernels
# ---------------------------------------------------------------------------


def _sign_adjusted(evals: jnp.ndarray, objective_sense: list) -> jnp.ndarray:
    """Flip minimized objectives so that higher is better on every column."""
    if isinstance(objective_sense, str):
        raise ValueError(
            "Multi-objective utilities expect `objective_sense` as a list of 'min'/'max' strings"
        )
    signs = jnp.asarray([1.0 if s == "max" else -1.0 for s in objective_sense])
    return evals * signs


@expects_ndim(1, 1, None)
def _dominates(evals1, evals2, objective_sense):
    adj1 = _sign_adjusted(evals1, objective_sense)
    adj2 = _sign_adjusted(evals2, objective_sense)
    return jnp.all(adj1 >= adj2) & jnp.any(adj1 > adj2)


def dominates(evals1, evals2, *, objective_sense: list):
    """True if ``evals1`` pareto-dominates ``evals2``
    (reference ``functional.py:240-276``)."""
    return _dominates(evals1, evals2, objective_sense)


@expects_ndim(2, None)
def _domination_matrix(evals, objective_sense):
    adj = _sign_adjusted(evals, objective_sense)
    no_worse = jnp.all(adj[:, None, :] >= adj[None, :, :], axis=-1)
    better = jnp.any(adj[:, None, :] > adj[None, :, :], axis=-1)
    return no_worse & better


def domination_matrix(evals, *, objective_sense: list):
    """Boolean ``(N, N)`` matrix whose ``[i, j]`` entry says "solution i
    dominates solution j" (reference ``functional.py:289-320``, orientation
    documented there as ``[i, j] = j dominates i``; we use the transpose and
    say so explicitly here)."""
    return _domination_matrix(evals, objective_sense)


@expects_ndim(2, None)
def _domination_counts(evals, objective_sense):
    return jnp.sum(_domination_matrix.__wrapped__(evals, objective_sense), axis=0)


def domination_counts(evals, *, objective_sense: list):
    """For each solution, the number of solutions dominating it; 0 means the
    solution is on the pareto front (reference ``functional.py:321-346``)."""
    return _domination_counts(evals, objective_sense)


@expects_ndim(2, None)
def _pareto_ranks(evals, objective_sense):
    n = evals.shape[0]
    dom = _domination_matrix.__wrapped__(evals, objective_sense)  # [i,j]: i dominates j

    def cond(carry):
        ranks, unranked, k = carry
        return jnp.any(unranked)

    def body(carry):
        ranks, unranked, k = carry
        # a solution is in the current front if it is unranked and no
        # unranked solution dominates it
        dominated_by_unranked = jnp.any(dom & unranked[:, None], axis=0)
        front = unranked & ~dominated_by_unranked
        ranks = jnp.where(front, k, ranks)
        return ranks, unranked & ~front, k + 1

    ranks0 = jnp.zeros(n, dtype=jnp.int32)
    unranked0 = jnp.ones(n, dtype=bool)
    ranks, _, _ = jax.lax.while_loop(cond, body, (ranks0, unranked0, jnp.int32(0)))
    return ranks


def pareto_ranks(evals, *, objective_sense: list):
    """Front index per solution (0 = best front), via iterative front peeling
    expressed as a jit-friendly ``lax.while_loop`` (the GPU-friendly
    formulation of reference ``core.py:3480-3551``)."""
    return _pareto_ranks(evals, objective_sense)


@expects_ndim(2, 1, None)
def _crowding_distances(evals, ranks, objective_sense):
    """NSGA-II crowding distances computed front-wise but fully vectorized:
    for each objective, solutions are sorted and the gap between same-front
    neighbors is accumulated; front-boundary solutions get +inf
    (reference ``core.py:3432-3477``, ``functional.py:357-447``)."""
    adj = _sign_adjusted(evals, objective_sense)
    n, k = adj.shape
    total = jnp.zeros(n, dtype=adj.dtype)
    big = jnp.inf

    def per_objective(j, total):
        vals = adj[:, j]
        # sort primarily by front, secondarily by objective value, so that
        # neighbors in the sorted order belong to the same front
        order = jnp.lexsort((vals, ranks))
        sorted_vals = vals[order]
        sorted_ranks = ranks[order]
        prev_vals = jnp.concatenate([sorted_vals[:1], sorted_vals[:-1]])
        next_vals = jnp.concatenate([sorted_vals[1:], sorted_vals[-1:]])
        prev_same = jnp.concatenate(
            [jnp.array([False]), sorted_ranks[1:] == sorted_ranks[:-1]]
        )
        next_same = jnp.concatenate(
            [sorted_ranks[:-1] == sorted_ranks[1:], jnp.array([False])]
        )
        # canonical NSGA-II normalizes each neighbor gap by the objective's
        # min/max *within the front* (ADVICE r1), not the global range
        front_max = jax.ops.segment_max(vals, ranks, num_segments=n)
        front_min = jax.ops.segment_min(vals, ranks, num_segments=n)
        front_range = front_max - front_min
        front_range = jnp.where(front_range <= 0, 1.0, front_range)
        dist = jnp.where(
            prev_same & next_same,
            (next_vals - prev_vals) / front_range[sorted_ranks],
            big,
        )
        # scatter back to original order
        contribution = jnp.zeros(n, dtype=adj.dtype).at[order].set(dist)
        return total + contribution

    total = jax.lax.fori_loop(0, k, per_objective, total)
    return total


def crowding_distances(evals, *, objective_sense: list, ranks=None):
    """Crowding distance per solution; boundary solutions of each front get
    ``+inf`` (reference ``functional.py:430-447``)."""
    if ranks is None:
        ranks = pareto_ranks(evals, objective_sense=objective_sense)
    return _crowding_distances(evals, ranks, objective_sense)


@expects_ndim(2, None, None)
def _pareto_utility(evals, objective_sense, crowdsort):
    ranks = _pareto_ranks.__wrapped__(evals, objective_sense)
    utilities = -ranks.astype(evals.dtype)
    if crowdsort:
        crowd = _crowding_distances.__wrapped__(evals, ranks, objective_sense)
        n = evals.shape[0]
        # map crowding to (0, 1) via global ordinal rank; a monotone map
        # preserves the within-front ordering while keeping the contribution
        # strictly below one front step
        crowd_rank = jnp.argsort(jnp.argsort(crowd)).astype(evals.dtype)
        utilities = utilities + crowd_rank / (n + 1)
    return utilities


def pareto_utility(evals, *, objective_sense: list, crowdsort: bool = True):
    """Scalar utility per solution for multi-objective selection: higher means
    better front, ties broken by crowding distance
    (reference ``functional.py:449-497``)."""
    return _pareto_utility(evals, objective_sense, bool(crowdsort))


# ---------------------------------------------------------------------------
# Fitness shaping
# ---------------------------------------------------------------------------


def utility(evals, *, objective_sense: str, ranking_method: Optional[str] = "centered"):
    """Fitness-shaped utilities, higher = better
    (reference ``functional.py:1580-1634``). Works along the last axis."""
    if not isinstance(objective_sense, str):
        return pareto_utility(evals, objective_sense=objective_sense)
    higher_is_better = {"max": True, "min": False}[objective_sense]
    if ranking_method is None:
        ranking_method = "raw"
    return rank(evals, ranking_method, higher_is_better=higher_is_better)




def _apply_with_per_lane_keys(core, key, arg_specs, args, statics=()):
    """Run ``core(*args_unbatched, *statics, key)`` with extra leading dims on
    the arrays treated as batch dims — splitting the PRNG key per batch lane
    so parallel (batched) searches get independent randomness.

    ``arg_specs`` gives each array's core ndim. Batch shapes broadcast.
    """
    import math as _math

    args = [jnp.asarray(a) for a in args]
    batch_shape = ()
    for a, nd in zip(args, arg_specs):
        batch_shape = jnp.broadcast_shapes(batch_shape, a.shape[: a.ndim - nd])
    if batch_shape == ():
        return core(*args, *statics, key)
    bsize = _math.prod(batch_shape)
    flat = []
    for a, nd in zip(args, arg_specs):
        core_shape = a.shape[a.ndim - nd :]
        flat.append(jnp.broadcast_to(a, batch_shape + core_shape).reshape((bsize,) + core_shape))
    keys = jax.random.split(key, bsize)
    out = jax.vmap(lambda *xs: core(*xs[:-1], *statics, xs[-1]))(*flat, keys)
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape(batch_shape + leaf.shape[1:]), out
    )

# ---------------------------------------------------------------------------
# Tournament selection
# ---------------------------------------------------------------------------


class TournamentResult(NamedTuple):
    parent1_values: Union[jnp.ndarray, ObjectArray]
    parent1_evals: Optional[jnp.ndarray]
    parent2_values: Union[jnp.ndarray, ObjectArray]
    parent2_evals: Optional[jnp.ndarray]


def _tournament_utilities(evals: jnp.ndarray, objective_sense) -> jnp.ndarray:
    if isinstance(objective_sense, str):
        return utility(evals, objective_sense=objective_sense, ranking_method="centered")
    return pareto_utility(evals, objective_sense=objective_sense)


@expects_ndim(1, None, None, None)
@partial(jax.jit, static_argnums=(1, 2))
def _tournament_indices(utilities, num_tournaments, tournament_size, key):
    """Two exclusive tournament sets (reference ``functional.py:500-578``):
    the winner of first-set tournament ``i`` is guaranteed not to participate
    in second-set tournament ``i`` (so each crossover pairs two distinct
    parents)."""
    n = utilities.shape[0]
    half = num_tournaments // 2
    key1, key2 = jax.random.split(key)
    cand1 = jax.random.randint(key1, (half, tournament_size), 0, n)
    win1_pos = jnp.argmax(utilities[cand1], axis=-1)
    winners1 = jnp.take_along_axis(cand1, win1_pos[:, None], axis=-1)[:, 0]
    # second set: draw from {0..n-2} and shift past the corresponding first
    # winner, excluding it from the tournament
    cand2 = jax.random.randint(key2, (half, tournament_size), 0, n - 1)
    cand2 = jnp.where(cand2 >= winners1[:, None], cand2 + 1, cand2)
    win2_pos = jnp.argmax(utilities[cand2], axis=-1)
    winners2 = jnp.take_along_axis(cand2, win2_pos[:, None], axis=-1)[:, 0]
    return jnp.concatenate([winners1, winners2])


def tournament(
    key,
    solutions: Union[jnp.ndarray, ObjectArray],
    evals: jnp.ndarray,
    *,
    num_tournaments: int,
    tournament_size: int,
    objective_sense: Union[str, list],
    return_indices: bool = False,
    with_evals: bool = False,
    split_results: bool = False,
):
    """Random pairs of tournaments; winners form two parent sets
    (reference ``functional.py:817-990``). Result forms follow the reference:
    indices / values / (values, evals), optionally split into the two sets."""
    num_tournaments = int(num_tournaments)
    tournament_size = int(tournament_size)
    if num_tournaments % 2 != 0:
        raise ValueError(f"num_tournaments must be even, got {num_tournaments}")
    evals = jnp.asarray(evals)
    utilities = _tournament_utilities(evals, objective_sense)

    if isinstance(solutions, ObjectArray):
        # host-side path for object-dtype populations
        util_np = np.asarray(utilities)
        n = len(solutions)
        rng = np.random.default_rng(np.asarray(jax.random.key_data(key)).ravel())
        half = num_tournaments // 2
        cand1 = rng.integers(0, n, size=(half, tournament_size))
        winners1 = cand1[np.arange(half), np.argmax(util_np[cand1], axis=-1)]
        cand2 = rng.integers(0, n - 1, size=(half, tournament_size))
        cand2 = np.where(cand2 >= winners1[:, None], cand2 + 1, cand2)
        winners2 = cand2[np.arange(half), np.argmax(util_np[cand2], axis=-1)]
        indices = np.concatenate([winners1, winners2])
        if return_indices:
            result = jnp.asarray(indices)
            return (result[:half], result[half:]) if split_results else result
        picked = solutions[indices]
        picked_evals = jnp.asarray(np.asarray(evals)[indices]) if with_evals else None
        if split_results:
            p1, p2 = picked[:half], picked[half:]
            if with_evals:
                return TournamentResult(p1, picked_evals[:half], p2, picked_evals[half:])
            return p1, p2
        return (picked, picked_evals) if with_evals else picked

    solutions = jnp.asarray(solutions)
    # batched: vmap over extra leftmost dims of evals with split keys
    batch_shape = utilities.shape[:-1]
    if batch_shape == ():
        indices = _tournament_indices.__wrapped__(
            utilities, num_tournaments, tournament_size, key
        )
    else:
        import math as _math

        bsize = _math.prod(batch_shape)
        keys = jax.random.split(key, bsize)
        flat_util = utilities.reshape((bsize, utilities.shape[-1]))
        indices = jax.vmap(
            lambda u, k: _tournament_indices.__wrapped__(
                u, num_tournaments, tournament_size, k
            )
        )(flat_util, keys)
        indices = indices.reshape(batch_shape + (num_tournaments,))

    half = num_tournaments // 2
    if return_indices:
        if split_results:
            return indices[..., :half], indices[..., half:]
        return indices

    picked = jnp.take_along_axis(
        solutions, indices[..., None], axis=-2
    )
    picked_evals = (
        jnp.take_along_axis(evals, indices, axis=-1)
        if evals.ndim == utilities.ndim
        else jnp.take_along_axis(evals, indices[..., None], axis=-2)
    ) if with_evals else None
    if split_results:
        p1, p2 = picked[..., :half, :], picked[..., half:, :]
        if with_evals:
            e1 = picked_evals[..., :half] if picked_evals.ndim == indices.ndim else picked_evals[..., :half, :]
            e2 = picked_evals[..., half:] if picked_evals.ndim == indices.ndim else picked_evals[..., half:, :]
            return TournamentResult(p1, e1, p2, e2)
        return p1, p2
    return (picked, picked_evals) if with_evals else picked


# ---------------------------------------------------------------------------
# Crossover
# ---------------------------------------------------------------------------


def _maybe_tournament(key, parents, evals, tournament_size, num_children, objective_sense):
    """Shared preamble (reference ``functional.py:1155-1190``): either split
    the given parents in half, or pick them via tournament."""
    if tournament_size is None:
        if num_children is not None:
            raise ValueError("`num_children` requires `tournament_size`")
        n = parents.shape[-2]
        if n % 2 != 0:
            raise ValueError(f"Number of parents must be even, got {n}")
        half = n // 2
        return key, parents[..., :half, :], parents[..., half:, :]
    if evals is None or objective_sense is None:
        raise ValueError("tournament selection requires `evals` and `objective_sense`")
    if num_children is None:
        num_children = parents.shape[-2]
    if num_children % 2 != 0:
        raise ValueError(f"num_children must be even, got {num_children}")
    key, sub = jax.random.split(key)
    p1, p2 = tournament(
        sub,
        parents,
        evals,
        num_tournaments=num_children,
        tournament_size=tournament_size,
        objective_sense=objective_sense,
        split_results=True,
    )
    return key, p1, p2


@partial(jax.jit, static_argnums=(2,))
def _kpoint_crossover_core(parents1, parents2, num_points, key):
    half, length = parents1.shape
    num_points = min(int(num_points), length - 1)
    # sample cut points in [1, length) per pair; build a parity mask
    cuts = jax.random.randint(key, (half, num_points), 1, length)
    positions = jnp.arange(length)
    counts = jnp.sum(positions[None, None, :] >= cuts[:, :, None], axis=1)
    use_other = (counts % 2) == 1
    child1 = jnp.where(use_other, parents2, parents1)
    child2 = jnp.where(use_other, parents1, parents2)
    return jnp.concatenate([child1, child2], axis=0)


def multi_point_cross_over(
    key,
    parents: jnp.ndarray,
    evals: Optional[jnp.ndarray] = None,
    *,
    num_points: int,
    tournament_size: Optional[int] = None,
    num_children: Optional[int] = None,
    objective_sense=None,
) -> jnp.ndarray:
    """Vectorized k-point crossover (reference ``functional.py:1091-1190``):
    each pair is cut at ``num_points`` random positions and recombined; two
    complementary children per pair."""
    parents = jnp.asarray(parents)
    key, p1, p2 = _maybe_tournament(key, parents, evals, tournament_size, num_children, objective_sense)
    key, sub = jax.random.split(key)
    return _apply_with_per_lane_keys(
        _kpoint_crossover_core, sub, (2, 2), (p1, p2), statics=(int(num_points),)
    )


def one_point_cross_over(key, parents, evals=None, *, tournament_size=None, num_children=None, objective_sense=None):
    """Reference ``functional.py:1192-1288``."""
    return multi_point_cross_over(
        key, parents, evals, num_points=1, tournament_size=tournament_size,
        num_children=num_children, objective_sense=objective_sense,
    )


def two_point_cross_over(key, parents, evals=None, *, tournament_size=None, num_children=None, objective_sense=None):
    """Reference ``functional.py:1290-1387``."""
    return multi_point_cross_over(
        key, parents, evals, num_points=2, tournament_size=tournament_size,
        num_children=num_children, objective_sense=objective_sense,
    )


@jax.jit
def _sbx_core(parents1, parents2, eta, key):
    u = jax.random.uniform(key, parents1.shape, dtype=parents1.dtype)
    beta = jnp.where(
        u <= 0.5,
        (2 * u) ** (1.0 / (eta + 1.0)),
        (1.0 / (2 * (1.0 - u))) ** (1.0 / (eta + 1.0)),
    )
    child1 = 0.5 * ((1 + beta) * parents1 + (1 - beta) * parents2)
    child2 = 0.5 * ((1 - beta) * parents1 + (1 + beta) * parents2)
    return jnp.concatenate([child1, child2], axis=0)


def simulated_binary_cross_over(
    key,
    parents: jnp.ndarray,
    evals: Optional[jnp.ndarray] = None,
    *,
    eta: Union[float, jnp.ndarray],
    tournament_size: Optional[int] = None,
    num_children: Optional[int] = None,
    objective_sense=None,
) -> jnp.ndarray:
    """SBX (Deb & Kumar 1995; reference ``functional.py:1389-1510``)."""
    parents = jnp.asarray(parents)
    key, p1, p2 = _maybe_tournament(key, parents, evals, tournament_size, num_children, objective_sense)
    key, sub = jax.random.split(key)
    return _apply_with_per_lane_keys(
        _sbx_core, sub, (2, 2, 0), (p1, p2, jnp.asarray(eta, dtype=parents.dtype))
    )


# ---------------------------------------------------------------------------
# Mutation (extensions: the reference expresses these via its OO operators,
# operators/real.py:30-66 and 484-604; provided functionally here)
# ---------------------------------------------------------------------------


@jax.jit
def _gaussian_mutation_core(values, stdev, key):
    noise = jax.random.normal(key, values.shape, dtype=values.dtype) * stdev
    return values + noise


@jax.jit
def _gaussian_mutation_core_gated(values, stdev, mutation_probability, key):
    # probability is a traced array: annealing it across generations reuses
    # one compiled executable instead of recompiling per value
    key1, key2 = jax.random.split(key)
    noise = jax.random.normal(key1, values.shape, dtype=values.dtype) * stdev
    mask = jax.random.uniform(key2, values.shape) < mutation_probability
    return values + jnp.where(mask, noise, 0.0)


def gaussian_mutation(key, values, *, stdev, mutation_probability: Optional[float] = None):
    """Additive Gaussian noise, optionally per-element gated
    (reference OO operator ``operators/real.py:30-66``). Batched inputs get
    independent noise per batch lane."""
    values = jnp.asarray(values)
    stdev = jnp.asarray(stdev, dtype=values.dtype)
    if mutation_probability is None:
        return _apply_with_per_lane_keys(
            _gaussian_mutation_core, key, (2, 0), (values, stdev)
        )
    return _apply_with_per_lane_keys(
        _gaussian_mutation_core_gated,
        key,
        (2, 0, 0),
        (values, stdev, jnp.asarray(mutation_probability, dtype=values.dtype)),
    )


def _polynomial_delta(values, lb, ub, eta, u):
    span = ub - lb
    delta1 = (values - lb) / span
    delta2 = (ub - values) / span
    mut_pow = 1.0 / (eta + 1.0)
    xy1 = 1.0 - delta1
    xy2 = 1.0 - delta2
    val1 = 2.0 * u + (1.0 - 2.0 * u) * xy1 ** (eta + 1.0)
    val2 = 2.0 * (1.0 - u) + 2.0 * (u - 0.5) * xy2 ** (eta + 1.0)
    deltaq = jnp.where(u <= 0.5, val1**mut_pow - 1.0, 1.0 - val2**mut_pow)
    return values + deltaq * span


@jax.jit
def _polynomial_mutation_core(values, lb, ub, eta, key):
    u = jax.random.uniform(key, values.shape, dtype=values.dtype)
    return jnp.clip(_polynomial_delta(values, lb, ub, eta, u), lb, ub)


@jax.jit
def _polynomial_mutation_core_gated(values, lb, ub, eta, mutation_probability, key):
    key1, key2 = jax.random.split(key)
    u = jax.random.uniform(key1, values.shape, dtype=values.dtype)
    mutated = _polynomial_delta(values, lb, ub, eta, u)
    mask = jax.random.uniform(key2, values.shape) < mutation_probability
    return jnp.clip(jnp.where(mask, mutated, values), lb, ub)


def polynomial_mutation(key, values, *, lb, ub, eta: float = 20.0, mutation_probability: Optional[float] = None):
    """Bounded polynomial mutation (Deb & Deb 2014; reference OO operator
    ``operators/real.py:484-604``). Batched inputs get independent noise per
    batch lane."""
    values = jnp.asarray(values)
    lb = jnp.broadcast_to(jnp.asarray(lb, dtype=values.dtype), values.shape[-1:])
    ub = jnp.broadcast_to(jnp.asarray(ub, dtype=values.dtype), values.shape[-1:])
    eta = jnp.asarray(eta, dtype=values.dtype)
    if mutation_probability is None:
        return _apply_with_per_lane_keys(
            _polynomial_mutation_core, key, (2, 1, 1, 0), (values, lb, ub, eta)
        )
    return _apply_with_per_lane_keys(
        _polynomial_mutation_core_gated,
        key,
        (2, 1, 1, 0, 0),
        (values, lb, ub, eta, jnp.asarray(mutation_probability, dtype=values.dtype)),
    )


# ---------------------------------------------------------------------------
# Cosyne permutation
# ---------------------------------------------------------------------------


@jax.jit
def _cosyne_full_permutation(values, key):
    n, length = values.shape
    noise = jax.random.uniform(key, (n, length))
    order = jnp.argsort(noise, axis=0)
    return jnp.take_along_axis(values, order, axis=0)


@partial(jax.jit, static_argnums=(2,))
def _cosyne_partial_permutation(values, evals, objective_sense, key):
    n = values.shape[0]
    key1, key2 = jax.random.split(key)
    permuted = _cosyne_full_permutation(values, key1)
    ranks = rank(evals, "linear", higher_is_better=(objective_sense == "max"))
    permutation_probs = 1.0 - ranks ** (1.0 / n)
    to_permute = jax.random.uniform(key2, values.shape) < permutation_probs[:, None]
    return jnp.where(to_permute, permuted, values)


def cosyne_permutation(
    key,
    values: jnp.ndarray,
    evals: Optional[jnp.ndarray] = None,
    *,
    permute_all: bool = True,
    objective_sense: Optional[str] = None,
) -> jnp.ndarray:
    """Column-wise shuffling of decision values (CoSyNE; reference
    ``functional.py:1737-1792``). With ``permute_all=False``, better solutions
    have a higher probability of keeping their values
    (``p_permute = 1 - linear_rank ** (1/n)``)."""
    values = jnp.asarray(values)
    if permute_all:
        return _apply_with_per_lane_keys(_cosyne_full_permutation, key, (2,), (values,))
    if evals is None or objective_sense is None:
        raise ValueError("When permute_all is False, `evals` and `objective_sense` are required")
    return _apply_with_per_lane_keys(
        lambda v, e, k: _cosyne_partial_permutation(v, e, objective_sense, k),
        key, (2, 1), (values, jnp.asarray(evals)),
    )


# ---------------------------------------------------------------------------
# Combine & take_best
# ---------------------------------------------------------------------------


def _is_pair(x) -> bool:
    return isinstance(x, (tuple, list)) and len(x) == 2


def combine(a, b, *, objective_sense=None):
    """Merge two populations (reference ``functional.py:1852-2011``).
    Accepts plain value arrays or ``(values, evals)`` pairs; ObjectArrays take
    the host-side path."""
    if _is_pair(a) != _is_pair(b):
        raise ValueError("combine expects both arguments in the same form (values or (values, evals))")
    if _is_pair(a):
        values1, evals1 = a
        values2, evals2 = b
        if isinstance(values1, ObjectArray) or isinstance(values2, ObjectArray):
            merged = ObjectArray.from_values(list(values1) + list(values2))
        else:
            merged = jnp.concatenate([jnp.asarray(values1), jnp.asarray(values2)], axis=-2)
        evals1 = jnp.asarray(evals1)
        evals2 = jnp.asarray(evals2)
        if evals1.ndim != evals2.ndim:
            raise ValueError("evals of both populations must have the same ndim")
        # multi-objective evals have a trailing objective axis: the solution
        # axis is -2 there, -1 for single-objective
        solution_axis = -2 if (objective_sense is not None and not isinstance(objective_sense, str)) else -1
        merged_evals = jnp.concatenate([evals1, evals2], axis=solution_axis)
        return merged, merged_evals
    if isinstance(a, ObjectArray) or isinstance(b, ObjectArray):
        return ObjectArray.from_values(list(a) + list(b))
    return jnp.concatenate([jnp.asarray(a), jnp.asarray(b)], axis=-2)


@expects_ndim(2, 1, None, None)
@partial(jax.jit, static_argnums=(2, 3))
def _take_best_single_obj(values, evals, n, maximize):
    utilities = evals if maximize else -evals
    if n is None:
        best = jnp.argmax(utilities)
        return values[best], evals[best]
    _, idx = jax.lax.top_k(utilities, n)
    return values[idx], evals[idx]


@expects_ndim(2, 2, None, None, None)
@partial(jax.jit, static_argnums=(2, 3, 4))
def _take_best_multi_obj(values, evals, n, objective_sense, crowdsort):
    utilities = _pareto_utility.__wrapped__(evals, list(objective_sense), crowdsort)
    _, idx = jax.lax.top_k(utilities, n)
    return values[idx], evals[idx]


def take_best(
    values,
    evals,
    n: Optional[int] = None,
    *,
    objective_sense,
    crowdsort: bool = True,
):
    """Take the best solution (``n=None``) or the best ``n`` solutions
    (reference ``functional.py:2111-2193``). Multi-objective selection uses
    pareto fronts with optional crowding tie-break (NSGA-II style)."""
    if isinstance(values, ObjectArray):
        evals_np = np.asarray(evals)
        if not isinstance(objective_sense, str):
            util = np.asarray(pareto_utility(jnp.asarray(evals_np), objective_sense=objective_sense, crowdsort=crowdsort))
        else:
            util = evals_np if objective_sense == "max" else -evals_np
        if n is None:
            i = int(np.argmax(util))
            return values[i], jnp.asarray(evals_np[i])
        idx = np.argsort(-util)[:n]
        return values[list(idx)], jnp.asarray(evals_np[idx])
    values = jnp.asarray(values)
    evals = jnp.asarray(evals)
    n = None if n is None else int(n)
    if isinstance(objective_sense, str):
        maximize = {"max": True, "min": False}[objective_sense]
        return _take_best_single_obj(values, evals, n, maximize)
    if n is None:
        raise ValueError("take_best with multiple objectives requires an explicit `n`")
    return _take_best_multi_obj(values, evals, n, tuple(objective_sense), bool(crowdsort))
