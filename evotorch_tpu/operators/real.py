"""OO operators on real-valued decision vectors.

Parity: reference ``operators/real.py`` — ``GaussianMutation``
(``real.py:30-66``), ``MultiPointCrossOver``/``OnePoint``/``TwoPoint``
(``real.py:69-389``), ``SimulatedBinaryCrossOver`` (``real.py:391-482``),
``PolynomialMutation`` (``real.py:484-604``), ``CosynePermutation``
(``real.py:606-706``).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import Problem, SolutionBatch
from . import functional as F
from .base import CopyingOperator, CrossOver

__all__ = [
    "GaussianMutation",
    "MultiPointCrossOver",
    "OnePointCrossOver",
    "TwoPointCrossOver",
    "SimulatedBinaryCrossOver",
    "PolynomialMutation",
    "CosynePermutation",
]


class GaussianMutation(CopyingOperator):
    """Additive Gaussian noise (reference ``real.py:30-66``)."""

    def __init__(self, problem: Problem, *, stdev: float, mutation_probability: Optional[float] = None):
        super().__init__(problem)
        self._stdev = float(stdev)
        self._mutation_probability = mutation_probability

    def _do(self, batch: SolutionBatch) -> SolutionBatch:
        mutated = F.gaussian_mutation(
            self._problem.next_rng_key(),
            batch.values,
            stdev=self._stdev,
            mutation_probability=self._mutation_probability,
        )
        return SolutionBatch(
            self._problem, mutated.shape[0], values=self._respect_bounds(mutated)
        )


class MultiPointCrossOver(CrossOver):
    """k-point crossover (reference ``real.py:69-389``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        tournament_size: int,
        num_points: int,
        obj_index: Optional[int] = None,
        num_children: Optional[int] = None,
        cross_over_rate: Optional[float] = None,
    ):
        super().__init__(
            problem,
            tournament_size=tournament_size,
            obj_index=obj_index,
            num_children=num_children,
            cross_over_rate=cross_over_rate,
        )
        self._num_points = int(num_points)
        if self._num_points < 1:
            raise ValueError(f"num_points must be >= 1, got {num_points}")

    def _do_cross_over(self, parents1, parents2) -> SolutionBatch:
        parents = jnp.concatenate([parents1, parents2], axis=0)
        children = F.multi_point_cross_over(
            self._problem.next_rng_key(), parents, num_points=self._num_points
        )
        return self._make_children_batch(children)


class OnePointCrossOver(MultiPointCrossOver):
    def __init__(self, problem: Problem, *, tournament_size: int, obj_index=None, num_children=None, cross_over_rate=None):
        super().__init__(
            problem, tournament_size=tournament_size, num_points=1,
            obj_index=obj_index, num_children=num_children, cross_over_rate=cross_over_rate,
        )


class TwoPointCrossOver(MultiPointCrossOver):
    def __init__(self, problem: Problem, *, tournament_size: int, obj_index=None, num_children=None, cross_over_rate=None):
        super().__init__(
            problem, tournament_size=tournament_size, num_points=2,
            obj_index=obj_index, num_children=num_children, cross_over_rate=cross_over_rate,
        )


class SimulatedBinaryCrossOver(CrossOver):
    """SBX (reference ``real.py:391-482``)."""

    def __init__(
        self,
        problem: Problem,
        *,
        tournament_size: int,
        eta: float,
        obj_index: Optional[int] = None,
        num_children: Optional[int] = None,
        cross_over_rate: Optional[float] = None,
    ):
        super().__init__(
            problem,
            tournament_size=tournament_size,
            obj_index=obj_index,
            num_children=num_children,
            cross_over_rate=cross_over_rate,
        )
        self._eta = float(eta)

    def _do_cross_over(self, parents1, parents2) -> SolutionBatch:
        parents = jnp.concatenate([parents1, parents2], axis=0)
        children = F.simulated_binary_cross_over(
            self._problem.next_rng_key(), parents, eta=self._eta
        )
        return self._make_children_batch(children)


class PolynomialMutation(CopyingOperator):
    """Bounded polynomial mutation (reference ``real.py:484-604``)."""

    def __init__(self, problem: Problem, *, eta: Optional[float] = None, mutation_probability: Optional[float] = None):
        super().__init__(problem)
        if problem.lower_bounds is None or problem.upper_bounds is None:
            raise ValueError("PolynomialMutation requires a bounded problem")
        self._eta = 20.0 if eta is None else float(eta)
        self._mutation_probability = mutation_probability

    def _do(self, batch: SolutionBatch) -> SolutionBatch:
        mutated = F.polynomial_mutation(
            self._problem.next_rng_key(),
            batch.values,
            lb=self._problem.lower_bounds,
            ub=self._problem.upper_bounds,
            eta=self._eta,
            mutation_probability=self._mutation_probability,
        )
        return SolutionBatch(self._problem, mutated.shape[0], values=mutated)


class CosynePermutation(CopyingOperator):
    """Rank-biased per-column permutation (reference ``real.py:606-706``)."""

    def __init__(self, problem: Problem, obj_index: Optional[int] = None, *, permute_all: bool = False):
        super().__init__(problem)
        self._permute_all = bool(permute_all)
        self._obj_index = problem.normalize_obj_index(obj_index) if not permute_all else None

    def _do(self, batch: SolutionBatch) -> SolutionBatch:
        if self._permute_all:
            permuted = F.cosyne_permutation(
                self._problem.next_rng_key(), batch.values, permute_all=True
            )
        else:
            i = self._obj_index
            permuted = F.cosyne_permutation(
                self._problem.next_rng_key(),
                batch.values,
                batch.evals[:, i],
                permute_all=False,
                objective_sense=self._problem.senses[i],
            )
        return SolutionBatch(self._problem, permuted.shape[0], values=permuted)
