"""Operators for variable-length (object-dtype) solutions.

Parity: reference ``operators/sequence.py`` — ``CutAndSplice``
(``sequence.py:25-74``): one-point crossover for sequences of differing
lengths, host-side (object-dtype populations never touch the device).
"""

from __future__ import annotations

import numpy as np

from ..core import SolutionBatch
from ..tools.objectarray import ObjectArray
from .base import CrossOver

__all__ = ["CutAndSplice"]


class CutAndSplice(CrossOver):
    """Cut-and-splice crossover on object-dtype (sequence) solutions
    (reference ``sequence.py:25-74``)."""

    def _do_cross_over(self, parents1, parents2) -> SolutionBatch:
        n = len(parents1)
        children = ObjectArray(2 * n)
        rng = np.random.default_rng(
            np.asarray(
                __import__("jax").random.key_data(self._problem.next_rng_key())
            ).ravel()
        )
        for i in range(n):
            a = list(parents1[i])
            b = list(parents2[i])
            cut_a = int(rng.integers(0, len(a) + 1))
            cut_b = int(rng.integers(0, len(b) + 1))
            children[i] = a[:cut_a] + b[cut_b:]
            children[n + i] = b[:cut_b] + a[cut_a:]
        batch = SolutionBatch(self._problem, len(children), values=children)
        return batch
