"""OO operator bases.

Parity: reference ``operators/base.py`` — ``Operator``/``CopyingOperator``
(``base.py:27-154``) and ``CrossOver`` with vectorized tournament selection
(``base.py:157-412``). The OO operators are thin, PRNG-threading wrappers over
``operators.functional``; the math lives there.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import Problem, SolutionBatch
from ..tools.misc import clip_tensor
from . import functional as F

__all__ = ["Operator", "CopyingOperator", "SingleObjOperator", "CrossOver"]


class Operator:
    """Base class: a callable acting on a SolutionBatch
    (reference ``base.py:27``)."""

    def __init__(self, problem: Problem):
        self._problem = problem

    @property
    def problem(self) -> Problem:
        return self._problem

    @property
    def dtype(self):
        return self._problem.dtype

    def _respect_bounds(self, values: jnp.ndarray) -> jnp.ndarray:
        """Clip to the problem's strict bounds if any (reference ``base.py:109``)."""
        return clip_tensor(values, self._problem.lower_bounds, self._problem.upper_bounds)

    def __call__(self, batch: SolutionBatch):
        raise NotImplementedError


class CopyingOperator(Operator):
    """Operator producing a new batch instead of mutating in place
    (reference ``base.py:120``)."""

    def __call__(self, batch: SolutionBatch) -> SolutionBatch:
        return self._do(batch)

    def _do(self, batch: SolutionBatch) -> SolutionBatch:
        raise NotImplementedError


class SingleObjOperator(Operator):
    """Marker base for operators valid only on single-objective problems."""

    def __init__(self, problem: Problem):
        if problem.is_multi_objective:
            raise ValueError(f"{type(self).__name__} supports single-objective problems only")
        super().__init__(problem)


class CrossOver(CopyingOperator):
    """Base for crossover operators with built-in tournament selection
    (reference ``base.py:157-412``: utilities are centered ranks in the
    single-objective case, pareto utilities in MOO)."""

    def __init__(
        self,
        problem: Problem,
        *,
        tournament_size: int,
        obj_index: Optional[int] = None,
        num_children: Optional[int] = None,
        cross_over_rate: Optional[float] = None,
    ):
        super().__init__(problem)
        self._tournament_size = int(tournament_size)
        self._obj_index = None if obj_index is None else problem.normalize_obj_index(obj_index)
        if num_children is not None and cross_over_rate is not None:
            raise ValueError("Provide at most one of num_children / cross_over_rate")
        self._num_children = None if num_children is None else int(num_children)
        self._cross_over_rate = None if cross_over_rate is None else float(cross_over_rate)

    def _resolve_num_children(self, batch: SolutionBatch) -> int:
        if self._num_children is not None:
            n = self._num_children
        elif self._cross_over_rate is not None:
            n = int(len(batch) * self._cross_over_rate)
        else:
            n = len(batch)
        if n % 2 != 0:
            n += 1
        return n

    def _do_tournament(self, batch: SolutionBatch):
        """Pick two parent sets via tournament (reference ``base.py:263-365``)."""
        num_children = self._resolve_num_children(batch)
        problem = self._problem
        if problem.is_multi_objective and self._obj_index is None:
            objective_sense = problem.senses
            evals = batch.evals[:, : problem.num_objectives]
        else:
            i = 0 if self._obj_index is None else self._obj_index
            objective_sense = problem.senses[i]
            evals = batch.evals[:, i]
        return F.tournament(
            problem.next_rng_key(),
            batch.values,
            evals,
            num_tournaments=num_children,
            tournament_size=self._tournament_size,
            objective_sense=objective_sense,
            split_results=True,
        )

    def _do_cross_over(self, parents1, parents2) -> SolutionBatch:
        raise NotImplementedError

    def _do(self, batch: SolutionBatch) -> SolutionBatch:
        parents1, parents2 = self._do_tournament(batch)
        return self._do_cross_over(parents1, parents2)

    def _make_children_batch(self, child_values) -> SolutionBatch:
        child_values = self._respect_bounds(child_values)
        return SolutionBatch(self._problem, child_values.shape[0], values=child_values)
