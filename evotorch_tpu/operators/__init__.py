"""Variation operators (L5')."""

from . import functional

__all__ = ["functional"]
