"""Declarative SLO watchdog over the decoded per-group telemetry.

Rules are plain declarative records (``Rule`` dataclasses or equivalent
dicts) evaluated once per generation against a decoded
:class:`~evotorch_tpu.observability.devicemetrics.GroupTelemetry` matrix —
the lag-by-one wire that the engines already emit, so checking SLOs costs
zero extra device syncs.  Four rule kinds cover the fairness/starvation
contract the multi-tenant eval service and island PBT need:

``occupancy_floor``
    every group's (or one group's) lane occupancy must be >= ``threshold``.
    Groups that were allotted zero capacity are skipped (vacuously true).
``starvation_ceiling``
    the share of refills landing in the TOP queue-wait bucket (waits >=
    the last histogram edge) must be <= ``threshold`` — the on-device
    starvation figure, per group or global.
``no_steady_compiles``
    the ``steady_compiles`` status key (retrace sentinel) must be 0.
    Skipped when the key is absent from ``status``.
``min_progress``
    every group's (or one group's) env-step count must be >= ``threshold``
    — a starved tenant shows up here even when its occupancy is undefined.
``min_model_efficiency``
    the ``model_efficiency`` status key (the program ledger's achieved
    fraction of nominal peak FLOPs — a BENCH_LEDGER=1 bench-line column)
    must be >= ``threshold``. Skipped when the key is absent; per-contract
    columns are checked by the bench CLI (``--min-model-efficiency``).
``max_nonfinite_share``
    the share of quarantined (non-finite-scored) solutions must be <=
    ``threshold``. Reads the exact ``eval_nonfinite_share`` status key when
    present (quarantined count / popsize); otherwise falls back to the
    telemetry matrix's per-group episode-denominated share — which also
    serves pinned-group rules (``group=g``). A diverging tenant shows up
    here before its quarantined scores distort anyone's ranking; see
    docs/resilience.md.

Three *search-health* kinds read the float32 health plane (schema v4
score statistics) and the algorithm status keys through the stateful
:class:`~evotorch_tpu.observability.health.HealthMonitor` the watchdog
owns (``SLOWatchdog.health``; ``state_dict()``/``load_state_dict()``
checkpoint the window state):

``plateau``
    the per-generation score mean (per group, or the global mean when
    ``group=None``) must keep a statistically significant trend — an EWMA
    slope gated on the stream's own noise floor
    (:class:`~evotorch_tpu.observability.health.EWMATrend`). Violation
    once the no-significant-trend streak reaches ``threshold``
    generations. Falls back to the ``mean_eval``/``score_mean`` status
    keys for global rules when the wire has no health plane.
``stdev_collapse``
    the ``stdev_norm`` status key must stay >= ``threshold`` x its
    first-seen baseline (default threshold 0.01): a distribution whose
    spread imploded by 100x relative to where the run started has stopped
    exploring. Skipped until the key appears.
``score_snr_floor``
    the population score signal-to-noise ratio ``|mean| / std`` must be
    >= ``threshold`` — per group or global, from the health plane.
    Skipped when fewer than 2 scores were seen; a zero std (all scores
    identical) gives infinite SNR and passes.

The watchdog surfaces as searcher status keys (``slo_ok`` /
``slo_violations`` / ``slo_detail``) via ``VecNEProblem(slo=...)``, and as
a battery verdict via the CLI::

    python -m evotorch_tpu.observability.slo --check-bench bench.log \
        --verdict-out slo_verdict.txt

which reads the LAST JSON line of a bench log (the bench.py output
contract), applies the battery default rules (steady_compiles == 0 plus a
global occupancy floor), writes a one-word ``pass``/``fail`` verdict file
for tpu_watch.sh, prints a JSON verdict line, and exits 0/1 — or 2
("insufficient") when the log has no decodable JSON line or the line
carries none of the checked keys (a BENCH_TELEMETRY=0 line): missing data
is distinguishable from failing data. A partial trailing line (crashed
writer) is skipped, never a traceback.

See docs/observability.md "Per-group telemetry & SLOs".
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Optional, Tuple, Union

from .devicemetrics import GroupTelemetry
from .health import HealthMonitor

__all__ = [
    "Rule",
    "RULE_KINDS",
    "SLOReport",
    "SLOWatchdog",
    "DEFAULT_BENCH_RULES",
]


RULE_KINDS = (
    "occupancy_floor",
    "starvation_ceiling",
    "no_steady_compiles",
    "min_progress",
    "min_model_efficiency",
    "max_nonfinite_share",
    "plateau",
    "stdev_collapse",
    "score_snr_floor",
)


@dataclass(frozen=True)
class Rule:
    """One declarative SLO rule.

    ``group=None`` means "every group" for the per-group kinds (and the
    global figure for ``starvation_ceiling``); an int pins the rule to a
    single group row.
    """

    kind: str
    threshold: float = 0.0
    group: Optional[int] = None

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"unknown SLO rule kind {self.kind!r}; expected one of {RULE_KINDS}"
            )


@dataclass(frozen=True)
class SLOReport:
    """Outcome of one watchdog evaluation (one generation)."""

    ok: bool
    violations: Tuple[str, ...] = field(default_factory=tuple)
    checked: int = 0

    def as_status(self) -> Dict[str, Any]:
        status: Dict[str, Any] = {
            "slo_ok": bool(self.ok),
            "slo_violations": len(self.violations),
        }
        if self.violations:
            status["slo_detail"] = "; ".join(self.violations)
        return status

    def summary(self) -> str:
        if self.ok:
            return f"SLO ok ({self.checked} rules)"
        return f"SLO FAIL ({len(self.violations)}/{self.checked}): " + "; ".join(
            self.violations
        )


def _coerce_rule(rule: Union[Rule, Dict[str, Any]]) -> Rule:
    if isinstance(rule, Rule):
        return rule
    if isinstance(rule, dict):
        return Rule(**rule)
    raise TypeError(f"SLO rule must be a Rule or a dict, got {type(rule).__name__}")


class SLOWatchdog:
    """Evaluates a fixed rule set against per-group telemetry each call.

    The search-health rule kinds (``plateau``, ``stdev_collapse``,
    ``score_snr_floor``) are *stateful*: the watchdog owns a
    :class:`~evotorch_tpu.observability.health.HealthMonitor` whose trend
    windows advance one step per :meth:`check` call.
    ``state_dict()``/``load_state_dict()`` round-trip that window state so
    checkpointed runs resume with identical verdict timing.
    """

    def __init__(
        self,
        rules: Optional[Iterable[Union[Rule, dict]]] = None,
        *,
        health: Optional[HealthMonitor] = None,
    ):
        if rules is None or rules is True:
            rules = DEFAULT_RULES
        self.rules: Tuple[Rule, ...] = tuple(_coerce_rule(r) for r in rules)
        self.health = health if health is not None else HealthMonitor()

    def __repr__(self):
        return f"SLOWatchdog(rules={list(self.rules)!r})"

    # --------------------------------------------------------- serialization
    def state_dict(self) -> Dict[str, Any]:
        return {"health": self.health.state_dict()}

    def load_state_dict(self, state: Dict[str, Any]) -> "SLOWatchdog":
        health_state = state.get("health")
        if health_state:
            self.health.load_state_dict(health_state)
        return self

    # ------------------------------------------------------------ evaluation
    def check(
        self,
        telemetry: Optional[GroupTelemetry],
        *,
        status: Optional[Dict[str, Any]] = None,
    ) -> SLOReport:
        """Evaluate every rule; telemetry=None checks only status-keyed rules."""
        violations = []
        checked = 0
        for rule in self.rules:
            outcome = self._check_rule(rule, telemetry, status or {})
            if outcome is None:  # rule not applicable (no data) — skipped
                continue
            checked += 1
            if outcome:
                violations.append(outcome if isinstance(outcome, str) else str(outcome))
        return SLOReport(
            ok=not violations, violations=tuple(violations), checked=checked
        )

    def _check_rule(self, rule, telemetry, status):
        """Returns None (skipped), False (passed) or a violation string."""
        if rule.kind == "no_steady_compiles":
            compiles = status.get("steady_compiles")
            if compiles is None:
                return None
            if int(compiles) > 0:
                return f"steady_compiles={int(compiles)} (expected 0)"
            return False
        if rule.kind == "min_model_efficiency":
            efficiency = status.get("model_efficiency")
            if efficiency is None:  # no ledger columns on this run — skip
                return None
            if float(efficiency) < rule.threshold:
                return (
                    f"model_efficiency={float(efficiency):.4g} < "
                    f"{rule.threshold:g}"
                )
            return False
        if rule.kind == "max_nonfinite_share":
            share = None
            if rule.group is None:
                share = status.get("eval_nonfinite_share")
            if share is None:
                if telemetry is None:
                    return None
                share = telemetry.nonfinite_share(group=rule.group)
            if float(share) > rule.threshold:
                label = "global" if rule.group is None else f"g{rule.group}"
                return (
                    f"nonfinite_share {label}={float(share):.3f} > "
                    f"{rule.threshold:g}"
                )
            return False
        if rule.kind == "stdev_collapse":
            value = status.get("stdev_norm")
            if value is None:
                return None
            value = float(value)
            self.health.observe("stdev_norm", value, group=rule.group)
            baseline = self.health.baseline("stdev_norm", group=rule.group)
            if baseline is None or baseline <= 0.0:
                return None
            if value < rule.threshold * baseline:
                return (
                    f"stdev_norm={value:.4g} < {rule.threshold:g} x "
                    f"baseline {baseline:.4g} (collapse)"
                )
            return False
        if rule.kind == "plateau":
            # group=None reads the GLOBAL score mean (like
            # starvation_ceiling's global figure), not every group
            value = None
            if telemetry is not None and telemetry.has_health:
                stats = telemetry.score_stats(group=rule.group)
                if stats["count"] > 0:
                    value = stats["mean"]
            if value is None and rule.group is None:
                value = status.get("score_mean", status.get("mean_eval"))
            if value is None:
                return None
            trend = self.health.observe(
                "score_mean", float(value), group=rule.group
            )
            if trend.stall_streak >= max(rule.threshold, 1.0):
                label = "global" if rule.group is None else f"g{rule.group}"
                return (
                    f"plateau {label}: no significant score trend for "
                    f"{trend.stall_streak} generations "
                    f"(|trend| {abs(trend.delta_ewma):.3g} <= "
                    f"noise floor {trend.noise_floor:.3g})"
                )
            return False
        if rule.kind == "score_snr_floor":
            if telemetry is None or not telemetry.has_health:
                return None
            stats = telemetry.score_stats(group=rule.group)
            if stats["count"] < 2:
                return None
            snr = (
                float("inf")
                if stats["std"] <= 0.0
                else abs(stats["mean"]) / stats["std"]
            )
            if snr < rule.threshold:
                label = "global" if rule.group is None else f"g{rule.group}"
                return f"score_snr {label}={snr:.3g} < {rule.threshold:g}"
            return False
        if telemetry is None:
            return None
        groups = (
            range(telemetry.num_groups) if rule.group is None else (rule.group,)
        )
        if rule.kind == "occupancy_floor":
            failed = []
            for g in groups:
                t = telemetry.group(g)
                if t.capacity <= 0:  # no lanes allotted: vacuously true
                    continue
                if t.occupancy < rule.threshold:
                    failed.append(f"g{g}={t.occupancy:.3f}")
            if failed:
                return f"occupancy < {rule.threshold:g}: " + ", ".join(failed)
            return False
        if rule.kind == "starvation_ceiling":
            targets = (None,) if rule.group is None else (rule.group,)
            failed = []
            for g in targets:
                share = telemetry.starvation_share(group=g)
                if share > rule.threshold:
                    label = "global" if g is None else f"g{g}"
                    failed.append(f"{label}={share:.3f}")
            if failed:
                return f"starvation > {rule.threshold:g}: " + ", ".join(failed)
            return False
        if rule.kind == "min_progress":
            failed = []
            for g in groups:
                steps = int(telemetry.group(g).env_steps)
                if steps < rule.threshold:
                    failed.append(f"g{g}={steps}")
            if failed:
                return f"env_steps < {rule.threshold:g}: " + ", ".join(failed)
            return False
        raise AssertionError(rule.kind)  # unreachable: ctor validates


#: defaults when ``VecNEProblem(slo=True)`` asks for a watchdog without
#: spelling rules out: no silent retraces, nobody fully starved
DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule("no_steady_compiles"),
    Rule("starvation_ceiling", threshold=0.5),
    Rule("min_progress", threshold=1),
)

#: battery-verdict defaults for ``--check-bench``: the flagship bench line
#: must be retrace-free and show a sane primary-mode occupancy
DEFAULT_BENCH_RULES: Tuple[Rule, ...] = (
    Rule("no_steady_compiles"),
    Rule("occupancy_floor", threshold=0.1),
)


# ---------------------------------------------------------------- bench CLI
def _score_snr(mean: float, std: float) -> float:
    """|mean| / std; infinite when the spread is exactly zero."""
    return float("inf") if float(std) <= 0.0 else abs(float(mean)) / float(std)


def check_bench_line(
    line: Dict[str, Any],
    *,
    occupancy_floor: float = 0.1,
    min_model_efficiency: Optional[float] = None,
    max_nonfinite_share: Optional[float] = None,
    max_score_collapse: Optional[float] = None,
    min_score_snr: Optional[float] = None,
    max_queue_wait_p99: Optional[float] = None,
) -> SLOReport:
    """Apply the battery rules to one decoded bench.py JSON line.

    The bench line carries scalars, not a (G, K) matrix, so this reads the
    top-level ``occupancy`` / ``steady_compiles`` keys (plus per-mode
    occupancies under ``modes``) directly. With ``min_model_efficiency``
    set, the program-ledger efficiency columns (``model_efficiency``,
    top-level and per contract under ``modes`` — present when the line was
    produced with BENCH_LEDGER=1) must each clear the floor; a line with
    no ledger columns skips those checks (missing analysis degrades, it
    doesn't fail).

    The health-plane flags read the ``score_mean`` / ``score_std`` columns
    (present when the line was produced with BENCH_HEALTH=1, the default):
    ``max_score_collapse`` fails when the score SNR ``|mean| / std``
    EXCEEDS the ceiling (the population's spread collapsed below 1/T of
    its mean scale — stdev-collapse seen from the score side);
    ``min_score_snr`` fails when the SNR is below the floor (the scores
    are noise-dominated). Lines without the columns skip both.

    ``max_queue_wait_p99`` gates the tail of the refill queue-wait
    distribution (in loop steps, from the on-device histograms): the
    top-level ``queue_wait_p99``, every per-mode one under ``modes``, and
    the serving A/B's ``serve_queue_wait_p99`` (a BENCH_SERVE=1 line) must
    each stay at or below the ceiling — the multi-tenant fairness gate.
    Lines without the columns skip the check.
    """
    violations = []
    checked = 0

    def _check_queue_wait(value, label):
        nonlocal checked
        if max_queue_wait_p99 is None or value is None:
            return
        checked += 1
        if float(value) > max_queue_wait_p99:
            violations.append(
                f"{label}queue_wait_p99={float(value):g} > {max_queue_wait_p99:g}"
            )

    _check_queue_wait(line.get("queue_wait_p99"), "")
    _check_queue_wait(line.get("serve_queue_wait_p99"), "serve_")
    compiles = line.get("steady_compiles")
    if compiles is not None:
        checked += 1
        if int(compiles) > 0:
            violations.append(f"steady_compiles={int(compiles)} (expected 0)")
    occ = line.get("occupancy")
    if occ is not None:
        checked += 1
        if float(occ) < occupancy_floor:
            violations.append(f"occupancy={float(occ):.3f} < {occupancy_floor:g}")
    nfs = line.get("eval_nonfinite_share")
    if max_nonfinite_share is not None and nfs is not None:
        checked += 1
        if float(nfs) > max_nonfinite_share:
            violations.append(
                f"eval_nonfinite_share={float(nfs):.3f} > {max_nonfinite_share:g}"
            )
    eff = line.get("model_efficiency")
    if min_model_efficiency is not None and eff is not None:
        checked += 1
        if float(eff) < min_model_efficiency:
            violations.append(
                f"model_efficiency={float(eff):.4g} < {min_model_efficiency:g}"
            )

    def _check_health(mean, std, label):
        nonlocal checked
        if mean is None or std is None:
            return
        snr = _score_snr(mean, std)
        if max_score_collapse is not None:
            checked += 1
            if snr > max_score_collapse:
                violations.append(
                    f"{label}score_snr={snr:.3g} > {max_score_collapse:g} "
                    "(score spread collapsed)"
                )
        if min_score_snr is not None:
            checked += 1
            if snr < min_score_snr:
                violations.append(
                    f"{label}score_snr={snr:.3g} < {min_score_snr:g}"
                )

    _check_health(line.get("score_mean"), line.get("score_std"), "")
    modes = line.get("modes") or {}
    for mode, rec in sorted(modes.items()):
        if not isinstance(rec, dict):
            continue
        mocc = rec.get("occupancy")
        if mocc is not None:
            checked += 1
            if float(mocc) < occupancy_floor:
                violations.append(
                    f"modes.{mode}.occupancy={float(mocc):.3f} < {occupancy_floor:g}"
                )
        meff = rec.get("model_efficiency")
        if min_model_efficiency is not None and meff is not None:
            checked += 1
            if float(meff) < min_model_efficiency:
                violations.append(
                    f"modes.{mode}.model_efficiency={float(meff):.4g} < "
                    f"{min_model_efficiency:g}"
                )
        _check_health(
            rec.get("score_mean"), rec.get("score_std"), f"modes.{mode}."
        )
        _check_queue_wait(rec.get("queue_wait_p99"), f"modes.{mode}.")
    return SLOReport(ok=not violations, violations=tuple(violations), checked=checked)


def _last_json_line(path: str) -> Optional[Dict[str, Any]]:
    """The last decodable JSON line of the log, or None when there is none.

    A crashed writer leaves a partial trailing line; that (and any other
    non-JSON noise) is skipped, not raised — the last COMPLETE line wins.
    """
    last = None
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw or not raw.startswith("{"):
                continue
            try:
                last = json.loads(raw)
            except json.JSONDecodeError:  # partial/corrupt row — skip it
                continue
    return last


def _main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="SLO watchdog: verdict over a bench.py JSON log"
    )
    parser.add_argument(
        "--check-bench",
        metavar="LOG",
        required=True,
        help="bench log; the LAST JSON line is checked",
    )
    parser.add_argument(
        "--occupancy-floor",
        type=float,
        default=0.1,
        help="minimum acceptable occupancy, global and per mode (default 0.1)",
    )
    parser.add_argument(
        "--min-model-efficiency",
        type=float,
        default=None,
        help="minimum acceptable program-ledger model_efficiency, global "
        "and per contract (default: unchecked; needs a BENCH_LEDGER=1 line)",
    )
    parser.add_argument(
        "--max-nonfinite-share",
        type=float,
        default=None,
        help="maximum acceptable eval_nonfinite_share (quarantined share of "
        "the population; default: unchecked)",
    )
    parser.add_argument(
        "--max-score-collapse",
        type=float,
        default=None,
        help="maximum acceptable score SNR |score_mean|/score_std, global "
        "and per contract — above it the population spread has collapsed "
        "(default: unchecked; needs a BENCH_HEALTH=1 line)",
    )
    parser.add_argument(
        "--min-score-snr",
        type=float,
        default=None,
        help="minimum acceptable score SNR |score_mean|/score_std — below "
        "it the scores are noise-dominated (default: unchecked)",
    )
    parser.add_argument(
        "--max-queue-wait-p99",
        type=float,
        default=None,
        help="maximum acceptable refill queue-wait p99 (loop steps), "
        "top-level, per contract and for the serving A/B "
        "(default: unchecked; needs histogrammed refill events)",
    )
    parser.add_argument(
        "--verdict-out",
        metavar="PATH",
        default=None,
        help="write a one-word pass/fail verdict file (read by tpu_watch.sh)",
    )
    args = parser.parse_args(argv)

    line = _last_json_line(args.check_bench)
    if line is None:
        report = SLOReport(ok=False, violations=(), checked=0)
    else:
        report = check_bench_line(
            line,
            occupancy_floor=args.occupancy_floor,
            min_model_efficiency=args.min_model_efficiency,
            max_nonfinite_share=args.max_nonfinite_share,
            max_score_collapse=args.max_score_collapse,
            min_score_snr=args.min_score_snr,
            max_queue_wait_p99=args.max_queue_wait_p99,
        )
    if report.checked == 0:
        # no decodable line, or a line with none of the checked keys (e.g.
        # BENCH_TELEMETRY=0): missing data is not a pass and not a fail
        verdict, code = "insufficient", 2
    elif report.ok:
        verdict, code = "pass", 0
    else:
        verdict, code = "fail", 1
    if args.verdict_out:
        with open(args.verdict_out, "w", encoding="utf-8") as fh:
            fh.write(verdict + "\n")
    print(
        json.dumps(
            {
                "slo_verdict": verdict,
                "slo_checked": report.checked,
                "slo_violations": list(report.violations),
                "source": args.check_bench,
            },
            sort_keys=True,
        )
    )
    return code


if __name__ == "__main__":
    raise SystemExit(_main())
