"""Host-side span tracer emitting Chrome trace-event JSON (Perfetto).

The device side of the eval stack is observable through the packed
telemetry vector (:mod:`~evotorch_tpu.observability.devicemetrics`) and
``jax.profiler``; this module covers the HOST side — the part Podracer
(arXiv:2104.06272) says you must see to tune an overlapped pipeline: the
search loop's ask/eval/tell phases, the host pipeline's S1/S2/S3 stages,
the physics worker thread, hostpool actor sync. Every span is one
`Chrome trace-event <https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
complete ("ph": "X") event; threads appear as separate tracks, so
pipeline overlap is *visible* as parallel spans.

Design constraints:

- **~0 overhead when disabled** (the default): :func:`span` returns a
  shared no-op context manager after a single ``None`` check — no dict, no
  timestamps, no allocation.
- **Ring-buffered**: events live in a bounded ``deque``; a long run keeps
  the most recent window instead of growing without bound.
- **Thread-safe**: events append from any thread (``deque.append`` is
  atomic under the GIL); per-thread track names are emitted as metadata
  events on first use.

Enable with ``EVOTORCH_TRACE=/path/to/trace.json`` in the environment
(written at process exit) or programmatically::

    from evotorch_tpu.observability import tracer
    tracer.start_tracing("pipeline.json")
    ...
    tracer.stop_tracing()          # writes the file

Open the file at https://ui.perfetto.dev ("Open trace file") or
``chrome://tracing``.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import counters

__all__ = [
    "SpanTracer",
    "span",
    "instant",
    "start_tracing",
    "stop_tracing",
    "get_tracer",
    "tracing_enabled",
]

#: default ring-buffer capacity (events); ~150 bytes/event => tens of MB max
DEFAULT_CAPACITY = 400_000


class _Span:
    """One in-flight span; appended to the ring as a complete event on exit."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t = self._tracer
        t._complete(self._name, self._t0, t._now_us() - self._t0, self._cat, self._args)
        return False


class _NoopSpan:
    """The shared disabled-path context manager: no state, no work."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()


class SpanTracer:
    """Ring-buffered Chrome trace-event recorder (see the module docstring)."""

    def __init__(
        self,
        *,
        capacity: int = DEFAULT_CAPACITY,
        flush_path: Optional[str] = None,
        flush_secs: Optional[float] = None,
    ):
        self._events: deque = deque(maxlen=int(capacity))
        self._meta: List[dict] = []  # thread-name metadata; never evicted
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        # one lock for appends AND snapshots: a worker thread finishing a
        # span while stop_tracing()/the atexit writer iterates the deque
        # would otherwise raise "deque mutated during iteration" and lose
        # the trace (the acquire is ~100ns against spans that are µs+)
        self._lock = threading.Lock()
        self._named: set = set()
        # periodic ring-buffer flush (off unless both are set): a SIGKILLed
        # long run keeps the last flushed window instead of losing the whole
        # trace at the missed atexit hook
        self._flush_path = flush_path
        self._flush_secs = float(flush_secs) if flush_secs else None
        self._last_flush = time.monotonic()
        self._flush_gate = threading.Lock()

    # ------------------------------------------------------------- recording
    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def now_us(self) -> float:
        """The trace clock (µs since tracer start) — pair with
        :meth:`complete` for manually-timed spans that cannot be expressed
        as one ``with`` block (e.g. an async dispatch whose wait happens in
        a later call)."""
        return self._now_us()

    def complete(self, name: str, ts: float, dur: float, cat: str = "", **args):
        """Append a complete event with caller-supplied timestamps."""
        self._complete(name, ts, dur, cat, args)

    def _tid(self) -> int:
        t = threading.current_thread()
        tid = t.ident or 0
        if tid not in self._named:
            with self._lock:
                if tid not in self._named:
                    self._named.add(tid)
                    self._meta.append(
                        {
                            "name": "thread_name",
                            "ph": "M",
                            "pid": self._pid,
                            "tid": tid,
                            "args": {"name": t.name},
                        }
                    )
        return tid

    def _complete(self, name: str, ts: float, dur: float, cat: str, args: dict):
        event = {
            "name": name,
            "ph": "X",
            "ts": ts,
            "dur": dur,
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            event["cat"] = cat
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)
        counters.increment("trace_spans")
        self._maybe_flush()

    def span(self, name: str, cat: str = "", **args) -> _Span:
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        """A point event ("ph": "i", thread-scoped)."""
        event = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": self._tid(),
        }
        if cat:
            event["cat"] = cat
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    def counter(self, name: str, value, cat: str = "") -> None:
        """A counter-track sample ("ph": "C")."""
        event = {
            "name": name,
            "ph": "C",
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": self._tid(),
            "args": {name: value},
        }
        if cat:
            event["cat"] = cat
        with self._lock:
            self._events.append(event)

    def _maybe_flush(self) -> None:
        """Write the ring buffer to ``flush_path`` when the flush interval
        has elapsed. Serialization happens OUTSIDE the event lock (events
        keep appending while the snapshot serializes); a second thread
        arriving mid-flush skips (non-blocking gate). Never raises — a
        flush failure must not take down the run being traced."""
        if self._flush_secs is None or self._flush_path is None:
            return
        now = time.monotonic()
        if now - self._last_flush < self._flush_secs:
            return
        if not self._flush_gate.acquire(blocking=False):
            return
        try:
            self._last_flush = now
            tmp = self._flush_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.to_chrome_trace(), f)
            os.replace(tmp, self._flush_path)  # readers never see a torn file
        except Exception:  # graftlint: allow(swallow): tracing must never take down the run it traces
            pass
        finally:
            self._flush_gate.release()

    # --------------------------------------------------------------- readout
    def events(self) -> List[dict]:
        with self._lock:
            return self._meta + list(self._events)

    def to_chrome_trace(self) -> Dict[str, Any]:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


# ---------------------------------------------------------------------------
# the module-level tracer (the one `span()` feeds)
# ---------------------------------------------------------------------------

_TRACER: Optional[SpanTracer] = None
_TRACE_PATH: Optional[str] = None
_STATE_LOCK = threading.Lock()


def get_tracer() -> Optional[SpanTracer]:
    return _TRACER


def tracing_enabled() -> bool:
    return _TRACER is not None


def start_tracing(
    path: Optional[str] = None,
    *,
    capacity: int = DEFAULT_CAPACITY,
    flush_secs: Optional[float] = None,
) -> SpanTracer:
    """Install a fresh process tracer. ``path`` (optional) is where
    :func:`stop_tracing` — or process exit — writes the trace.
    ``flush_secs`` (or ``EVOTORCH_TRACE_FLUSH_SECS`` in the environment;
    default off) additionally rewrites ``path`` every that-many seconds,
    so a killed run keeps a partial trace."""
    global _TRACER, _TRACE_PATH
    with _STATE_LOCK:
        _TRACER = SpanTracer(
            capacity=capacity,
            flush_path=path if flush_secs else None,
            flush_secs=flush_secs,
        )
        _TRACE_PATH = path
        return _TRACER


def stop_tracing(*, write: bool = True) -> Optional[str]:
    """Uninstall the tracer; write the trace to its configured path (if any).
    Returns the written path, or None."""
    global _TRACER, _TRACE_PATH
    with _STATE_LOCK:
        tracer, path = _TRACER, _TRACE_PATH
        _TRACER, _TRACE_PATH = None, None
    if tracer is not None and path is not None and write:
        return tracer.write(path)
    return None


def span(name: str, cat: str = "", **args):
    """A context manager recording one complete event on the installed
    tracer — or the shared no-op when tracing is off (the fast path: one
    global read + one ``None`` check)."""
    t = _TRACER
    if t is None:
        return _NOOP
    return t.span(name, cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    t = _TRACER
    if t is not None:
        t.instant(name, cat, **args)


def _write_at_exit() -> None:
    # EVOTORCH_TRACE runs write the ring buffer out even on an unclean stop;
    # nothing here may raise (an atexit traceback would mask the real error
    # of the run being traced)
    if _TRACER is not None and _TRACE_PATH is not None:
        try:
            _TRACER.write(_TRACE_PATH)
        except Exception:  # graftlint: allow(swallow): tracing must never take down the run it traces
            pass


def _env_flush_secs() -> Optional[float]:
    raw = os.environ.get("EVOTORCH_TRACE_FLUSH_SECS")
    if not raw:
        return None
    try:
        secs = float(raw)
    except ValueError:
        return None
    return secs if secs > 0 else None


_env_path = os.environ.get("EVOTORCH_TRACE")
if _env_path:
    start_tracing(_env_path, flush_secs=_env_flush_secs())
atexit.register(_write_at_exit)
