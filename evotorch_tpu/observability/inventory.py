"""The registered program inventory of the stack's jitted entry points.

One place declares *which* compiled programs constitute the framework —
the four eval-contract rollout programs (plus their trunk-delta policy
forms, ``docs/policies.md``), the sharded evaluator, the gaussian
functional ask/tell, the batched functional search, and the
bench/multichip/GSPMD whole-generation steps (dense and trunk-delta) —
so the program ledger
(:mod:`~evotorch_tpu.observability.programs`), the report CLI and the
fast-tier perf-regression gate all see the same surface.

Everything here builds programs at a configurable *gate shape*
(:class:`GateConfig`, tiny by default so a full capture costs seconds of
compile on the CPU mesh, not minutes). FLOPs and per-lane memory scale
~linearly in ``popsize``/``episode_length`` for fixed program structure,
so a structural regression at the gate shape is a flagship regression too
— the gate catches it in tier-1 instead of months later in a rare healthy
TPU window (the flagship-shape snapshot on the real chip is a
``scripts/tpu_window.sh`` battery step).

Heavy imports stay inside the builders: ``observability`` is imported by
``algorithms`` at class-definition time, so importing envs/algorithms at
module scope here would cycle.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .programs import (
    ProgramLedger,
    ProgramRecord,
    abstract_like as _abstract,
    ledger,
    program_key,
)

__all__ = [
    "GateConfig",
    "ProgramSpec",
    "build_specs",
    "capture_compact_chunk",
    "capture_inventory",
    "donated_programs",
    "inventory_keys",
]


@dataclass(frozen=True)
class GateConfig:
    """Shape configuration for an inventory capture. The defaults are the
    fast-tier gate shapes (checked into ``ledger_baseline.json``); the
    report CLI's ``--flagship`` swaps in benchmark-scale values."""

    env_name: str = "cartpole"
    popsize: int = 8
    episode_length: int = 16
    hidden: Tuple[int, ...] = (8,)
    refill_width: int = 4
    chunk_size: int = 8
    trunk_rank: int = 4
    batched_searches: int = 4
    batched_dim: int = 8
    batched_popsize: int = 8
    batched_generations: int = 3
    span: int = 3


@dataclass(frozen=True)
class ProgramSpec:
    """One registered program: a stable (name, shape) identity plus a
    thunk that captures it into a ledger."""

    name: str
    shape: Dict[str, Any] = field(compare=False, default_factory=dict)
    capture: Callable[[ProgramLedger], ProgramRecord] = field(
        compare=False, default=None
    )

    @property
    def key(self) -> str:
        return program_key(self.name, self.shape)


# ---------------------------------------------------------------------------
# jitted program builders (lru_cached: one wrapper per config, never per call)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _env_policy(env_name: str, hidden: Tuple[int, ...]):
    """Cached: env/policy identity keys the jitted-program lru_caches below
    (and vecrl's engine caches), so repeated build_specs/donated_programs
    calls reuse compiled programs instead of retracing per call."""
    from ..envs import make_env
    from ..neuroevolution.net import FlatParamsPolicy, tanh_mlp

    env = make_env(env_name)
    net = tanh_mlp(env.observation_size, env.action_size, hidden)
    return env, FlatParamsPolicy(net)


def _fresh_pgpe_state(parameter_count: int):
    import jax.numpy as jnp

    from ..algorithms.functional import pgpe

    return pgpe(
        center_init=jnp.zeros(parameter_count, dtype=jnp.float32),
        center_learning_rate=0.1,
        stdev_learning_rate=0.1,
        objective_sense="max",
        stdev_init=0.1,
    )


@functools.lru_cache(maxsize=1)
def _gaussian_programs():
    import jax

    from ..algorithms.functional import pgpe_ask, pgpe_tell

    ask = jax.jit(pgpe_ask, static_argnames=("popsize",))
    tell = jax.jit(pgpe_tell, donate_argnums=(0,))
    return ask, tell


@functools.lru_cache(maxsize=8)
def _batched_search_program(num_searches: int, dim: int, popsize: int):
    """The examples/functional_batched_search.py program shape: N
    independent CEM searches scanned as ONE jitted, state-donating
    program (batch dims on the state) — built on the shared
    scanned-generations idiom (``algorithms.functional.make_search_span``),
    the same helper the example itself uses."""
    import functools as ft

    import jax.numpy as jnp

    from ..algorithms.functional import cem_ask, cem_tell, make_search_span

    return make_search_span(
        lambda pop: jnp.sum(pop**2, axis=-1),
        ask=ft.partial(cem_ask, popsize=popsize),
        tell=cem_tell,
        metrics=lambda pop, fit: jnp.min(fit, axis=-1),
    )


@functools.lru_cache(maxsize=8)
def _trunk_delta_batch(policy, popsize: int, rank: int):
    """One concrete trunk-delta population at the gate shape (cached: the
    rollout captures only need its ShapeDtypeStruct skeleton, but the
    skeleton must carry the REAL pytree structure — factors treedef
    included — for the capture to lower the dispatched program)."""
    import jax

    from ..algorithms.functional import pgpe_ask_trunk_delta

    state = _fresh_pgpe_state(policy.parameter_count)
    return pgpe_ask_trunk_delta(
        jax.random.key(0), state, popsize=popsize, rank=rank, policy=policy
    )


@functools.lru_cache(maxsize=8)
def _trunk_generation_program(
    env, policy, popsize: int, episode_length: int, rank: int
):
    """The trunk-delta analog of the bench generation: factored ask ->
    budget rollout (shared-trunk + per-lane delta forward) -> factored
    tell, one jitted program donating the optimizer state."""
    import jax

    from ..algorithms.functional import (
        pgpe_ask_trunk_delta,
        pgpe_tell_trunk_delta,
    )
    from ..neuroevolution.net.vecrl import run_vectorized_rollout

    def _generation(state, key, stats):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask_trunk_delta(
            k1, state, popsize=popsize, rank=rank, policy=policy
        )
        result = run_vectorized_rollout(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=episode_length,
            eval_mode="budget",
        )
        new_state = pgpe_tell_trunk_delta(state, values, result.scores)
        return new_state, result.total_steps, result.scores

    return jax.jit(_generation, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _bench_generation_program(env, policy, popsize: int, episode_length: int):
    """bench.py's monolithic generation: PGPE ask -> budget rollout ->
    tell, one jitted program donating the optimizer state."""
    import jax

    from ..algorithms.functional import pgpe_ask, pgpe_tell
    from ..neuroevolution.net.vecrl import run_vectorized_rollout

    def _generation(state, key, stats):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask(k1, state, popsize=popsize)
        result = run_vectorized_rollout(
            env,
            policy,
            values,
            k2,
            stats,
            num_episodes=1,
            episode_length=episode_length,
            eval_mode="budget",
        )
        new_state = pgpe_tell(state, values, result.scores)
        return new_state, result.total_steps, result.scores

    return jax.jit(_generation, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _multichip_generation_program(
    env, policy, mesh_size: int, popsize: int, episode_length: int
):
    """bench_multichip.py's generation: the same program shard_mapped over
    a ("pop",) mesh with psum stat/step merging, state donated."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..algorithms.functional import pgpe_ask, pgpe_tell
    from ..neuroevolution.net.vecrl import global_lane_ids, run_vectorized_rollout

    mesh = Mesh(np.asarray(jax.devices()[:mesh_size]), axis_names=("pop",))
    pop_sharding = NamedSharding(mesh, P("pop"))

    def _local_rollout(values_shard, key, stats):
        ids = global_lane_ids("pop", values_shard.shape[0])
        result = run_vectorized_rollout(
            env,
            policy,
            values_shard,
            key,
            stats,
            lane_ids=ids,
            num_episodes=1,
            episode_length=episode_length,
            eval_mode="budget",
        )
        delta = jax.tree_util.tree_map(
            lambda new, old: new - old, result.stats, stats
        )
        merged = jax.tree_util.tree_map(
            lambda old, d: old + jax.lax.psum(d, "pop"), stats, delta
        )
        return result.scores, merged, result.total_steps[None]

    sharded = jax.shard_map(
        _local_rollout,
        mesh=mesh,
        in_specs=(P("pop"), P(), P()),
        out_specs=(P("pop"), P(), P("pop")),
        check_vma=False,
    )

    def _generation(state, key, stats):
        k1, k2 = jax.random.split(key)
        values = pgpe_ask(k1, state, popsize=popsize)
        values = jax.lax.with_sharding_constraint(values, pop_sharding)
        scores, stats, per_shard = sharded(values, k2, stats)
        return pgpe_tell(state, values, scores), stats, per_shard

    return jax.jit(_generation, donate_argnums=(0,))


@functools.lru_cache(maxsize=8)
def _gspmd_generation_program(env, policy, mesh_size, popsize, episode_length):
    """parallel.make_generation_step at the gate shape: ask -> GSPMD-sharded
    rollout -> tell compiled as ONE global program over a ("pop",) mesh with
    the evolution state donated end-to-end (docs/sharding.md)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..algorithms.functional import pgpe_ask, pgpe_tell
    from ..parallel.evaluate import make_generation_step

    mesh = Mesh(np.asarray(jax.devices()[:mesh_size]), axis_names=("pop",))
    return make_generation_step(
        env,
        policy,
        ask=lambda k, s: pgpe_ask(k, s, popsize=popsize),
        tell=pgpe_tell,
        popsize=popsize,
        mesh=mesh,
        num_episodes=1,
        episode_length=episode_length,
        eval_mode="budget",
    )


@functools.lru_cache(maxsize=8)
def _gspmd_span_program(env, policy, mesh_size, popsize, episode_length, span):
    """parallel.make_training_span at the gate shape: ``span`` generations
    of the GSPMD ask -> rollout -> tell body scanned into ONE donated
    program (docs/sharding.md "Fused multi-generation training spans")."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..algorithms.functional import pgpe_ask, pgpe_tell
    from ..parallel.evaluate import make_training_span

    mesh = Mesh(np.asarray(jax.devices()[:mesh_size]), axis_names=("pop",))
    return make_training_span(
        env,
        policy,
        ask=lambda k, s: pgpe_ask(k, s, popsize=popsize),
        tell=pgpe_tell,
        popsize=popsize,
        span=span,
        mesh=mesh,
        num_episodes=1,
        episode_length=episode_length,
        eval_mode="budget",
    )


def capture_compact_chunk(
    led: ProgramLedger,
    env,
    policy,
    popsize: int,
    episode_length: int,
    *,
    chunk_size: int,
    compute_dtype=None,
    telemetry: bool = True,
    name: str = "rollout.episodes_compact.chunk",
    shape: Optional[Dict[str, Any]] = None,
) -> ProgramRecord:
    """Capture the lane-compacting runner's full-width chunk program — the
    dominant cost of the host-orchestrated ``episodes_compact`` contract
    (the width-descent runs the SAME program at narrower shapes). Shared
    by the inventory and bench.py so the two cannot drift."""
    import jax
    import jax.numpy as jnp

    from ..neuroevolution.net.runningnorm import RunningNorm
    from ..neuroevolution.net.vecrl import _compacting_fns

    max_t = env.max_episode_steps if env.max_episode_steps is not None else 1000
    max_t = min(max_t, int(episode_length))
    hard_cap = max_t + 1
    init_fn, chunk_fn, _, _ = _compacting_fns(
        env,
        policy,
        1,
        max_t,
        hard_cap,
        False,
        None,
        None,
        None,
        compute_dtype,
        collect_telemetry=bool(telemetry),
    )
    params = jnp.zeros((popsize, policy.parameter_count), dtype=jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    carry, fwd_params = init_fn(params, jax.random.key(0), stats)
    return led.capture(
        name,
        chunk_fn,
        _abstract(fwd_params),
        _abstract(carry),
        shape=shape,
        num_steps=int(chunk_size),
    )


# ---------------------------------------------------------------------------
# the inventory
# ---------------------------------------------------------------------------


def _mesh_size(popsize: int) -> int:
    """The largest usable ("pop",) mesh for this process: every device when
    the popsize divides evenly, else the largest divisor of popsize."""
    import jax

    n = len(jax.devices())
    while n > 1 and popsize % n != 0:
        n -= 1
    return n


def build_specs(cfg: Optional[GateConfig] = None) -> List[ProgramSpec]:
    """The registered program list at ``cfg``'s shapes. Building specs is
    cheap (host objects only); compiles happen in each spec's capture."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..neuroevolution.net.runningnorm import RunningNorm
    from ..neuroevolution.net.vecrl import run_vectorized_rollout
    from ..parallel.evaluate import make_sharded_rollout_evaluator

    cfg = cfg if cfg is not None else GateConfig()
    env, policy = _env_policy(cfg.env_name, cfg.hidden)
    L = policy.parameter_count
    params_sds = jax.ShapeDtypeStruct((cfg.popsize, L), jnp.float32)
    stats = RunningNorm(env.observation_size).stats
    base_shape = {
        "env": cfg.env_name,
        "popsize": cfg.popsize,
        "episode_length": cfg.episode_length,
        "params": L,
    }
    specs: List[ProgramSpec] = []

    def add(name, shape, capture):
        specs.append(ProgramSpec(name=name, shape=shape, capture=capture))

    def rollout_capture(mode, shape, **extra):
        def _capture(led):
            return led.capture(
                f"rollout.{mode}",
                run_vectorized_rollout,
                env,
                policy,
                params_sds,
                jax.random.key(0),
                stats,
                shape=shape,
                num_episodes=1,
                episode_length=cfg.episode_length,
                eval_mode=mode,
                **extra,
            )

        return _capture

    for mode in ("budget", "episodes"):
        add(f"rollout.{mode}", base_shape, rollout_capture(mode, base_shape))
    refill_shape = dict(base_shape, width=cfg.refill_width)
    add(
        "rollout.episodes_refill",
        refill_shape,
        rollout_capture("episodes_refill", refill_shape, refill_width=cfg.refill_width),
    )

    trunk_shape = dict(base_shape, rank=cfg.trunk_rank)

    def trunk_rollout_capture(mode, name, shape, **extra):
        def _capture(led):
            batch = _trunk_delta_batch(policy, cfg.popsize, cfg.trunk_rank)
            return led.capture(
                name,
                run_vectorized_rollout,
                env,
                policy,
                _abstract(batch),
                jax.random.key(0),
                stats,
                shape=shape,
                num_episodes=1,
                episode_length=cfg.episode_length,
                eval_mode=mode,
                **extra,
            )

        return _capture

    add(
        "rollout.budget.trunk_delta",
        trunk_shape,
        trunk_rollout_capture("budget", "rollout.budget.trunk_delta", trunk_shape),
    )
    trunk_refill_shape = dict(trunk_shape, width=cfg.refill_width)
    add(
        "rollout.episodes_refill.trunk_delta",
        trunk_refill_shape,
        trunk_rollout_capture(
            "episodes_refill",
            "rollout.episodes_refill.trunk_delta",
            trunk_refill_shape,
            refill_width=cfg.refill_width,
        ),
    )

    compact_shape = dict(base_shape, chunk=cfg.chunk_size)

    def compact_capture(led):
        return capture_compact_chunk(
            led,
            env,
            policy,
            cfg.popsize,
            cfg.episode_length,
            chunk_size=cfg.chunk_size,
            shape=compact_shape,
        )

    add("rollout.episodes_compact.chunk", compact_shape, compact_capture)

    mesh_size = _mesh_size(cfg.popsize)
    sharded_shape = dict(base_shape, mesh=mesh_size)

    def sharded_capture(led):
        # the SAME mesh the shape metadata records: every popsize keeps a
        # valid (divisible) pop axis, not just multiples of the device count
        mesh = Mesh(np.asarray(jax.devices()[:mesh_size]), axis_names=("pop",))
        evaluator = make_sharded_rollout_evaluator(
            env,
            policy,
            mesh=mesh,
            num_episodes=1,
            episode_length=cfg.episode_length,
            eval_mode="budget",
        )
        fn = evaluator.program_builder(False, cfg.popsize)
        return led.capture(
            "sharded_evaluator",
            fn,
            params_sds,
            jax.random.key(0),
            stats,
            shape=sharded_shape,
        )

    add("sharded_evaluator", sharded_shape, sharded_capture)

    ask_shape = {"popsize": cfg.popsize, "params": L}

    def ask_capture(led):
        ask, _ = _gaussian_programs()
        return led.capture(
            "gaussian.ask",
            ask,
            jax.random.key(0),
            _abstract(_fresh_pgpe_state(L)),
            shape=ask_shape,
            popsize=cfg.popsize,
        )

    def tell_capture(led):
        _, tell = _gaussian_programs()
        return led.capture(
            "gaussian.tell",
            tell,
            _abstract(_fresh_pgpe_state(L)),
            params_sds,
            jax.ShapeDtypeStruct((cfg.popsize,), jnp.float32),
            shape=ask_shape,
        )

    add("gaussian.ask", ask_shape, ask_capture)
    add("gaussian.tell", ask_shape, tell_capture)

    batched_shape = {
        "searches": cfg.batched_searches,
        "dim": cfg.batched_dim,
        "popsize": cfg.batched_popsize,
        "generations": cfg.batched_generations,
    }

    def batched_capture(led):
        fn = _batched_search_program(
            cfg.batched_searches, cfg.batched_dim, cfg.batched_popsize
        )
        state, keys = _batched_search_args(cfg)
        return led.capture(
            "functional_batched_search",
            fn,
            _abstract(state),
            _abstract(keys),
            shape=batched_shape,
        )

    add("functional_batched_search", batched_shape, batched_capture)

    def bench_capture(led):
        fn = _bench_generation_program(env, policy, cfg.popsize, cfg.episode_length)
        return led.capture(
            "bench.generation",
            fn,
            _abstract(_fresh_pgpe_state(L)),
            jax.random.key(0),
            stats,
            shape=base_shape,
        )

    add("bench.generation", base_shape, bench_capture)

    def trunk_bench_capture(led):
        fn = _trunk_generation_program(
            env, policy, cfg.popsize, cfg.episode_length, cfg.trunk_rank
        )
        return led.capture(
            "bench.generation.trunk_delta",
            fn,
            _abstract(_fresh_pgpe_state(L)),
            jax.random.key(0),
            stats,
            shape=trunk_shape,
        )

    add("bench.generation.trunk_delta", trunk_shape, trunk_bench_capture)

    def multichip_capture(led):
        fn = _multichip_generation_program(
            env, policy, mesh_size, cfg.popsize, cfg.episode_length
        )
        return led.capture(
            "multichip.generation",
            fn,
            _abstract(_fresh_pgpe_state(L)),
            jax.random.key(0),
            stats,
            shape=sharded_shape,
        )

    add("multichip.generation", sharded_shape, multichip_capture)

    def gspmd_capture(led):
        fn = _gspmd_generation_program(
            env, policy, mesh_size, cfg.popsize, cfg.episode_length
        )
        return led.capture(
            "gspmd.generation",
            fn,
            _abstract(_fresh_pgpe_state(L)),
            jax.random.key(0),
            stats,
            shape=sharded_shape,
        )

    add("gspmd.generation", sharded_shape, gspmd_capture)

    span_shape = dict(sharded_shape, span=cfg.span)

    def span_capture(led):
        fn = _gspmd_span_program(
            env, policy, mesh_size, cfg.popsize, cfg.episode_length, cfg.span
        )
        return led.capture(
            "gspmd.training_span",
            fn,
            _abstract(_fresh_pgpe_state(L)),
            jax.random.split(jax.random.key(0), cfg.span),
            stats,
            shape=span_shape,
        )

    add("gspmd.training_span", span_shape, span_capture)
    return specs


def _batched_search_args(cfg: GateConfig):
    import jax

    from ..algorithms.functional import cem

    centers = (
        jax.random.normal(
            jax.random.key(0), (cfg.batched_searches, cfg.batched_dim)
        )
        * 3.0
    )
    state = cem(
        center_init=centers,
        parenthood_ratio=0.5,
        objective_sense="min",
        stdev_init=2.0,
        stdev_max_change=0.2,
    )
    keys = jax.random.split(jax.random.key(1), cfg.batched_generations)
    return state, keys


def inventory_keys(cfg: Optional[GateConfig] = None) -> List[str]:
    return [spec.key for spec in build_specs(cfg)]


def capture_inventory(
    cfg: Optional[GateConfig] = None,
    led: Optional[ProgramLedger] = None,
    *,
    strict: bool = True,
) -> Tuple[List[ProgramRecord], Dict[str, str]]:
    """Capture every registered program into ``led`` (the process ledger by
    default). Returns ``(records, errors)``; with ``strict`` (the default)
    the first capture failure raises instead."""
    led = led if led is not None else ledger
    records: List[ProgramRecord] = []
    errors: Dict[str, str] = {}
    for spec in build_specs(cfg):
        try:
            records.append(spec.capture(led))
        except Exception as e:  # pragma: no cover - strict re-raises
            if strict:
                raise
            errors[spec.key] = f"{type(e).__name__}: {e}"
    return records, errors


# ---------------------------------------------------------------------------
# the runtime donation sweep surface
# ---------------------------------------------------------------------------


def donated_programs(cfg: Optional[GateConfig] = None):
    """``(name, fn, args, donate_argnums)`` for every ``donate_argnums``
    entry point the repo registers — bench tell, the bench and multichip
    generation steps, the GSPMD training span, and the batched functional
    search. Each call builds
    FRESH concrete arguments (the verification executes the program and
    consumes the donated buffers). The dynamic complement of graftlint's
    static ``donation`` checker: these assert XLA *applied* the aliasing."""
    import jax
    import jax.numpy as jnp

    from ..neuroevolution.net.runningnorm import RunningNorm

    cfg = cfg if cfg is not None else GateConfig()
    env, policy = _env_policy(cfg.env_name, cfg.hidden)
    L = policy.parameter_count
    stats = RunningNorm(env.observation_size).stats
    _, tell = _gaussian_programs()
    mesh_size = _mesh_size(cfg.popsize)
    values = jnp.zeros((cfg.popsize, L), dtype=jnp.float32)
    fitnesses = jnp.zeros((cfg.popsize,), dtype=jnp.float32)
    batched_state, batched_keys = _batched_search_args(cfg)
    return [
        (
            "gaussian.tell",
            tell,
            (_fresh_pgpe_state(L), values, fitnesses),
            (0,),
        ),
        (
            "bench.generation",
            _bench_generation_program(env, policy, cfg.popsize, cfg.episode_length),
            (_fresh_pgpe_state(L), jax.random.key(0), stats),
            (0,),
        ),
        (
            "bench.generation.trunk_delta",
            _trunk_generation_program(
                env, policy, cfg.popsize, cfg.episode_length, cfg.trunk_rank
            ),
            (_fresh_pgpe_state(L), jax.random.key(0), stats),
            (0,),
        ),
        (
            "multichip.generation",
            _multichip_generation_program(
                env, policy, mesh_size, cfg.popsize, cfg.episode_length
            ),
            (_fresh_pgpe_state(L), jax.random.key(0), stats),
            (0,),
        ),
        (
            "gspmd.generation",
            _gspmd_generation_program(
                env, policy, mesh_size, cfg.popsize, cfg.episode_length
            ),
            (_fresh_pgpe_state(L), jax.random.key(0), stats),
            (0,),
        ),
        (
            "gspmd.training_span",
            _gspmd_span_program(
                env, policy, mesh_size, cfg.popsize, cfg.episode_length, cfg.span
            ),
            (
                _fresh_pgpe_state(L),
                jax.random.split(jax.random.key(0), cfg.span),
                stats,
            ),
            (0,),
        ),
        (
            "functional_batched_search",
            _batched_search_program(
                cfg.batched_searches, cfg.batched_dim, cfg.batched_popsize
            ),
            (batched_state, batched_keys),
            (0,),
        ),
    ]
