"""MetricsHub: streaming export of decoded telemetry + host counters.

One hub = one output file.  Two wire formats, chosen by suffix:

* anything else (conventionally ``.jsonl``) — append-only JSONL: the
  FIRST line is a schema-versioned run manifest
  (``{"manifest": {...}}``: telemetry schema version, best-effort git
  sha, mesh label, tuned_config_source, whatever the caller adds), every
  later line is one ``{"row": N, ...}`` record.  A killed run keeps
  every row already written.
* ``.prom`` — Prometheus text exposition format, FULLY REWRITTEN on each
  emit (the node-exporter "textfile collector" contract): numeric row
  fields become ``evotorch_<key>`` gauges, per-group figures become
  ``evotorch_eval_<col>{group="g"}`` series.

The hub never decodes device arrays itself: callers hand it the
already-decoded :class:`GroupTelemetry` (or plain scalars), so PR 8's
lag-by-one decode discipline — one metered fetch per generation — is
preserved; exporting costs zero extra device syncs.  ``MetricsHub.
from_env()`` wires the ``EVOTORCH_METRICS=path`` knob used by bench.py
and examples/locomotion_curve.py.

See docs/observability.md "Per-group telemetry & SLOs".
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .devicemetrics import (
    GROUP_TELEMETRY_WIDTH,
    TELEMETRY_SCHEMA_VERSION,
    TELEMETRY_WIDTH,
    EvalTelemetry,
    GroupTelemetry,
    _SLOTS,
)
from .registry import counters
from ..resilience.retry import retry_call

__all__ = ["MetricsHub"]

_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: per-group columns exported as labelled Prometheus series (the score_*
#: columns appear only on schema-v4 wires carrying the health plane)
_GROUP_EXPORT_COLS = (
    "env_steps",
    "episodes",
    "capacity",
    "lane_width",
    "refill_events",
    "queue_wait",
    "nonfinite",
    "occupancy",
    "score_count",
    "score_mean",
    "score_std",
    "score_min",
    "score_max",
)


def _git_sha() -> Optional[str]:
    """Best-effort short sha of the working tree; None outside a repo."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def _json_safe(value):
    """Coerce numpy scalars / odd types so json.dumps never raises."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    for cast in (int, float):
        try:
            return cast(value)
        except (TypeError, ValueError):
            continue
    return str(value)


class MetricsHub:
    """Streams per-generation metric rows to a JSONL or ``.prom`` file."""

    def __init__(self, path: str, *, manifest: Optional[Dict[str, Any]] = None):
        self._path = str(path)
        self._prom = self._path.endswith(".prom")
        self._lock = threading.Lock()
        self._rows = 0
        self._manifest = {
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "git_sha": _git_sha(),
            "created_unix": round(time.time(), 3),
            **_json_safe(dict(manifest or {})),
        }
        if not self._prom:
            # manifest is the FIRST line, written eagerly so even a run
            # killed before its first generation leaves a parseable stream
            parent = os.path.dirname(os.path.abspath(self._path))
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self._path, "w", encoding="utf-8") as fh:
                fh.write(json.dumps({"manifest": self._manifest}, sort_keys=True))
                fh.write("\n")

    @classmethod
    def from_env(
        cls, *, manifest: Optional[Dict[str, Any]] = None
    ) -> Optional["MetricsHub"]:
        """Build a hub from ``EVOTORCH_METRICS=path``; None when unset."""
        path = os.environ.get("EVOTORCH_METRICS")
        if not path:
            return None
        return cls(path, manifest=manifest)

    @property
    def path(self) -> str:
        return self._path

    @property
    def manifest(self) -> Dict[str, Any]:
        return dict(self._manifest)

    # ------------------------------------------------------------------ emit
    def emit(
        self,
        row: Optional[Dict[str, Any]] = None,
        *,
        telemetry=None,
        include_counters: bool = True,
    ) -> Dict[str, Any]:
        """Write one record; returns the record as emitted.

        ``telemetry`` may be a decoded :class:`GroupTelemetry`, an
        :class:`EvalTelemetry`, or None.  Its global figures land as
        top-level fields and (at G > 1) the per-group breakdown under
        ``groups``.
        """
        record: Dict[str, Any] = {}
        if telemetry is not None:
            record.update(self._telemetry_fields(telemetry))
        if row:
            record.update(_json_safe(dict(row)))
        if include_counters:
            record["counters"] = {
                k: _json_safe(v) for k, v in counters.snapshot().items()
            }
        with self._lock:
            record["row"] = self._rows
            self._rows += 1
            # writes retry with bounded backoff (resilience.retry): a
            # transient IO blip must not kill the run its metrics describe;
            # the site name makes the path fault-injectable (EVOTORCH_FAULTS
            # "metricshub.write:raise@N")
            if self._prom:
                retry_call(self._write_prom, record, site="metricshub.write")
            else:
                retry_call(self._append_jsonl, record, site="metricshub.write")
        return record

    def _append_jsonl(self, record: Dict[str, Any]) -> None:
        # crash-safe rows: flush + fsync per line, so a SIGKILL'd run keeps
        # every row already emitted (readers skip at most the partial
        # trailing line — slo._last_json_line tolerates one)
        with open(self._path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(record, sort_keys=True))
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())

    @staticmethod
    def _telemetry_fields(telemetry) -> Dict[str, Any]:
        if isinstance(telemetry, EvalTelemetry):
            row = np.zeros((1, GROUP_TELEMETRY_WIDTH), dtype=np.int64)
            row[0, :TELEMETRY_WIDTH] = [
                getattr(telemetry, name) for name in _SLOTS
            ]
            telemetry = GroupTelemetry(data=row)
        if not isinstance(telemetry, GroupTelemetry):
            raise TypeError(
                "telemetry must be GroupTelemetry or EvalTelemetry, got "
                f"{type(telemetry).__name__}"
            )
        total = telemetry.total()
        fields: Dict[str, Any] = {
            "eval_occupancy": round(total.occupancy, 6),
            "eval_env_steps": int(total.env_steps),
            "eval_episodes": int(total.episodes),
            "eval_refill_events": int(total.refill_events),
            "eval_queue_wait": int(total.queue_wait),
            "eval_nonfinite": int(total.nonfinite),
            "queue_wait_p50": telemetry.queue_wait_quantile(0.5),
            "queue_wait_p99": telemetry.queue_wait_quantile(0.99),
        }
        if telemetry.has_health:
            # search-health plane (schema v4): global score statistics
            stats = telemetry.score_stats()
            if stats["count"] > 0:
                fields["score_mean"] = round(stats["mean"], 6)
                fields["score_std"] = round(stats["std"], 6)
                fields["score_min"] = round(stats["min"], 6)
                fields["score_max"] = round(stats["max"], 6)
        if telemetry.num_groups > 1:
            fields["groups"] = telemetry.to_rows()
        return fields

    # ------------------------------------------------------------ prometheus
    def _write_prom(self, record: Dict[str, Any]) -> None:
        # strict textfile-collector format: every metric family gets its
        # `# HELP` / `# TYPE` comment pair before its samples (bare samples
        # trip strict scrapers); labelled per-group series share ONE
        # family header
        families: Dict[str, Dict[str, Any]] = {}

        def add(name, sample, *, mtype, help_text):
            fam = families.setdefault(
                name, {"type": mtype, "help": help_text, "samples": []}
            )
            fam["samples"].append(sample)

        for key, value in sorted(record.items()):
            if key == "groups":
                continue
            if key == "counters" and isinstance(value, dict):
                for cname, cval in sorted(value.items()):
                    if isinstance(cval, (int, float)) and not isinstance(cval, bool):
                        metric = f"evotorch_counter_{_metric_name(cname)}"
                        add(
                            metric,
                            f"{metric} {cval}",
                            mtype="counter",
                            help_text=f"process-lifetime counter {cname}",
                        )
                continue
            if isinstance(value, bool):
                value = int(value)
            elif not isinstance(value, (int, float)):
                continue
            metric = f"evotorch_{_metric_name(key)}"
            add(
                metric,
                f"{metric} {value}",
                mtype="gauge",
                help_text=f"per-generation row field {key}",
            )
        for group_row in record.get("groups", ()):  # labelled per-group series
            gid = group_row.get("group")
            for col in _GROUP_EXPORT_COLS:
                if col in group_row:
                    metric = f"evotorch_eval_{_metric_name(col)}"
                    add(
                        metric,
                        f'{metric}{{group="{gid}"}} {group_row[col]}',
                        mtype="gauge",
                        help_text=f"per-group telemetry column {col}",
                    )
        lines = [
            "# evotorch_tpu metrics (textfile-collector format; "
            f"schema_version={self._manifest['schema_version']})"
        ]
        for name, fam in families.items():
            lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            lines.extend(fam["samples"])
        tmp = self._path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines))
            fh.write("\n")
        os.replace(tmp, self._path)  # atomic: scrapers never see a torn file


def _metric_name(name: str) -> str:
    return _METRIC_NAME_RE.sub("_", str(name))
