"""On-device eval telemetry: packed counter vectors/matrices and host decode.

The zero-sync contract: every rollout engine accumulates its metrics as a
few int32 scalars INSIDE the loop carry it already runs (no new programs,
no host round-trips, no retraces — sentinel-asserted), and packs them into
ONE int32 output at the end of the jitted program. The output rides out in
``RolloutResult.telemetry`` next to the scores, so fetching the whole
telemetry of an evaluation is a single small device->host transfer of an
already-materialized output — and every slot is ADDITIVE, so sharded
evaluations psum it and sub-batched evaluations just add.

Wire formats share the slot layout:

* **v1** — one global ``(TELEMETRY_WIDTH,)`` vector (the PR-8 format;
  ``pack_eval_telemetry`` builds it, :class:`EvalTelemetry` decodes it).
* **v2/v3** — a per-group ``(G, GROUP_TELEMETRY_WIDTH)`` matrix: the first
  ``TELEMETRY_WIDTH`` columns are the v1 slots *per group id*, the
  remaining ``QUEUE_WAIT_BUCKETS`` columns are a log-bucketed queue-wait
  histogram per group (``pack_group_telemetry`` builds it,
  :class:`GroupTelemetry` decodes it; ``TELEMETRY_SCHEMA_VERSION`` names
  the format in metrics manifests). Column-summing the counter block of a
  v2 matrix reproduces the v1 global numbers exactly. (v3 added the
  ``nonfinite`` column to the counter block; v2 wires lift with the
  column read as 0.)
* **v4** — the v3 matrix plus a ``HEALTH_WIDTH``-column *search-health
  plane*: per-group float32 score statistics — ``count, sum, sumsq, min,
  max`` of the final per-solution mean scores — BIT-CAST to int32 so the
  whole wire stays one int32 array and rides the existing psum/``__add__``
  plumbing unchanged (``compute_health_block`` + ``append_health_block``
  build it; the decoders split and re-view the float block). count/sum/
  sumsq are Chan-combinable sums; min/max combine by min/max with
  zero-count rows masked — :meth:`GroupTelemetry.__add__` implements the
  host-side combiner, and on device the engines compute the block ONCE at
  program end from the final scores (sliced to the static ``num_valid``
  so padded and unpadded programs reduce over identical shapes), which is
  what makes rows bit-identical across mesh shapes.

Slots (column order is the wire format — append only):

===================  =======================================================
``env_steps``        counted env interactions (active lanes x steps)
``episodes``         episodes finished
``capacity``         lane-step slots the program executed (working width
                     summed over loop iterations) — the denominator of
                     occupancy; idle masked lanes burn capacity without
                     producing env_steps
``lane_width``       lanes at evaluation start (summed across shards)
``refill_events``    (solution, episode) items loaded into a recycled lane
                     by the refill scheduler (0 outside ``episodes_refill``)
``queue_wait``       lane-steps spent idle while pending work existed —
                     refill-period / drain-ordering waiting; the
                     starvation-accounting numerator
``nonfinite``        solutions whose final score was non-finite and was
                     quarantined (replaced by the worst finite score / a
                     fixed penalty) by the engines' ``nonfinite_quarantine``
                     path (0 with quarantine off; docs/resilience.md)
===================  =======================================================

Histogram buckets (columns ``TELEMETRY_WIDTH ..``): each refilled item's
per-item wait (loop steps between the lane going idle and the refill that
reused it) increments one of ``QUEUE_WAIT_BUCKETS`` log-spaced buckets with
lower edges ``QUEUE_WAIT_BUCKET_EDGES`` — bucket 0 counts zero-wait
refills, bucket ``b`` counts waits in ``[2^(b-1), 2^b - 1]``, the last
bucket is the overflow (>= 64 steps). ``GroupTelemetry.queue_wait_quantile``
reads p50/p99 tail wait off the buckets without ever materializing per-item
waits on the host.

Derived: ``occupancy = env_steps / capacity`` (1.0 for the budget contract
by construction; the idle-lane waste of plain ``episodes`` and the
work-conservation of ``episodes_refill`` are directly visible here), and
``mean_item_wait = queue_wait / refill_events``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from .registry import counters

__all__ = [
    "TELEMETRY_WIDTH",
    "GROUP_TELEMETRY_WIDTH",
    "HEALTH_WIDTH",
    "HEALTH_TELEMETRY_WIDTH",
    "QUEUE_WAIT_BUCKETS",
    "QUEUE_WAIT_BUCKET_EDGES",
    "TELEMETRY_SCHEMA_VERSION",
    "pack_eval_telemetry",
    "pack_group_telemetry",
    "compute_health_block",
    "append_health_block",
    "device_episode_total",
    "queue_wait_bucket_index",
    "EvalTelemetry",
    "GroupTelemetry",
]

#: packed vector layout (order is the wire format — append only)
_SLOTS = (
    "env_steps",
    "episodes",
    "capacity",
    "lane_width",
    "refill_events",
    "queue_wait",
    "nonfinite",
)
TELEMETRY_WIDTH = len(_SLOTS)

#: queue-wait histogram: log-spaced int32 buckets. Bucket 0 = zero-wait
#: refills; bucket b (1..6) = waits in [2^(b-1), 2^b - 1]; bucket 7 =
#: overflow (>= 64 loop steps of waiting).
QUEUE_WAIT_BUCKET_EDGES = (1, 2, 4, 8, 16, 32, 64)
QUEUE_WAIT_BUCKETS = len(QUEUE_WAIT_BUCKET_EDGES) + 1

#: v2 row width: the v1 counter block + the histogram block
GROUP_TELEMETRY_WIDTH = TELEMETRY_WIDTH + QUEUE_WAIT_BUCKETS

#: v4 search-health plane: per-group float32 score statistics in
#: combinable form (count/sum/sumsq add; min/max combine by min/max with
#: empty rows masked), bit-cast to int32 on the wire
_HEALTH_SLOTS = ("score_count", "score_sum", "score_sumsq", "score_min", "score_max")
HEALTH_WIDTH = len(_HEALTH_SLOTS)

#: v4 row width: the v3 row + the bit-cast health block
HEALTH_TELEMETRY_WIDTH = GROUP_TELEMETRY_WIDTH + HEALTH_WIDTH

#: recorded in metrics manifests; bump on any wire-format change
TELEMETRY_SCHEMA_VERSION = 4

#: pre-quarantine wire widths (schema <= 2: no ``nonfinite`` slot) — still
#: decoded, with the missing column read as 0, so recorded feeds and the
#: golden wire vectors from older runs stay loadable
_LEGACY_TELEMETRY_WIDTH = 6
_LEGACY_GROUP_TELEMETRY_WIDTH = _LEGACY_TELEMETRY_WIDTH + QUEUE_WAIT_BUCKETS


def _lift_legacy(values: np.ndarray) -> Optional[np.ndarray]:
    """A schema<=2 wire (no ``nonfinite`` column) widened to the current
    layout (nonfinite=0), or None when ``values`` is not a legacy shape."""
    if values.shape == (_LEGACY_TELEMETRY_WIDTH,):
        out = np.zeros((TELEMETRY_WIDTH,), dtype=np.int64)
        out[:_LEGACY_TELEMETRY_WIDTH] = values
        return out
    if values.ndim == 2 and values.shape[1] == _LEGACY_GROUP_TELEMETRY_WIDTH:
        out = np.zeros((values.shape[0], GROUP_TELEMETRY_WIDTH), dtype=np.int64)
        out[:, :_LEGACY_TELEMETRY_WIDTH] = values[:, :_LEGACY_TELEMETRY_WIDTH]
        out[:, TELEMETRY_WIDTH:] = values[:, _LEGACY_TELEMETRY_WIDTH:]
        return out
    return None

#: inclusive UPPER edge of each non-overflow bucket (host-side quantile
#: decode, Prometheus style: a quantile inside bucket b reports the bucket's
#: upper edge); the overflow bucket reports its lower edge
_BUCKET_UPPER_EDGES = (0, 1, 3, 7, 15, 31, 63, 64)


def pack_eval_telemetry(
    *,
    env_steps,
    episodes,
    capacity,
    lane_width,
    refill_events=0,
    queue_wait=0,
    nonfinite=0,
):
    """Stack the counters into the ``(TELEMETRY_WIDTH,)`` int32 v1 wire
    vector (call inside jit, on the final carry's scalars)."""
    import jax.numpy as jnp

    return jnp.stack(
        [
            jnp.asarray(env_steps, dtype=jnp.int32),
            jnp.asarray(episodes, dtype=jnp.int32),
            jnp.asarray(capacity, dtype=jnp.int32),
            jnp.asarray(lane_width, dtype=jnp.int32),
            jnp.asarray(refill_events, dtype=jnp.int32),
            jnp.asarray(queue_wait, dtype=jnp.int32),
            jnp.asarray(nonfinite, dtype=jnp.int32),
        ]
    )


def pack_group_telemetry(group_counts, hist=None):
    """Concatenate a ``(G, TELEMETRY_WIDTH)`` counter block and a
    ``(G, QUEUE_WAIT_BUCKETS)`` histogram block into the
    ``(G, GROUP_TELEMETRY_WIDTH)`` int32 v2 wire matrix (call inside jit).
    ``hist=None`` emits all-zero buckets (the non-refill engines)."""
    import jax.numpy as jnp

    group_counts = jnp.asarray(group_counts, dtype=jnp.int32)
    if hist is None:
        hist = jnp.zeros(
            (group_counts.shape[0], QUEUE_WAIT_BUCKETS), dtype=jnp.int32
        )
    return jnp.concatenate(
        [group_counts, jnp.asarray(hist, dtype=jnp.int32)], axis=1
    )


def compute_health_block(scores, groups=None, num_groups=1):
    """The ``(G, HEALTH_WIDTH)`` float32 search-health block (call inside
    jit, ONCE at program end): per-group ``count, sum, sumsq, min, max`` of
    the per-solution mean scores. Callers must hand in only the VALID
    scores (slice to the static ``num_valid`` before calling) so padded
    and unpadded programs reduce over identical shapes — that, plus
    computing the block from the final scores rather than accumulating it
    in the loop carry, is what makes the block bit-identical across mesh
    shapes. Empty groups read 0 in every slot (min/max are masked by
    count)."""
    import jax
    import jax.numpy as jnp

    scores = jnp.asarray(scores, dtype=jnp.float32)
    if groups is None:
        groups = jnp.zeros(scores.shape, dtype=jnp.int32)
    else:
        groups = jnp.asarray(groups, dtype=jnp.int32)
    num_groups = int(num_groups)
    count = jax.ops.segment_sum(
        jnp.ones_like(scores), groups, num_segments=num_groups
    )
    total = jax.ops.segment_sum(scores, groups, num_segments=num_groups)
    sumsq = jax.ops.segment_sum(scores * scores, groups, num_segments=num_groups)
    gmin = jax.ops.segment_min(scores, groups, num_segments=num_groups)
    gmax = jax.ops.segment_max(scores, groups, num_segments=num_groups)
    has = count > 0
    gmin = jnp.where(has, gmin, 0.0)
    gmax = jnp.where(has, gmax, 0.0)
    return jnp.stack([count, total, sumsq, gmin, gmax], axis=1)


def append_health_block(telemetry, health):
    """Bit-cast a ``(G, HEALTH_WIDTH)`` float32 health block to int32 and
    append it to the ``(G, GROUP_TELEMETRY_WIDTH)`` counter matrix,
    producing the ``(G, HEALTH_TELEMETRY_WIDTH)`` v4 wire (call inside
    jit). The bit-cast keeps the wire a single int32 array: sharded
    evaluations zero every shard's block except shard 0 before the psum,
    so the existing integer psum carries the float bits through exactly."""
    import jax
    import jax.numpy as jnp

    as_int = jax.lax.bitcast_convert_type(
        jnp.asarray(health, dtype=jnp.float32), jnp.int32
    )
    return jnp.concatenate(
        [jnp.asarray(telemetry, dtype=jnp.int32), as_int], axis=1
    )


def _split_health(values: np.ndarray):
    """Split a host-side v4 ``(G, HEALTH_TELEMETRY_WIDTH)`` matrix into the
    int64 counter block and the re-viewed float32 health block."""
    counter = np.asarray(values[:, :GROUP_TELEMETRY_WIDTH], dtype=np.int64)
    health_bits = np.ascontiguousarray(
        values[:, GROUP_TELEMETRY_WIDTH:], dtype=np.int32
    )
    return counter, health_bits.view(np.float32).astype(np.float64)


def device_episode_total(telemetry):
    """Sum the ``episodes`` slot of a telemetry wire ON DEVICE (jit-safe —
    no host fetch, so async counter bumps stay async): accepts a v1
    ``(TELEMETRY_WIDTH,)`` vector, a ``(G, C)`` matrix, or a STACKED
    ``(K, G, C)`` span of matrices; returns an int32 scalar (0 for an
    empty/telemetry-off wire). The single sanctioned device-side column
    read of the wire — span consumers use it to bump episode counters
    without decoding the stacked rows eagerly."""
    import jax.numpy as jnp

    t = jnp.asarray(telemetry)
    if t.size == 0:
        return jnp.zeros((), dtype=jnp.int32)
    col = _SLOTS.index("episodes")
    if t.ndim == 1:
        return t[col].astype(jnp.int32)
    return t[..., col].sum().astype(jnp.int32)


def queue_wait_bucket_index(waits):
    """Map int32 wait values to histogram bucket indices (inside jit).
    ``sum(wait >= edge)`` over the log-spaced lower edges — branch-free and
    integer-exact."""
    import jax.numpy as jnp

    edges = jnp.asarray(QUEUE_WAIT_BUCKET_EDGES, dtype=jnp.int32)
    waits = jnp.asarray(waits, dtype=jnp.int32)
    return jnp.sum(waits[..., None] >= edges, axis=-1)


@dataclass(frozen=True)
class EvalTelemetry:
    """Host-side decode of one (or an accumulated sum of) telemetry vectors."""

    env_steps: int = 0
    episodes: int = 0
    capacity: int = 0
    lane_width: int = 0
    refill_events: int = 0
    queue_wait: int = 0
    nonfinite: int = 0

    @classmethod
    def from_array(cls, array) -> "EvalTelemetry":
        """Decode a packed v1 ``(TELEMETRY_WIDTH,)`` vector OR a v2
        ``(G, GROUP_TELEMETRY_WIDTH)`` matrix (column-summed to the global
        totals). The one device->host transfer of the telemetry path —
        metered as a ``telemetry_fetches`` registry count so "zero extra
        transfers" stays auditable."""
        values = np.asarray(array)
        legacy = _lift_legacy(values)
        if legacy is not None:
            values = legacy
        if values.shape == (TELEMETRY_WIDTH,):
            counters.increment("telemetry_fetches")
            return cls(**{name: int(values[i]) for i, name in enumerate(_SLOTS)})
        if values.ndim == 2 and values.shape[1] in (
            GROUP_TELEMETRY_WIDTH,
            HEALTH_TELEMETRY_WIDTH,
        ):
            counters.increment("telemetry_fetches")
            totals = values[:, :TELEMETRY_WIDTH].sum(axis=0)
            return cls(**{name: int(totals[i]) for i, name in enumerate(_SLOTS)})
        raise ValueError(
            f"expected a ({TELEMETRY_WIDTH},) telemetry vector or a"
            f" (G, {GROUP_TELEMETRY_WIDTH}) / (G, {HEALTH_TELEMETRY_WIDTH})"
            f" per-group matrix, got shape {values.shape}"
        )

    def __add__(self, other: "EvalTelemetry") -> "EvalTelemetry":
        if not isinstance(other, EvalTelemetry):
            return NotImplemented
        return EvalTelemetry(
            **{name: getattr(self, name) + getattr(other, name) for name in _SLOTS}
        )

    @property
    def occupancy(self) -> float:
        """Fraction of executed lane-step slots that were genuine, counted
        env interactions (0.0 when nothing ran)."""
        return self.env_steps / self.capacity if self.capacity else 0.0

    @property
    def mean_item_wait(self) -> float:
        """Mean idle lane-steps per refilled item — the refill-fairness /
        starvation figure (0.0 without refills)."""
        return self.queue_wait / self.refill_events if self.refill_events else 0.0

    def as_status(self, prefix: str = "eval_") -> dict:
        """The scalar status-dict form loggers pick up."""
        return {
            f"{prefix}occupancy": round(self.occupancy, 6),
            f"{prefix}refill_events": self.refill_events,
            f"{prefix}queue_wait": self.queue_wait,
            f"{prefix}nonfinite": self.nonfinite,
        }

    def summary(self) -> str:
        return (
            f"env_steps={self.env_steps} episodes={self.episodes} "
            f"occupancy={self.occupancy:.4f} lane_width={self.lane_width} "
            f"refill_events={self.refill_events} queue_wait={self.queue_wait} "
            f"nonfinite={self.nonfinite}"
        )


@dataclass(frozen=True)
class GroupTelemetry:
    """Host-side decode of a v2 per-group ``(G, GROUP_TELEMETRY_WIDTH)``
    telemetry matrix — per-group counters plus queue-wait histograms.

    Rows are ADDITIVE like the v1 slots: sharded matrices psum, sub-batched
    matrices add (``__add__``). ``total()`` collapses to the v1 global
    figures; ``group(g)`` reads one group's counters; the histogram
    quantiles answer "what is this group's tail queue wait" without a
    per-item host transfer.

    A v4 wire additionally carries the bit-cast search-health block;
    ``health`` holds it re-viewed as a float ``(G, HEALTH_WIDTH)`` matrix
    (None on pre-v4 wires), ``score_stats`` derives mean/std/min/max, and
    ``__add__`` combines blocks Chan-style (count/sum/sumsq add, min/max
    by min/max with empty rows masked).
    """

    data: np.ndarray = field(
        default_factory=lambda: np.zeros(
            (1, GROUP_TELEMETRY_WIDTH), dtype=np.int64
        )
    )
    health: Optional[np.ndarray] = None

    @classmethod
    def from_array(cls, array) -> "GroupTelemetry":
        """Decode a v2/v3/v4 matrix, or lift a v1 vector into a
        single-group matrix with empty histogram buckets. Metered like
        :meth:`EvalTelemetry.from_array`."""
        values = np.asarray(array)
        legacy = _lift_legacy(values)
        if legacy is not None:
            values = legacy
        if values.shape == (TELEMETRY_WIDTH,):
            row = np.zeros((1, GROUP_TELEMETRY_WIDTH), dtype=np.int64)
            row[0, :TELEMETRY_WIDTH] = values
            counters.increment("telemetry_fetches")
            return cls(data=row)
        if values.ndim == 2 and values.shape[1] == HEALTH_TELEMETRY_WIDTH:
            counters.increment("telemetry_fetches")
            counter, health = _split_health(values)
            return cls(data=counter, health=health)
        if values.ndim == 2 and values.shape[1] == GROUP_TELEMETRY_WIDTH:
            counters.increment("telemetry_fetches")
            return cls(data=np.asarray(values, dtype=np.int64).copy())
        raise ValueError(
            f"expected a (G, {GROUP_TELEMETRY_WIDTH}) or"
            f" (G, {HEALTH_TELEMETRY_WIDTH}) per-group telemetry matrix or"
            f" a ({TELEMETRY_WIDTH},) v1 vector, got shape {values.shape}"
        )

    @property
    def num_groups(self) -> int:
        return int(self.data.shape[0])

    @property
    def hist(self) -> np.ndarray:
        """The ``(G, QUEUE_WAIT_BUCKETS)`` queue-wait histogram block."""
        return self.data[:, TELEMETRY_WIDTH:]

    def group(self, g: int) -> EvalTelemetry:
        """One group's counters as an :class:`EvalTelemetry` (no fetch
        metering — the matrix was already fetched)."""
        row = self.data[g]
        return EvalTelemetry(
            **{name: int(row[i]) for i, name in enumerate(_SLOTS)}
        )

    def total(self) -> EvalTelemetry:
        """Column-sum to the v1 global figures (no fetch metering)."""
        totals = self.data[:, :TELEMETRY_WIDTH].sum(axis=0)
        return EvalTelemetry(
            **{name: int(totals[i]) for i, name in enumerate(_SLOTS)}
        )

    def __add__(self, other: "GroupTelemetry") -> "GroupTelemetry":
        if not isinstance(other, GroupTelemetry):
            return NotImplemented
        a, b = self.data, other.data
        ha, hb = self.health, other.health
        g = max(a.shape[0], b.shape[0])
        if a.shape[0] != b.shape[0]:
            # sub-batches may see different group counts; pad to the max
            pa = np.zeros((g, GROUP_TELEMETRY_WIDTH), dtype=np.int64)
            pb = np.zeros((g, GROUP_TELEMETRY_WIDTH), dtype=np.int64)
            pa[: a.shape[0]] = a
            pb[: b.shape[0]] = b
            a, b = pa, pb
        health = None
        if ha is not None and hb is not None:
            pa = np.zeros((g, HEALTH_WIDTH), dtype=np.float64)
            pb = np.zeros((g, HEALTH_WIDTH), dtype=np.float64)
            pa[: ha.shape[0]] = ha
            pb[: hb.shape[0]] = hb
            health = pa + pb  # count/sum/sumsq are Chan-combinable sums
            # min/max: the empty side must not contribute its masked 0
            a_has, b_has = pa[:, 0] > 0, pb[:, 0] > 0
            health[:, 3] = np.where(
                a_has & b_has,
                np.minimum(pa[:, 3], pb[:, 3]),
                np.where(a_has, pa[:, 3], pb[:, 3]),
            )
            health[:, 4] = np.where(
                a_has & b_has,
                np.maximum(pa[:, 4], pb[:, 4]),
                np.where(a_has, pa[:, 4], pb[:, 4]),
            )
        return GroupTelemetry(data=a + b, health=health)

    def queue_wait_quantile(
        self, q: float, group: Optional[int] = None
    ) -> float:
        """Approximate wait quantile (in loop steps) off the bucketed
        histogram, Prometheus style: walk the cumulative counts and report
        the inclusive upper edge of the bucket containing the quantile (the
        overflow bucket reports its lower edge, 64). 0.0 when no refills
        were histogrammed."""
        hist = self.hist if group is None else self.hist[group : group + 1]
        hist = np.asarray(hist, dtype=np.int64).sum(axis=0)
        total = int(hist.sum())
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for b in range(QUEUE_WAIT_BUCKETS):
            cum += int(hist[b])
            if cum >= target:
                return float(_BUCKET_UPPER_EDGES[b])
        return float(_BUCKET_UPPER_EDGES[-1])

    def nonfinite_share(self, group: Optional[int] = None) -> float:
        """Share of finished episodes whose solution was quarantined for a
        non-finite score — the ``max_nonfinite_share`` SLO fallback figure
        when no exact status key is available. ``nonfinite`` counts
        SOLUTIONS and ``episodes`` counts episodes, so the ratio is exact
        at ``num_episodes=1`` and an under-estimate otherwise (each
        quarantined solution contributed ``num_episodes`` episodes);
        0.0 when nothing finished."""
        rows = self.data if group is None else self.data[group : group + 1]
        episodes = int(rows[:, _SLOTS.index("episodes")].sum())
        nonfinite = int(rows[:, _SLOTS.index("nonfinite")].sum())
        return (nonfinite / episodes) if episodes else 0.0

    def starvation_share(self, group: Optional[int] = None) -> float:
        """Share of refilled items that landed in the overflow (>= 64 step
        wait) bucket — the SLO watchdog's starvation figure (0.0 without
        histogrammed refills)."""
        hist = self.hist if group is None else self.hist[group : group + 1]
        hist = np.asarray(hist, dtype=np.int64).sum(axis=0)
        total = int(hist.sum())
        return (int(hist[-1]) / total) if total else 0.0

    @property
    def has_health(self) -> bool:
        """Whether this wire carried the v4 search-health block."""
        return self.health is not None

    def score_stats(self, group: Optional[int] = None) -> Optional[dict]:
        """Score statistics derived from the health block — ``count``,
        ``mean``, ``std`` (population), ``min``, ``max`` — globally or for
        one group; None on pre-v4 wires, all-zero when nothing scored."""
        if self.health is None:
            return None
        rows = self.health if group is None else self.health[group : group + 1]
        count = float(rows[:, 0].sum())
        if count <= 0:
            return {"count": 0.0, "mean": 0.0, "std": 0.0, "min": 0.0, "max": 0.0}
        mean = float(rows[:, 1].sum()) / count
        var = max(float(rows[:, 2].sum()) / count - mean * mean, 0.0)
        nz = rows[rows[:, 0] > 0]
        return {
            "count": count,
            "mean": mean,
            "std": var ** 0.5,
            "min": float(nz[:, 3].min()),
            "max": float(nz[:, 4].max()),
        }

    def score_mean(self, group: Optional[int] = None) -> Optional[float]:
        stats = self.score_stats(group)
        return None if stats is None else stats["mean"]

    def score_std(self, group: Optional[int] = None) -> Optional[float]:
        stats = self.score_stats(group)
        return None if stats is None else stats["std"]

    def as_status(self, prefix: str = "eval_") -> dict:
        """Per-group status keys (``{prefix}g{g}_...``) next to the global
        figures — only emitted when there is more than one group, so the
        G=1 status dict stays exactly the v1 shape."""
        out = {}
        if self.num_groups > 1:
            for g in range(self.num_groups):
                row = self.group(g)
                out[f"{prefix}g{g}_occupancy"] = round(row.occupancy, 6)
                out[f"{prefix}g{g}_env_steps"] = row.env_steps
                out[f"{prefix}g{g}_episodes"] = row.episodes
                out[f"{prefix}g{g}_queue_wait"] = row.queue_wait
                out[f"{prefix}g{g}_nonfinite"] = row.nonfinite
                if self.health is not None:
                    stats = self.score_stats(g)
                    out[f"{prefix}g{g}_score_mean"] = round(stats["mean"], 6)
                    out[f"{prefix}g{g}_score_std"] = round(stats["std"], 6)
        return out

    def summary(self) -> str:
        tot = self.total()
        parts = [f"groups={self.num_groups}", tot.summary()]
        if int(self.hist.sum()):
            parts.append(
                f"queue_wait_p50={self.queue_wait_quantile(0.5):g}"
                f" p99={self.queue_wait_quantile(0.99):g}"
            )
        if self.health is not None:
            stats = self.score_stats()
            parts.append(
                f"score_mean={stats['mean']:g} score_std={stats['std']:g}"
            )
        return " ".join(parts)

    def to_wire(self) -> np.ndarray:
        """Re-pack into the int32 wire matrix — ``(G,
        HEALTH_TELEMETRY_WIDTH)`` with the health block bit-cast back when
        this decode carried one, else ``(G, GROUP_TELEMETRY_WIDTH)``.
        Decode → combine (``__add__``) → re-pack is lossless for the
        counter block and float32-exact for the health block, which is how
        host-side consumers that accumulate rows across dispatches (the
        serving backend merging a request's per-dispatch tenant rows) hand
        a standard wire back to ``from_array`` consumers."""
        counter = np.asarray(self.data, dtype=np.int64)
        if np.any(counter > np.iinfo(np.int32).max) or np.any(
            counter < np.iinfo(np.int32).min
        ):
            raise OverflowError(
                "accumulated telemetry counters exceed the int32 wire range"
            )
        wire = counter.astype(np.int32)
        if self.health is None:
            return wire
        bits = (
            np.asarray(self.health, dtype=np.float32)
            .view(np.int32)
            .reshape(self.num_groups, HEALTH_WIDTH)
        )
        return np.concatenate([wire, bits], axis=1)

    def to_rows(self) -> Tuple[dict, ...]:
        """JSON-safe per-group rows for the MetricsHub stream."""
        rows = []
        for g in range(self.num_groups):
            row = self.group(g)
            entry = {
                "group": g,
                **{name: getattr(row, name) for name in _SLOTS},
                "occupancy": round(row.occupancy, 6),
                "queue_wait_hist": [int(v) for v in self.hist[g]],
            }
            if self.health is not None:
                stats = self.score_stats(g)
                entry["score_count"] = stats["count"]
                entry["score_mean"] = stats["mean"]
                entry["score_std"] = stats["std"]
                entry["score_min"] = stats["min"]
                entry["score_max"] = stats["max"]
            rows.append(entry)
        return tuple(rows)
