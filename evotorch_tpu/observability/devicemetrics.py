"""On-device eval telemetry: the packed counter vector and its host decode.

The zero-sync contract: every rollout engine accumulates its metrics as a
few int32 scalars INSIDE the loop carry it already runs (no new programs,
no host round-trips, no retraces — sentinel-asserted), and packs them into
ONE ``(TELEMETRY_WIDTH,)`` int32 vector at the end of the jitted program.
The vector rides out in ``RolloutResult.telemetry`` next to the scores, so
fetching the whole telemetry of an evaluation is a single ~24-byte
device->host transfer of an already-materialized output — and every slot is
ADDITIVE, so sharded evaluations psum the vector and sub-batched
evaluations just add them.

Slots (``pack_eval_telemetry`` builds, :class:`EvalTelemetry` decodes):

===================  =======================================================
``env_steps``        counted env interactions (active lanes x steps)
``episodes``         episodes finished
``capacity``         lane-step slots the program executed (working width
                     summed over loop iterations) — the denominator of
                     occupancy; idle masked lanes burn capacity without
                     producing env_steps
``lane_width``       lanes at evaluation start (summed across shards)
``refill_events``    (solution, episode) items loaded into a recycled lane
                     by the refill scheduler (0 outside ``episodes_refill``)
``queue_wait``       lane-steps spent idle while pending work existed —
                     refill-period / drain-ordering waiting; the
                     starvation-accounting numerator
===================  =======================================================

Derived: ``occupancy = env_steps / capacity`` (1.0 for the budget contract
by construction; the idle-lane waste of plain ``episodes`` and the
work-conservation of ``episodes_refill`` are directly visible here), and
``mean_item_wait = queue_wait / refill_events``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .registry import counters

__all__ = ["TELEMETRY_WIDTH", "pack_eval_telemetry", "EvalTelemetry"]

#: packed vector layout (order is the wire format — append only)
_SLOTS = (
    "env_steps",
    "episodes",
    "capacity",
    "lane_width",
    "refill_events",
    "queue_wait",
)
TELEMETRY_WIDTH = len(_SLOTS)


def pack_eval_telemetry(
    *,
    env_steps,
    episodes,
    capacity,
    lane_width,
    refill_events=0,
    queue_wait=0,
):
    """Stack the counters into the ``(TELEMETRY_WIDTH,)`` int32 wire vector
    (call inside jit, on the final carry's scalars)."""
    import jax.numpy as jnp

    return jnp.stack(
        [
            jnp.asarray(env_steps, dtype=jnp.int32),
            jnp.asarray(episodes, dtype=jnp.int32),
            jnp.asarray(capacity, dtype=jnp.int32),
            jnp.asarray(lane_width, dtype=jnp.int32),
            jnp.asarray(refill_events, dtype=jnp.int32),
            jnp.asarray(queue_wait, dtype=jnp.int32),
        ]
    )


@dataclass(frozen=True)
class EvalTelemetry:
    """Host-side decode of one (or an accumulated sum of) telemetry vectors."""

    env_steps: int = 0
    episodes: int = 0
    capacity: int = 0
    lane_width: int = 0
    refill_events: int = 0
    queue_wait: int = 0

    @classmethod
    def from_array(cls, array) -> "EvalTelemetry":
        """Decode a packed vector (device or host). The one device->host
        transfer of the telemetry path — metered as a ``telemetry_fetches``
        registry count so "zero extra transfers" stays auditable."""
        values = np.asarray(array)
        if values.shape != (TELEMETRY_WIDTH,):
            raise ValueError(
                f"expected a ({TELEMETRY_WIDTH},) telemetry vector, got shape"
                f" {values.shape}"
            )
        counters.increment("telemetry_fetches")
        return cls(**{name: int(values[i]) for i, name in enumerate(_SLOTS)})

    def __add__(self, other: "EvalTelemetry") -> "EvalTelemetry":
        if not isinstance(other, EvalTelemetry):
            return NotImplemented
        return EvalTelemetry(
            **{name: getattr(self, name) + getattr(other, name) for name in _SLOTS}
        )

    @property
    def occupancy(self) -> float:
        """Fraction of executed lane-step slots that were genuine, counted
        env interactions (0.0 when nothing ran)."""
        return self.env_steps / self.capacity if self.capacity else 0.0

    @property
    def mean_item_wait(self) -> float:
        """Mean idle lane-steps per refilled item — the refill-fairness /
        starvation figure (0.0 without refills)."""
        return self.queue_wait / self.refill_events if self.refill_events else 0.0

    def as_status(self, prefix: str = "eval_") -> dict:
        """The scalar status-dict form loggers pick up."""
        return {
            f"{prefix}occupancy": round(self.occupancy, 6),
            f"{prefix}refill_events": self.refill_events,
            f"{prefix}queue_wait": self.queue_wait,
        }

    def summary(self) -> str:
        return (
            f"env_steps={self.env_steps} episodes={self.episodes} "
            f"occupancy={self.occupancy:.4f} lane_width={self.lane_width} "
            f"refill_events={self.refill_events} queue_wait={self.queue_wait}"
        )
