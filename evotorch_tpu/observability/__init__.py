"""Zero-sync telemetry for the eval stack.

Three cooperating pieces (docs/observability.md has the full catalog):

- :mod:`~evotorch_tpu.observability.devicemetrics` — ON-DEVICE metric
  accumulators: env-steps, episodes, lane capacity (occupancy), refill
  events and queue-wait lane-steps, accumulated inside the existing
  rollout ``lax.while_loop`` carries and returned as ONE packed ``(6,)``
  int32 vector in the same device->host transfer as the scores. Zero
  extra dispatches, zero retraces (sentinel-asserted in the fast tier).
- :mod:`~evotorch_tpu.observability.tracer` — a host-side span tracer
  emitting Chrome trace-event JSON loadable in Perfetto (ring-buffered;
  a no-op singleton when disabled). Spans cover ask/eval/tell in the
  search loop, the host pipeline's S1/S2/S3 stages + the physics worker
  thread (overlap is visible as parallel tracks), and hostpool syncs.
  Enable with ``EVOTORCH_TRACE=/path/to/trace.json`` or
  :func:`~evotorch_tpu.observability.tracer.start_tracing`.
- :mod:`~evotorch_tpu.observability.registry` — a process-wide counter
  registry (``compiles`` via the session-wide promotion of
  ``retrace_sentinel``'s compile counting, ``trace_spans``,
  ``telemetry_fetches``, ``compile_seconds`` wall time,
  ``peak_hbm_bytes`` gauge) surfaced through searcher ``status`` dicts, so
  ``StdOutLogger``/``PandasLogger`` pick everything up for free.
- :mod:`~evotorch_tpu.observability.programs` — the PROGRAM ledger
  (compile-time sibling of the runtime telemetry above): per
  (program, shape) XLA cost/memory accounting, runtime-verified
  ``donate_argnums`` aliasing, and the checked-in perf-regression
  baseline (``ledger_baseline.json``, gated in the fast tier). Report
  CLI: ``python -m evotorch_tpu.observability.report``.
- :mod:`~evotorch_tpu.observability.timings` — the MEASURED-timing
  ledger (runtime sibling of the program ledger: median steps/s,
  occupancy, compile seconds per (program, shape, machine) key) and the
  persisted tuned-config cache (``tuned_configs.json``) the eval stack
  consults at setup time — explicit knobs always override; every
  consumer reports ``tuned_config_source`` provenance. Filled by the
  autotuner: ``python -m evotorch_tpu.observability.autotune``
  (:mod:`~evotorch_tpu.observability.autotune`).
"""

from .compilecache import (  # noqa: F401
    cache_stats,
    enable_persistent_cache,
)
from .devicemetrics import (  # noqa: F401
    EvalTelemetry,
    TELEMETRY_WIDTH,
    pack_eval_telemetry,
)
from .programs import (  # noqa: F401
    DonationReport,
    ProgramLedger,
    ProgramRecord,
    compare_to_baseline,
    default_ledger_baseline_path,
    guarded_cost_analysis,
    guarded_memory_analysis,
    ledger,
    load_ledger_baseline,
    save_ledger_baseline,
    verify_runtime_donation,
)
from .registry import (  # noqa: F401
    CounterRegistry,
    counters,
    ensure_compile_counter,
    ensure_compile_timer,
)
from .timings import (  # noqa: F401
    SOURCE_CACHE,
    SOURCE_FALLBACK,
    SOURCE_OVERRIDE,
    TimingLedger,
    TimingRecord,
    TunedEntry,
    canonical_env_label,
    default_tuned_cache_path,
    load_tuned_cache,
    lookup_tuned,
    machine_fingerprint,
    resolve_knobs,
    save_tuned_entry,
    timing_key,
    timings,
)
from .tracer import (  # noqa: F401
    SpanTracer,
    get_tracer,
    instant,
    span,
    start_tracing,
    stop_tracing,
    tracing_enabled,
)

__all__ = [
    "cache_stats",
    "enable_persistent_cache",
    "EvalTelemetry",
    "TELEMETRY_WIDTH",
    "pack_eval_telemetry",
    "CounterRegistry",
    "counters",
    "ensure_compile_counter",
    "ensure_compile_timer",
    "DonationReport",
    "ProgramLedger",
    "ProgramRecord",
    "compare_to_baseline",
    "default_ledger_baseline_path",
    "guarded_cost_analysis",
    "guarded_memory_analysis",
    "ledger",
    "load_ledger_baseline",
    "save_ledger_baseline",
    "verify_runtime_donation",
    "SpanTracer",
    "get_tracer",
    "instant",
    "span",
    "start_tracing",
    "stop_tracing",
    "tracing_enabled",
    "SOURCE_CACHE",
    "SOURCE_FALLBACK",
    "SOURCE_OVERRIDE",
    "TimingLedger",
    "TimingRecord",
    "TunedEntry",
    "canonical_env_label",
    "default_tuned_cache_path",
    "load_tuned_cache",
    "lookup_tuned",
    "machine_fingerprint",
    "resolve_knobs",
    "save_tuned_entry",
    "timing_key",
    "timings",
]
