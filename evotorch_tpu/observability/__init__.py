"""Zero-sync telemetry for the eval stack.

Three cooperating pieces (docs/observability.md has the full catalog):

- :mod:`~evotorch_tpu.observability.devicemetrics` — ON-DEVICE metric
  accumulators: env-steps, episodes, lane capacity (occupancy), refill
  events and queue-wait lane-steps, accumulated inside the existing
  rollout ``lax.while_loop`` carries and returned as ONE packed int32
  array in the same device->host transfer as the scores. Zero extra
  dispatches, zero retraces (sentinel-asserted in the fast tier). The v4
  wire is a PER-GROUP ``(G, 20)`` matrix (segment-summed counters +
  bucketed queue-wait histograms + the float32 search-health block of
  score statistics, bit-cast into the int32 rows; ``GroupTelemetry``
  decodes it, and the v1 ``(6,)`` / v2 ``(G, 14)`` / v3 ``(G, 15)``
  wires still decode everywhere).
- :mod:`~evotorch_tpu.observability.health` — windowed, variance-aware
  trend detection over the health plane (``EWMATrend`` /
  ``HealthMonitor``), feeding the ``plateau`` / ``stdev_collapse`` /
  ``score_snr_floor`` SLO rule kinds.
- :mod:`~evotorch_tpu.observability.tracer` — a host-side span tracer
  emitting Chrome trace-event JSON loadable in Perfetto (ring-buffered;
  a no-op singleton when disabled). Spans cover ask/eval/tell in the
  search loop, the host pipeline's S1/S2/S3 stages + the physics worker
  thread (overlap is visible as parallel tracks), and hostpool syncs.
  Enable with ``EVOTORCH_TRACE=/path/to/trace.json`` or
  :func:`~evotorch_tpu.observability.tracer.start_tracing`.
- :mod:`~evotorch_tpu.observability.registry` — a process-wide counter
  registry (``compiles`` via the session-wide promotion of
  ``retrace_sentinel``'s compile counting, ``trace_spans``,
  ``telemetry_fetches``, ``compile_seconds`` wall time,
  ``peak_hbm_bytes`` gauge) surfaced through searcher ``status`` dicts, so
  ``StdOutLogger``/``PandasLogger`` pick everything up for free.
- :mod:`~evotorch_tpu.observability.programs` — the PROGRAM ledger
  (compile-time sibling of the runtime telemetry above): per
  (program, shape) XLA cost/memory accounting, runtime-verified
  ``donate_argnums`` aliasing, and the checked-in perf-regression
  baseline (``ledger_baseline.json``, gated in the fast tier). Report
  CLI: ``python -m evotorch_tpu.observability.report``.
- :mod:`~evotorch_tpu.observability.timings` — the MEASURED-timing
  ledger (runtime sibling of the program ledger: median steps/s,
  occupancy, compile seconds per (program, shape, machine) key) and the
  persisted tuned-config cache (``tuned_configs.json``) the eval stack
  consults at setup time — explicit knobs always override; every
  consumer reports ``tuned_config_source`` provenance. Filled by the
  autotuner: ``python -m evotorch_tpu.observability.autotune``
  (:mod:`~evotorch_tpu.observability.autotune`).
- :mod:`~evotorch_tpu.observability.metricshub` — streaming export of the
  decoded telemetry + counter registry as schema-versioned JSONL (manifest
  first line) or Prometheus text (``.prom`` suffix); wired to
  ``EVOTORCH_METRICS=path`` in bench.py and the curve runner.
- :mod:`~evotorch_tpu.observability.slo` — declarative SLO watchdog
  (per-group occupancy floor, starvation ceiling off the top queue-wait
  bucket, steady_compiles == 0, min progress) surfaced as searcher status
  keys (``VecNEProblem(slo=...)``) and the tpu_window.sh battery verdict
  (``python -m evotorch_tpu.observability.slo --check-bench``).
"""

from .compilecache import (  # noqa: F401
    cache_stats,
    enable_persistent_cache,
)
from .devicemetrics import (  # noqa: F401
    EvalTelemetry,
    GROUP_TELEMETRY_WIDTH,
    GroupTelemetry,
    HEALTH_TELEMETRY_WIDTH,
    HEALTH_WIDTH,
    QUEUE_WAIT_BUCKET_EDGES,
    QUEUE_WAIT_BUCKETS,
    TELEMETRY_SCHEMA_VERSION,
    TELEMETRY_WIDTH,
    append_health_block,
    compute_health_block,
    pack_eval_telemetry,
    pack_group_telemetry,
    queue_wait_bucket_index,
)
# MetricsHub / SLO / health names resolve lazily (module __getattr__
# below): an eager `from .slo import ...` here would trip runpy's
# double-import warning every time the CLI runs as
# `python -m evotorch_tpu.observability.slo`
_LAZY_EXPORTS = {
    "MetricsHub": "metricshub",
    "Rule": "slo",
    "SLOReport": "slo",
    "SLOWatchdog": "slo",
    "EWMATrend": "health",
    "HealthMonitor": "health",
}


def __getattr__(name):
    submodule = _LAZY_EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{submodule}", __name__), name)
    globals()[name] = value
    return value
from .programs import (  # noqa: F401
    DonationReport,
    ProgramLedger,
    ProgramRecord,
    compare_to_baseline,
    default_ledger_baseline_path,
    guarded_cost_analysis,
    guarded_memory_analysis,
    ledger,
    load_ledger_baseline,
    save_ledger_baseline,
    verify_runtime_donation,
)
from .registry import (  # noqa: F401
    CounterRegistry,
    counters,
    ensure_compile_counter,
    ensure_compile_timer,
)
from .timings import (  # noqa: F401
    SOURCE_CACHE,
    SOURCE_FALLBACK,
    SOURCE_OVERRIDE,
    TimingLedger,
    TimingRecord,
    TunedEntry,
    canonical_env_label,
    default_tuned_cache_path,
    load_tuned_cache,
    lookup_tuned,
    machine_fingerprint,
    resolve_knobs,
    save_tuned_entry,
    timing_key,
    timings,
)
from .tracer import (  # noqa: F401
    SpanTracer,
    get_tracer,
    instant,
    span,
    start_tracing,
    stop_tracing,
    tracing_enabled,
)

__all__ = [
    "cache_stats",
    "enable_persistent_cache",
    "EvalTelemetry",
    "GroupTelemetry",
    "GROUP_TELEMETRY_WIDTH",
    "HEALTH_TELEMETRY_WIDTH",
    "HEALTH_WIDTH",
    "QUEUE_WAIT_BUCKETS",
    "QUEUE_WAIT_BUCKET_EDGES",
    "TELEMETRY_SCHEMA_VERSION",
    "TELEMETRY_WIDTH",
    "append_health_block",
    "compute_health_block",
    "pack_eval_telemetry",
    "pack_group_telemetry",
    "queue_wait_bucket_index",
    "MetricsHub",
    "Rule",
    "SLOReport",
    "SLOWatchdog",
    "EWMATrend",
    "HealthMonitor",
    "CounterRegistry",
    "counters",
    "ensure_compile_counter",
    "ensure_compile_timer",
    "DonationReport",
    "ProgramLedger",
    "ProgramRecord",
    "compare_to_baseline",
    "default_ledger_baseline_path",
    "guarded_cost_analysis",
    "guarded_memory_analysis",
    "ledger",
    "load_ledger_baseline",
    "save_ledger_baseline",
    "verify_runtime_donation",
    "SpanTracer",
    "get_tracer",
    "instant",
    "span",
    "start_tracing",
    "stop_tracing",
    "tracing_enabled",
    "SOURCE_CACHE",
    "SOURCE_FALLBACK",
    "SOURCE_OVERRIDE",
    "TimingLedger",
    "TimingRecord",
    "TunedEntry",
    "canonical_env_label",
    "default_tuned_cache_path",
    "load_tuned_cache",
    "lookup_tuned",
    "machine_fingerprint",
    "resolve_knobs",
    "save_tuned_entry",
    "timing_key",
    "timings",
]
