"""Zero-sync telemetry for the eval stack.

Three cooperating pieces (docs/observability.md has the full catalog):

- :mod:`~evotorch_tpu.observability.devicemetrics` — ON-DEVICE metric
  accumulators: env-steps, episodes, lane capacity (occupancy), refill
  events and queue-wait lane-steps, accumulated inside the existing
  rollout ``lax.while_loop`` carries and returned as ONE packed ``(6,)``
  int32 vector in the same device->host transfer as the scores. Zero
  extra dispatches, zero retraces (sentinel-asserted in the fast tier).
- :mod:`~evotorch_tpu.observability.tracer` — a host-side span tracer
  emitting Chrome trace-event JSON loadable in Perfetto (ring-buffered;
  a no-op singleton when disabled). Spans cover ask/eval/tell in the
  search loop, the host pipeline's S1/S2/S3 stages + the physics worker
  thread (overlap is visible as parallel tracks), and hostpool syncs.
  Enable with ``EVOTORCH_TRACE=/path/to/trace.json`` or
  :func:`~evotorch_tpu.observability.tracer.start_tracing`.
- :mod:`~evotorch_tpu.observability.registry` — a process-wide counter
  registry (``compiles`` via the session-wide promotion of
  ``retrace_sentinel``'s compile counting, ``trace_spans``,
  ``telemetry_fetches``) surfaced through searcher ``status`` dicts, so
  ``StdOutLogger``/``PandasLogger`` pick everything up for free.
"""

from .devicemetrics import (  # noqa: F401
    EvalTelemetry,
    TELEMETRY_WIDTH,
    pack_eval_telemetry,
)
from .registry import (  # noqa: F401
    CounterRegistry,
    counters,
    ensure_compile_counter,
)
from .tracer import (  # noqa: F401
    SpanTracer,
    get_tracer,
    instant,
    span,
    start_tracing,
    stop_tracing,
    tracing_enabled,
)

__all__ = [
    "EvalTelemetry",
    "TELEMETRY_WIDTH",
    "pack_eval_telemetry",
    "CounterRegistry",
    "counters",
    "ensure_compile_counter",
    "SpanTracer",
    "get_tracer",
    "instant",
    "span",
    "start_tracing",
    "stop_tracing",
    "tracing_enabled",
]
