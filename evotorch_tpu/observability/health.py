"""Windowed, variance-aware trend detection over the search-health plane.

The device side of the health plane (``devicemetrics.compute_health_block``)
ships per-group score statistics inside the zero-sync telemetry wire; the
gaussian searchers publish algorithm scalars (``stdev_norm``,
``center_update_norm``, ``clipup_velocity_norm``) as status keys.  This
module turns those *streams* into *verdicts* without ever claiming more
certainty than the data supports: every trend test is gated on a noise
floor estimated from the stream's own residual variance, in the same
spirit as this box's ±20% timing rule (never conclude from single
samples — see CLAUDE.md).

:class:`EWMATrend`
    one scalar stream.  Tracks an EWMA of the per-step deltas plus an EWMA
    of the residual variance around that trend; the trend is "significant"
    only when ``|delta_ewma|`` clears ``noise_scale`` standard errors of
    the delta stream (standard error = ``sqrt(var / eff_n)`` with
    ``eff_n = (2 - alpha) / alpha``, the effective sample size of an
    exponential window).  ``stall_streak`` counts consecutive observations
    (after a 3-delta warmup) whose trend stayed *inside* the noise floor —
    the plateau signal.

:class:`HealthMonitor`
    a keyed collection of detectors plus first-seen baselines, with a
    ``state_dict()`` / ``load_state_dict()`` pair of plain floats so
    checkpoint bundles can carry the window state and resume stays
    bit-identical (examples/locomotion_curve.py does).

The declarative SLO rule kinds built on top (``plateau``,
``stdev_collapse``, ``score_snr_floor``) live in
:mod:`~evotorch_tpu.observability.slo`; see docs/observability.md
"Search health".
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

__all__ = ["EWMATrend", "HealthMonitor"]


#: observations (deltas) required before stall_streak starts counting —
#: below this the variance estimate is meaningless and every verdict
#: would be noise
_WARMUP_DELTAS = 3


class EWMATrend:
    """EWMA slope detector with a residual-variance noise floor.

    ``alpha`` is the EWMA smoothing factor for both the delta trend and
    the residual variance; ``noise_scale`` is the number of standard
    errors the trend must clear to count as significant (3.0 default: a
    deliberately conservative z-gate, because a false "plateau" verdict
    on a noisy-but-progressing run is worse than a late true one).
    """

    def __init__(self, alpha: float = 0.2, noise_scale: float = 3.0):
        alpha = float(alpha)
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.noise_scale = float(noise_scale)
        self.n = 0  # observations seen
        self.value: Optional[float] = None  # last observed value
        self.delta_ewma = 0.0
        self.var_ewma = 0.0
        self.stall_streak = 0

    # ------------------------------------------------------------ properties
    @property
    def eff_n(self) -> float:
        """Effective sample size of the exponential window."""
        return (2.0 - self.alpha) / self.alpha

    @property
    def noise_floor(self) -> float:
        """Minimum |trend| distinguishable from the stream's own noise."""
        return self.noise_scale * math.sqrt(max(self.var_ewma, 0.0) / self.eff_n)

    @property
    def warmed_up(self) -> bool:
        """True once enough deltas accumulated for verdicts to mean anything."""
        return self.n > _WARMUP_DELTAS  # n observations = n - 1 deltas

    @property
    def significant(self) -> bool:
        """True when the current trend clears the noise floor (either
        direction — a significantly *worsening* stream is not a plateau,
        it is a different pathology caught by other rules)."""
        return self.warmed_up and abs(self.delta_ewma) > self.noise_floor

    # ------------------------------------------------------------- observing
    def observe(self, value: float) -> "EWMATrend":
        """Fold one observation in; returns self for chaining."""
        value = float(value)
        if not math.isfinite(value):
            # non-finite samples carry no trend information; they are
            # already quarantined/counted elsewhere (docs/resilience.md)
            return self
        if self.value is not None:
            delta = value - self.value
            residual = delta - self.delta_ewma
            a = self.alpha
            self.delta_ewma += a * residual
            self.var_ewma = (1.0 - a) * (self.var_ewma + a * residual * residual)
        self.value = value
        self.n += 1
        if self.warmed_up:
            if abs(self.delta_ewma) > self.noise_floor:
                self.stall_streak = 0
            else:
                self.stall_streak += 1
        return self

    # --------------------------------------------------------- serialization
    def state_dict(self) -> Dict[str, Any]:
        return {
            "alpha": self.alpha,
            "noise_scale": self.noise_scale,
            "n": self.n,
            "value": self.value,
            "delta_ewma": self.delta_ewma,
            "var_ewma": self.var_ewma,
            "stall_streak": self.stall_streak,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "EWMATrend":
        self.alpha = float(state["alpha"])
        self.noise_scale = float(state["noise_scale"])
        self.n = int(state["n"])
        self.value = None if state["value"] is None else float(state["value"])
        self.delta_ewma = float(state["delta_ewma"])
        self.var_ewma = float(state["var_ewma"])
        self.stall_streak = int(state["stall_streak"])
        return self

    def __repr__(self):
        return (
            f"EWMATrend(n={self.n}, value={self.value}, "
            f"delta_ewma={self.delta_ewma:.4g}, "
            f"noise_floor={self.noise_floor:.4g}, "
            f"stall_streak={self.stall_streak})"
        )


def _key(name: str, group: Optional[int]) -> str:
    # string keys so state_dict round-trips through JSON untouched
    return str(name) if group is None else f"{name}@g{int(group)}"


class HealthMonitor:
    """Keyed :class:`EWMATrend` detectors plus first-seen baselines."""

    def __init__(self, alpha: float = 0.2, noise_scale: float = 3.0):
        self.alpha = float(alpha)
        self.noise_scale = float(noise_scale)
        self._trends: Dict[str, EWMATrend] = {}
        self._baselines: Dict[str, float] = {}

    # ------------------------------------------------------------- observing
    def observe(
        self, name: str, value: float, *, group: Optional[int] = None
    ) -> EWMATrend:
        """Fold one sample into the stream's detector (created on first
        use); also records the first finite sample as the stream's
        baseline (the ``stdev_collapse`` reference point)."""
        key = _key(name, group)
        trend = self._trends.get(key)
        if trend is None:
            trend = self._trends[key] = EWMATrend(self.alpha, self.noise_scale)
        if key not in self._baselines and math.isfinite(float(value)):
            self._baselines[key] = float(value)
        return trend.observe(value)

    def trend(self, name: str, *, group: Optional[int] = None) -> Optional[EWMATrend]:
        return self._trends.get(_key(name, group))

    def baseline(self, name: str, *, group: Optional[int] = None) -> Optional[float]:
        return self._baselines.get(_key(name, group))

    def keys(self) -> Tuple[str, ...]:
        return tuple(sorted(self._trends))

    # --------------------------------------------------------- serialization
    def state_dict(self) -> Dict[str, Any]:
        return {
            "alpha": self.alpha,
            "noise_scale": self.noise_scale,
            "trends": {k: t.state_dict() for k, t in sorted(self._trends.items())},
            "baselines": dict(sorted(self._baselines.items())),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "HealthMonitor":
        self.alpha = float(state.get("alpha", self.alpha))
        self.noise_scale = float(state.get("noise_scale", self.noise_scale))
        self._trends = {
            k: EWMATrend(self.alpha, self.noise_scale).load_state_dict(s)
            for k, s in state.get("trends", {}).items()
        }
        self._baselines = {
            k: float(v) for k, v in state.get("baselines", {}).items()
        }
        return self

    def __repr__(self):
        return f"HealthMonitor(streams={list(self.keys())!r})"
