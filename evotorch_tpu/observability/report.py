"""Program-ledger report CLI.

``python -m evotorch_tpu.observability.report`` captures the registered
program inventory (:mod:`~evotorch_tpu.observability.inventory`) and
prints the per-program accounting table: compile wall-time, cost-model
FLOPs / bytes accessed, analyzed peak memory, the runtime-verified
donation map, and — for the rollout contracts — measured env-steps/s next
to the cost-model ceiling (analytic efficiency).

Modes:

- (default) capture at the fast-tier gate shapes and print the table;
- ``--flagship`` capture at benchmark scale (Humanoid, BENCH_POPSIZE) —
  the ``scripts/tpu_window.sh`` battery step runs this on the real chip
  with ``--json`` so flagship-shape peak HBM + compile seconds are
  snapshotted whenever the tunnel is healthy;
- ``--check`` assert the capture against ``ledger_baseline.json``
  (exit 1 on violations/stale — the CLI form of the tier-1 gate in
  ``tests/test_program_ledger.py``);
- ``--write-baseline`` refresh the checked-in baseline (refuses partial
  captures; run under ``--cpu`` so the values match the pytest mesh).

The cost-model ceiling divides the program's analyzed FLOPs by a nominal
per-backend peak (override with ``EVOTORCH_PEAK_FLOPS``); efficiency is
achieved-FLOPs-rate / peak. On backends without cost analysis the derived
columns degrade to ``-`` instead of failing (the guarded accessors).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

from .inventory import GateConfig, capture_inventory, inventory_keys
from .programs import (
    ProgramLedger,
    compare_to_baseline,
    load_ledger_baseline,
    save_ledger_baseline,
)

#: nominal peak FLOP/s per backend for the analytic-efficiency ceiling —
#: deliberately round figures (a modern host core's SIMD envelope; a
#: single-chip TPU's bf16 MXU envelope). Override with EVOTORCH_PEAK_FLOPS
#: when the real part number is known; the column is a RELATIVE regression
#: metric, not a datasheet claim.
NOMINAL_PEAK_FLOPS = {"cpu": 5.0e10, "tpu": 2.0e14, "axon": 2.0e14}


def peak_flops(platform: str) -> Optional[float]:
    override = os.environ.get("EVOTORCH_PEAK_FLOPS")
    if override:
        return float(override)
    return NOMINAL_PEAK_FLOPS.get(platform)


def _gate_config(args) -> GateConfig:
    from dataclasses import replace

    if args.flagship:
        base = GateConfig(
            env_name="humanoid",
            popsize=int(os.environ.get("BENCH_POPSIZE", 10_000)),
            episode_length=int(os.environ.get("BENCH_EPISODE_LENGTH", 200)),
            hidden=(64, 64),
            chunk_size=25,
        )
    else:
        base = GateConfig()
    overrides = {}
    if args.env is not None:
        overrides["env_name"] = args.env
    if args.popsize is not None:
        overrides["popsize"] = args.popsize
    if args.episode_length is not None:
        overrides["episode_length"] = args.episode_length
    if args.hidden is not None:
        overrides["hidden"] = tuple(int(h) for h in args.hidden.split(",") if h)
    cfg = replace(base, **overrides) if overrides else base
    if args.flagship:
        # width derives from the EFFECTIVE popsize (CLI overrides included)
        # so the refill record's width= label matches the compiled program
        cfg = replace(cfg, refill_width=max(1, cfg.popsize // 8))
    return cfg


def _measure_rollouts(cfg: GateConfig, generations: int = 2) -> dict:
    """Measured env-steps/s per monolithic rollout contract at ``cfg``'s
    shapes (warmup + ``generations`` timed calls; tiny at gate shapes)."""
    import jax
    import jax.numpy as jnp

    from ..neuroevolution.net.runningnorm import RunningNorm
    from ..neuroevolution.net.vecrl import run_vectorized_rollout
    from .inventory import _env_policy

    env, policy = _env_policy(cfg.env_name, cfg.hidden)
    stats = RunningNorm(env.observation_size).stats
    params = jnp.zeros((cfg.popsize, policy.parameter_count), dtype=jnp.float32)
    measured = {}
    for mode, extra in (
        ("budget", {}),
        ("episodes", {}),
        ("episodes_refill", {"refill_width": cfg.refill_width}),
    ):
        def once(key):
            result = run_vectorized_rollout(
                env, policy, params, key, stats,
                num_episodes=1, episode_length=cfg.episode_length,
                eval_mode=mode, **extra,
            )
            jax.block_until_ready(result.scores)
            return int(result.total_steps)

        once(jax.random.key(0))  # warmup: compile outside the clock
        t0 = time.perf_counter()
        steps = 0
        for g in range(generations):
            steps += once(jax.random.key(g + 1))
        elapsed = time.perf_counter() - t0
        measured[f"rollout.{mode}"] = {
            "steps_per_call": steps / generations,
            "steps_per_sec": steps / elapsed,
            "calls_per_sec": generations / elapsed,
        }
    return measured


def _fmt(value, spec="{:g}") -> str:
    return "-" if value is None else spec.format(value)


def _donation_cell(record) -> str:
    if record.donation is None or record.donation.verified is None:
        return "-"
    if record.donation.verified:
        return f"ok({len(record.donation.donated)})"
    return f"DROPPED{list(record.donation.missing)}"


def print_table(records, measured, platform_peak) -> None:
    cols = (
        f"{'program':58s} {'compile_s':>9s} {'flops':>12s} {'bytes_acc':>12s} "
        f"{'peak_bytes':>11s} {'donation':>12s} {'steps/s':>11s} {'efficiency':>10s}"
    )
    print(cols)
    print("-" * len(cols))
    for record in sorted(records, key=lambda r: r.key):
        meas = measured.get(record.name)
        steps_per_sec = None if meas is None else meas["steps_per_sec"]
        efficiency = None
        if (
            meas is not None
            and record.flops is not None
            and platform_peak is not None
        ):
            efficiency = record.flops * meas["calls_per_sec"] / platform_peak
        print(
            f"{record.key:58s} {record.compile_seconds:9.3f} "
            f"{_fmt(record.flops):>12s} {_fmt(record.bytes_accessed):>12s} "
            f"{_fmt(record.peak_bytes):>11s} {_donation_cell(record):>12s} "
            f"{_fmt(steps_per_sec, '{:.1f}'):>11s} "
            f"{_fmt(efficiency, '{:.2%}'):>10s}"
        )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m evotorch_tpu.observability.report",
        description="Program-ledger capture: XLA cost/memory accounting, "
        "donation verification, perf-regression baseline workflow.",
    )
    parser.add_argument("--cpu", action="store_true",
                        help="force the 8-virtual-device CPU backend (use for "
                        "baseline writes: matches the pytest mesh)")
    parser.add_argument("--flagship", action="store_true",
                        help="benchmark-scale shapes (Humanoid, BENCH_POPSIZE)")
    parser.add_argument("--env", default=None)
    parser.add_argument("--popsize", type=int, default=None)
    parser.add_argument("--episode-length", type=int, default=None)
    parser.add_argument("--hidden", default=None, help="comma list, e.g. 64,64")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line instead of the table")
    parser.add_argument("--check", action="store_true",
                        help="assert against ledger_baseline.json; exit 1 on "
                        "violations or stale entries")
    parser.add_argument("--write-baseline", action="store_true",
                        help="refresh ledger_baseline.json (refuses partial runs)")
    parser.add_argument("--baseline", default=None, help="alternate baseline path")
    parser.add_argument("--no-measure", action="store_true",
                        help="skip the timed rollout runs (table loses the "
                        "steps/s and efficiency columns)")
    args = parser.parse_args(argv)

    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    cfg = _gate_config(args)
    led = ProgramLedger()
    expected = inventory_keys(cfg)
    records, errors = capture_inventory(cfg, led, strict=False)
    for key, err in sorted(errors.items()):
        print(f"capture failed: {key}: {err}", file=sys.stderr)

    measure = not args.no_measure and not args.flagship
    measured = _measure_rollouts(cfg) if measure else {}
    platform = records[0].platform if records else jax.devices()[0].platform
    if args.json:
        payload = led.to_json()
        payload["measured"] = measured
        payload["peak_flops"] = peak_flops(platform)
        print(json.dumps(payload))
    else:
        print_table(records, measured, peak_flops(platform))

    rc = 0
    if args.write_baseline:
        path = save_ledger_baseline(
            records, args.baseline, expected_keys=expected
        )
        print(f"wrote {len(records)} programs to {path}", file=sys.stderr)
    if args.check:
        baseline = load_ledger_baseline(args.baseline)
        base_platform = baseline.get("platform")
        if base_platform not in (None, platform):
            print(
                f"warning: baseline platform {base_platform!r} != "
                f"this run's {platform!r} — bands may not be comparable",
                file=sys.stderr,
            )
        violations, stale = compare_to_baseline(records, baseline)
        for message in violations:
            print(f"VIOLATION: {message}", file=sys.stderr)
        for message in stale:
            print(f"STALE: {message}", file=sys.stderr)
        if violations or stale:
            rc = 1
    if errors:
        rc = max(rc, 2)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
