"""Program ledger: XLA cost/memory accounting + donation verification.

PR 8 made the *runtime* visible (on-device counters, span traces, compile
counting); this module is the *program*-level sibling: what does a compiled
entry point cost in FLOPs, how much memory does it pin, and did XLA honor
the ``donate_argnums`` contract the code declares? Everything here is
ahead-of-time introspection over :meth:`jax.stages.Wrapped.lower` /
:meth:`jax.stages.Lowered.compile` — no hot-path interception, no hooks on
dispatch. A capture costs ONE extra trace+compile of the program (lowering
on ``ShapeDtypeStruct``s, so no buffers are touched and donated callers are
safe); steady-state execution is never observed or perturbed.

Pieces:

- :func:`guarded_cost_analysis` / :func:`guarded_memory_analysis` — the
  backend-robust accessors. ``lowered.cost_analysis()`` and
  ``compiled.memory_analysis()`` availability varies by backend and jax
  path (a backend can return ``None``, raise, or list-wrap the dict); these
  normalize to plain dicts and degrade to ``None`` instead of crashing, so
  ledger fields are nullable rather than fatal.
- donation verification — two independent signals for "XLA actually
  aliased the buffers ``donate_argnums`` promised":
  (a) **static**: the compiled module's ENTRY ``input_output_alias`` table
  (parsed from ``compiled.as_text()`` with a balanced-brace scan) checked
  against the donated flat-parameter indices from ``lowered.args_info`` —
  a donated parameter missing from the table is a silently-dropped
  donation, the failure mode graftlint's static ``donation`` checker
  cannot see (it only proves the *request* is present in source);
  (b) **runtime**: :func:`verify_runtime_donation` executes the program
  and asserts the donated input buffers were invalidated
  (``jax.Array.is_deleted``) — jax only deletes inputs whose donation the
  executable consumed, so a dropped donation leaves them alive.
- :class:`ProgramLedger` — the process-wide registry of
  :class:`ProgramRecord`\\ s keyed ``name@shape``; feeds the observability
  counter registry (``peak_hbm_bytes`` max-gauge) so searcher status rows
  pick the figure up for free.
- the baseline workflow — :func:`save_ledger_baseline` /
  :func:`compare_to_baseline` implement the perf-regression gate
  (tolerance bands like ``analysis/baseline.json``'s grandfathering:
  a program whose FLOPs or peak bytes grow past the band fails tier-1;
  one that *shrinks* past the band is a stale entry that must be
  refreshed in the same change). ``ledger_baseline.json`` next to this
  module is the checked-in per-shape baseline
  (``python -m evotorch_tpu.observability.report --cpu --write-baseline``
  refreshes it, refusing partial captures).

See docs/observability.md ("Program ledger") for the field catalog and
bench.py wiring (``BENCH_LEDGER``).
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .registry import counters

__all__ = [
    "DonationReport",
    "abstract_like",
    "ProgramLedger",
    "ProgramRecord",
    "compare_to_baseline",
    "default_ledger_baseline_path",
    "donated_param_indices",
    "guarded_cost_analysis",
    "guarded_memory_analysis",
    "ledger",
    "load_ledger_baseline",
    "parse_alias_sources",
    "save_ledger_baseline",
    "verify_runtime_donation",
]


def abstract_like(tree):
    """``ShapeDtypeStruct`` skeleton of a pytree of arrays: lowering on it
    touches no device buffers, so programs that DONATE their inputs can be
    captured on live state without consuming it."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: (
            jax.ShapeDtypeStruct(x.shape, x.dtype) if hasattr(x, "shape") else x
        ),
        tree,
    )


# ---------------------------------------------------------------------------
# backend-robust introspection
# ---------------------------------------------------------------------------

#: normalized cost fields (XLA's HloCostAnalysis names, spaces and all)
_COST_FIELDS = (
    ("flops", "flops"),
    ("transcendentals", "transcendentals"),
    ("bytes_accessed", "bytes accessed"),
)

#: CompiledMemoryStats attributes worth recording (device side; the host_*
#: twins are 0 everywhere we run)
_MEMORY_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
)


def guarded_cost_analysis(lowered) -> Optional[Dict[str, float]]:
    """``lowered.cost_analysis()`` normalized to
    ``{"flops", "transcendentals", "bytes_accessed"}`` floats, or ``None``
    when the backend path provides no analysis (CPU fallbacks and older
    plugin paths can return ``None``, raise, or wrap the dict in a
    per-partition list — all of those degrade to nullable fields instead
    of crashing the caller)."""
    try:
        cost = lowered.cost_analysis()
    except Exception:  # graftlint: allow(swallow): guarded probe: analysis availability varies by backend, None degrades the column
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    out: Dict[str, float] = {}
    for name, xla_key in _COST_FIELDS:
        value = cost.get(xla_key)
        if isinstance(value, (int, float)) and value >= 0:
            out[name] = float(value)
    return out or None


def guarded_memory_analysis(compiled) -> Optional[Dict[str, int]]:
    """``compiled.memory_analysis()`` normalized to plain int byte fields
    plus the derived ``peak_bytes``, or ``None`` when unavailable.

    ``peak_bytes = argument + output - alias + temp`` — the live-at-once
    footprint of one execution. Donation-aware by construction: an aliased
    (donated) output reuses its argument's buffer, so a DROPPED donation
    shows up as an inflated ``peak_bytes`` — exactly the regression the
    gate exists to catch."""
    try:
        mem = compiled.memory_analysis()
    except Exception:  # graftlint: allow(swallow): guarded probe: analysis availability varies by backend, None degrades the column
        return None
    if mem is None:
        return None
    out: Dict[str, int] = {}
    for name, attr in _MEMORY_FIELDS:
        value = getattr(mem, attr, None)
        if isinstance(value, int) and value >= 0:
            out[name] = value
    if not out:
        return None
    if all(k in out for k in ("argument_bytes", "output_bytes", "temp_bytes")):
        out["peak_bytes"] = (
            out["argument_bytes"]
            + out["output_bytes"]
            - out.get("alias_bytes", 0)
            + out["temp_bytes"]
        )
    return out


# ---------------------------------------------------------------------------
# donation verification
# ---------------------------------------------------------------------------


def donated_param_indices(lowered) -> Optional[List[int]]:
    """Flat ENTRY-parameter indices the lowering marked donated, from
    ``lowered.args_info`` (leaves flatten in parameter order). ``None``
    when the stage doesn't expose the info.

    Caveat: with ``keep_unused=False`` (the jit default) an entirely
    UNUSED argument is pruned from the executable and shifts parameter
    numbering; donated state args are by construction used, so the mapping
    is exact for every program this repo registers."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(lowered.args_info)
    except Exception:  # graftlint: allow(swallow): guarded probe: analysis availability varies by backend, None degrades the column
        return None
    flags = [getattr(leaf, "donated", None) for leaf in leaves]
    if any(flag is None for flag in flags):
        return None
    return [i for i, flag in enumerate(flags) if flag]


def parse_alias_sources(hlo_text: str) -> Optional[List[int]]:
    """Parameter numbers appearing as alias *sources* in the compiled
    module's ENTRY ``input_output_alias`` table, or ``None`` when the
    module declares no table at all (no donation was applied).

    The table syntax nests braces — ``{ {0}: (0, {}, may-alias), ... }`` —
    so the extent is found with a balanced-brace scan, not a regex."""
    anchor = hlo_text.find("input_output_alias=")
    if anchor < 0:
        return None
    start = hlo_text.find("{", anchor)
    if start < 0:
        return None
    depth = 0
    end = -1
    for j in range(start, len(hlo_text)):
        ch = hlo_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                end = j
                break
    if end < 0:
        return None
    body = hlo_text[start : end + 1]
    # each alias entry's source is "(<param_number>, {<param_index>}..."
    return sorted({int(m.group(1)) for m in re.finditer(r"\((\d+)\s*,", body)})


@dataclass(frozen=True)
class DonationReport:
    """The runtime-verified donation map of one compiled program."""

    donated: Tuple[int, ...]  # flat param indices the code donated
    aliased: Tuple[int, ...]  # param indices XLA actually aliased
    missing: Tuple[int, ...]  # donated but NOT aliased — dropped donations

    @property
    def verified(self) -> Optional[bool]:
        """True when every donated parameter was aliased; None when the
        program donates nothing (nothing to verify)."""
        if not self.donated:
            return None
        return not self.missing

    def to_json(self) -> dict:
        return {
            "donated": list(self.donated),
            "aliased": list(self.aliased),
            "missing": list(self.missing),
            "verified": self.verified,
        }


def _donation_report(lowered, compiled) -> Optional[DonationReport]:
    donated = donated_param_indices(lowered)
    if donated is None:
        return None
    try:
        text = compiled.as_text()
    except Exception:  # graftlint: allow(swallow): guarded probe: analysis availability varies by backend, None degrades the column
        return None
    aliased = parse_alias_sources(text)
    aliased = [] if aliased is None else aliased
    missing = [p for p in donated if p not in aliased]
    return DonationReport(
        donated=tuple(donated), aliased=tuple(aliased), missing=tuple(missing)
    )


def verify_runtime_donation(fn, args: Sequence[Any], donate_argnums: Sequence[int]):
    """Execute ``fn(*args)`` and report, per donated argument position,
    whether its buffers were actually invalidated — the runtime ground
    truth of donation (jax deletes exactly the inputs whose donation the
    executable consumed; a dropped donation leaves them alive and warns).

    Returns ``(outputs, {argnum: all_leaves_deleted})``. The caller must
    treat ``args`` at the donated positions as consumed either way."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    report: Dict[int, bool] = {}
    for argnum in donate_argnums:
        leaves = [
            leaf
            for leaf in jax.tree_util.tree_leaves(args[argnum])
            if isinstance(leaf, jax.Array)
        ]
        report[int(argnum)] = bool(leaves) and all(
            leaf.is_deleted() for leaf in leaves
        )
    return out, report


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


@dataclass
class ProgramRecord:
    """Everything the ledger knows about one (program, shape) pair. Nullable
    fields mean "this backend/jax path did not provide the analysis" (the
    guarded accessors above), never "zero"."""

    name: str
    shape: Dict[str, Any] = field(default_factory=dict)
    platform: str = ""
    lower_seconds: float = 0.0
    compile_seconds: float = 0.0
    cost: Optional[Dict[str, float]] = None
    memory: Optional[Dict[str, int]] = None
    donation: Optional[DonationReport] = None

    @property
    def key(self) -> str:
        return program_key(self.name, self.shape)

    @property
    def flops(self) -> Optional[float]:
        return None if self.cost is None else self.cost.get("flops")

    @property
    def bytes_accessed(self) -> Optional[float]:
        return None if self.cost is None else self.cost.get("bytes_accessed")

    @property
    def peak_bytes(self) -> Optional[int]:
        return None if self.memory is None else self.memory.get("peak_bytes")

    def to_json(self) -> dict:
        return {
            "key": self.key,
            "name": self.name,
            "shape": dict(self.shape),
            "platform": self.platform,
            "lower_seconds": round(self.lower_seconds, 4),
            "compile_seconds": round(self.compile_seconds, 4),
            "cost": self.cost,
            "memory": self.memory,
            "donation": None if self.donation is None else self.donation.to_json(),
        }


def program_key(name: str, shape: Dict[str, Any]) -> str:
    """The stable ledger/baseline key: ``name@k1=v1,k2=v2`` with the shape
    dict sorted — human-readable and insensitive to capture order."""
    if not shape:
        return name
    return name + "@" + ",".join(f"{k}={shape[k]}" for k in sorted(shape))


class ProgramLedger:
    """Process-wide registry of captured :class:`ProgramRecord`\\ s.

    :meth:`capture` is the one entry point: AOT-lower the jitted callable
    on the given (abstract or concrete) arguments, compile it, and record
    compile wall-time, cost analysis, memory analysis and the donation
    report. Lowering never executes or consumes buffers, so donated
    programs can be captured on live state safely; pass
    ``jax.ShapeDtypeStruct`` trees to avoid touching device memory at all.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, ProgramRecord] = {}

    def capture(
        self,
        name: str,
        fn,
        *args,
        shape: Optional[Dict[str, Any]] = None,
        **kwargs,
    ) -> ProgramRecord:
        import jax

        shape = dict(shape) if shape else {}
        t0 = time.perf_counter()
        lowered = fn.lower(*args, **kwargs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        # analyses run OUTSIDE the timed windows: compile_seconds is the
        # compile, not the cost-analysis pass over the (possibly huge) module
        cost = guarded_cost_analysis(lowered)
        record = ProgramRecord(
            name=name,
            shape=shape,
            platform=jax.devices()[0].platform,
            lower_seconds=t1 - t0,
            compile_seconds=t2 - t1,
            cost=cost,
            memory=guarded_memory_analysis(compiled),
            donation=_donation_report(lowered, compiled),
        )
        with self._lock:
            self._records[record.key] = record
        counters.increment("ledger_captures")
        if record.peak_bytes is not None:
            counters.observe_max("peak_hbm_bytes", record.peak_bytes)
        counters.accumulate("ledger_compile_seconds", record.compile_seconds)
        return record

    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records.values())

    def get(self, name: str, shape: Optional[Dict[str, Any]] = None) -> Optional[ProgramRecord]:
        with self._lock:
            return self._records.get(program_key(name, shape or {}))

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def to_json(self) -> dict:
        return {"programs": [r.to_json() for r in self.records()]}


#: the process-wide ledger every subsystem feeds
ledger = ProgramLedger()


# ---------------------------------------------------------------------------
# the perf-regression baseline
# ---------------------------------------------------------------------------

#: fields the gate asserts, when both sides have a number
GATED_FIELDS = ("flops", "peak_bytes")

#: the tolerance band: measured within [base*(1-tol), base*(1+tol)] passes;
#: above is a violation, below is a stale entry (refresh required, like
#: graftlint's fixed-findings rule)
DEFAULT_TOLERANCE = 0.15


def default_ledger_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "ledger_baseline.json"


def load_ledger_baseline(path=None) -> dict:
    path = Path(path) if path is not None else default_ledger_baseline_path()
    if not path.exists():
        return {"tolerance": DEFAULT_TOLERANCE, "platform": None, "programs": []}
    with open(path) as f:
        return json.load(f)


def save_ledger_baseline(
    records: Sequence[ProgramRecord],
    path=None,
    *,
    expected_keys: Optional[Sequence[str]] = None,
    tolerance: float = DEFAULT_TOLERANCE,
) -> Path:
    """Write the checked-in baseline from a capture run.

    Refuses partial runs: when ``expected_keys`` (the full inventory) is
    given, every expected program must have been captured AND carry every
    gated field — a baseline written from a half-failed capture would
    silently shrink the gate's coverage."""
    records = list(records)
    by_key = {r.key: r for r in records}
    if expected_keys is not None:
        missing = sorted(set(expected_keys) - set(by_key))
        if missing:
            raise ValueError(
                "refusing to write a partial ledger baseline: programs not "
                f"captured: {missing}"
            )
        incomplete = sorted(
            k
            for k in expected_keys
            if any(_record_field(by_key[k], f) is None for f in GATED_FIELDS)
        )
        if incomplete:
            raise ValueError(
                "refusing to write a partial ledger baseline: programs "
                f"missing gated analysis fields {GATED_FIELDS}: {incomplete}"
            )
    path = Path(path) if path is not None else default_ledger_baseline_path()
    platforms = sorted({r.platform for r in records})
    payload = {
        "tolerance": tolerance,
        "platform": platforms[0] if len(platforms) == 1 else platforms,
        "programs": [
            {
                "key": r.key,
                "flops": r.flops,
                "peak_bytes": r.peak_bytes,
                "bytes_accessed": r.bytes_accessed,
            }
            for r in sorted(records, key=lambda r: r.key)
        ],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def _record_field(record: ProgramRecord, fieldname: str):
    return getattr(record, fieldname)


def compare_to_baseline(
    records: Sequence[ProgramRecord], baseline: dict
) -> Tuple[List[str], List[str]]:
    """The regression gate: returns ``(violations, stale)`` message lists.

    - a captured program absent from the baseline, or a gated field that
      GREW past the tolerance band, is a **violation** (fails tier-1);
    - a baseline entry whose program is no longer captured, or a gated
      field that SHRANK past the band, is **stale** — the improvement must
      refresh the baseline in the same change (mirrors
      ``tests/test_lint.py``'s stale-entry rule), so the gate's bands
      always track reality."""
    tolerance = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    base_by_key = {e["key"]: e for e in baseline.get("programs", [])}
    rec_by_key = {r.key: r for r in records}
    violations: List[str] = []
    stale: List[str] = []
    for key, record in sorted(rec_by_key.items()):
        entry = base_by_key.get(key)
        if entry is None:
            violations.append(
                f"{key}: not in ledger_baseline.json — new program; refresh "
                "the baseline (report --write-baseline)"
            )
            continue
        for fieldname in GATED_FIELDS:
            base_value = entry.get(fieldname)
            if base_value is None:
                continue
            measured = _record_field(record, fieldname)
            if measured is None:
                violations.append(
                    f"{key}: {fieldname} regressed to unavailable "
                    f"(baseline {base_value:g})"
                )
                continue
            if measured > base_value * (1.0 + tolerance):
                violations.append(
                    f"{key}: {fieldname} {measured:g} exceeds baseline "
                    f"{base_value:g} by more than {tolerance:.0%} "
                    f"({measured / base_value - 1.0:+.1%})"
                )
            elif measured < base_value * (1.0 - tolerance):
                stale.append(
                    f"{key}: {fieldname} {measured:g} improved past the "
                    f"{tolerance:.0%} band vs baseline {base_value:g} — "
                    "refresh the baseline (report --write-baseline)"
                )
    for key in sorted(set(base_by_key) - set(rec_by_key)):
        stale.append(f"{key}: baseline entry for a program no longer captured")
    return violations, stale
