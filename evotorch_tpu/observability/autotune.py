"""Occupancy-driven autotuner: close the loop from telemetry to knobs.

PR 8's very first occupancy readout proved the default refill width
mistuned on this box (BENCH_NOTES.md r8: width 128 → occupancy 0.97,
refill_speedup 1.72x, vs 0.83 at the work/8 default); this module is the
loop-closer (ROADMAP item 2, the Podracer discipline of arXiv:2104.06272):
**measured device utilization, not guesses, picks the schedule.**

The loop::

    on-device counters ──► trial harness ──► measured-timing ledger
       (PR 8: occupancy,     (interleaved      (timings.TimingLedger:
        queue_wait,           medians of ≥3)    steps/s + occupancy +
        refill_events)             │            compile_s per machine key)
                                   │                      │
    program ledger ──► analytic pruning            winner persisted
       (PR 9: peak-HBM /   (reject before                 │
        FLOPs bounds)       ever timing)                  ▼
                                            tuned_configs.json ──► consumers
                                              (checked in)    VecNE · GymNE ·
                                                               hostvecenv ·
                                                               parallel.evaluate
                                                               · bench.py

Three layers:

- **The pure search core** — :func:`candidate_grid`,
  :func:`neighborhood`, :func:`analytic_prune`,
  :func:`successive_halving`, :func:`autotune_search`. Deterministic,
  zero wall-clock, no jax: unit-testable against a synthetic measurement
  function (tier-1 does exactly that). Selection is always on **medians**
  (this box times ±20% run to run — CLAUDE.md), with an occupancy floor
  on the winner (a config that starves lanes does not win on a lucky
  run).
- **The trial harnesses** — :class:`RefillHarness` /
  :class:`CompactHarness` (the bespoke-sim device knobs) and
  :class:`HostPipelineHarness` (the host-path knobs). Candidates are
  interleaved in ONE process; every timed call runs under the retrace
  sentinel (a mid-loop compile invalidates the sample and shows up as
  ``steady_compiles``), telemetry is decoded after the clock stops, and
  each trial emits an ``autotune.trial`` tracer span carrying the
  candidate config as span args — a tuning run under ``EVOTORCH_TRACE``
  is inspectable in Perfetto next to the ask/eval/tell spans.
- **The CLI** — ``python -m evotorch_tpu.observability.autotune``:
  tunes the requested knob groups at bench-compatible shapes (the
  ``BENCH_*`` env knobs are honored), records every candidate in the
  measured-timing ledger, and persists each winner to the tuned-config
  cache (:mod:`~evotorch_tpu.observability.timings`) that the eval stack
  consults at setup time. A ``scripts/tpu_window.sh`` battery step runs
  it on the real chip, so a few minutes of healthy tunnel self-tunes the
  flagship shapes.
"""

from __future__ import annotations

import itertools
import math
import time
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from . import tracer
from .timings import (
    TimingLedger,
    TimingRecord,
    TunedEntry,
    _median,
    dtype_label,
    machine_fingerprint,
    timings,
)

__all__ = [
    "CandidateStats",
    "CompactHarness",
    "HostPipelineHarness",
    "KnobGroup",
    "KnobSpec",
    "PolicyHarness",
    "RefillHarness",
    "SearchOutcome",
    "SpanHarness",
    "analytic_prune",
    "autotune_search",
    "candidate_grid",
    "neighborhood",
    "successive_halving",
]


# ---------------------------------------------------------------------------
# the pure search core (no jax, no clocks — tier-1 tests run it synthetically)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KnobSpec:
    """One tunable knob: a name and its ORDERED value grid. ``refine``
    marks knobs whose neighborhood may propose off-grid midpoints (widths
    and chunk sizes are continuous-ish integers; a boolean or enum knob
    sets it False)."""

    name: str
    values: Tuple[Any, ...]
    refine: bool = True


@dataclass(frozen=True)
class KnobGroup:
    """A named set of knobs tuned together (one cache entry per group)."""

    name: str
    knobs: Tuple[KnobSpec, ...]


def candidate_grid(group: KnobGroup) -> List[Dict[str, Any]]:
    """The full cartesian candidate grid, in deterministic knob-major
    order (the order is load-bearing: ties in the search break toward
    earlier candidates, so grids should list preferred defaults first)."""
    names = [k.name for k in group.knobs]
    return [
        dict(zip(names, combo))
        for combo in itertools.product(*(k.values for k in group.knobs))
    ]


def neighborhood(group: KnobGroup, config: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One-knob-at-a-time refinements around ``config``: for each
    refinable integer knob, the (rounded) midpoints between its current
    value and the adjacent grid values. Off-grid by construction —
    candidates already in the grid were already measured — and
    deterministic (no randomness anywhere in the core)."""
    out: List[Dict[str, Any]] = []
    seen = set()
    for knob in group.knobs:
        if not knob.refine:
            continue
        current = config.get(knob.name)
        if not isinstance(current, int):
            continue
        values = sorted(v for v in knob.values if isinstance(v, int))
        if current not in values:
            continue
        i = values.index(current)
        for j in (i - 1, i + 1):
            if not (0 <= j < len(values)):
                continue
            mid = (current + values[j]) // 2
            if mid in values or mid == current or mid <= 0:
                continue
            candidate = dict(config, **{knob.name: mid})
            key = tuple(sorted(candidate.items()))
            if key not in seen:
                seen.add(key)
                out.append(candidate)
    return out


def analytic_prune(
    candidates: Sequence[Dict[str, Any]],
    cost_fn: Optional[Callable[[Dict[str, Any]], Optional[Dict[str, Any]]]],
    *,
    hbm_budget_bytes: Optional[float] = None,
    flops_bound: Optional[float] = None,
) -> Tuple[List[Dict[str, Any]], List[Tuple[Dict[str, Any], str]], Dict[int, Dict]]:
    """Reject candidates on the PR 9 cost model BEFORE any wall-clock is
    spent on them: a candidate whose captured program analyzes over the
    peak-HBM budget or the FLOPs bound never reaches the trial harness.

    ``cost_fn(config)`` returns ``{"peak_bytes", "flops",
    "compile_seconds"}`` (any field nullable) or ``None`` when no
    analysis is available — unknown cost NEVER prunes (the guarded-
    accessor discipline: missing analysis degrades, it doesn't reject).

    Returns ``(kept, pruned, costs)`` where ``pruned`` carries the
    human-readable reason and ``costs`` maps an index INTO ``kept`` (the
    surviving candidates, in order) to its cost dict, so the caller can
    attach ``compile_seconds`` to the matching measurement records."""
    kept: List[Dict[str, Any]] = []
    pruned: List[Tuple[Dict[str, Any], str]] = []
    costs: Dict[int, Dict] = {}
    for config in candidates:
        cost = cost_fn(config) if cost_fn is not None else None
        if cost is not None:
            peak = cost.get("peak_bytes")
            if (
                hbm_budget_bytes is not None
                and peak is not None
                and peak > hbm_budget_bytes
            ):
                pruned.append(
                    (
                        config,
                        f"peak_bytes {peak:.3g} exceeds HBM budget "
                        f"{hbm_budget_bytes:.3g}",
                    )
                )
                continue
            flops = cost.get("flops")
            if flops_bound is not None and flops is not None and flops > flops_bound:
                pruned.append(
                    (config, f"flops {flops:.3g} exceeds bound {flops_bound:.3g}")
                )
                continue
        if cost is not None:
            costs[len(kept)] = cost
        kept.append(config)
    return kept, pruned, costs


@dataclass
class CandidateStats:
    """Accumulated measurement state of one candidate across rounds."""

    config: Dict[str, Any]
    samples: List[float] = field(default_factory=list)
    occupancies: List[float] = field(default_factory=list)
    steady_compiles: int = 0
    refill_events: Optional[int] = None
    queue_wait: Optional[int] = None
    cost: Optional[Dict[str, Any]] = None

    @property
    def steps_per_sec(self) -> float:
        """The headline figure: the MEDIAN of every timed sample."""
        return _median(self.samples)

    @property
    def occupancy(self) -> Optional[float]:
        return _median(self.occupancies) if self.occupancies else None

    def merge(self, measurement: Dict[str, Any]) -> None:
        self.samples.extend(measurement.get("samples", ()))
        self.occupancies.extend(measurement.get("occupancies", ()))
        self.steady_compiles += int(measurement.get("steady_compiles", 0))
        for key in ("refill_events", "queue_wait"):
            value = measurement.get(key)
            if value is not None:
                setattr(self, key, value)


#: measure(configs, trials, round_index) -> one measurement dict per config,
#: each {"samples": [...], "occupancies": [...], "steady_compiles": int, ...}
MeasureFn = Callable[[List[Dict[str, Any]], int, int], List[Dict[str, Any]]]


def successive_halving(
    candidates: Sequence[Dict[str, Any]],
    measure: MeasureFn,
    *,
    trials_per_round: int = 3,
    survivor_frac: float = 0.5,
    min_survivors: int = 2,
    max_rounds: int = 2,
) -> List[CandidateStats]:
    """Successive halving on MEDIANS: every round measures all surviving
    candidates (``trials_per_round`` more samples each — the harness
    interleaves them in one process), then keeps the top
    ``survivor_frac`` by median steps/s. Survivors accumulate samples
    across rounds, so the final ranking rests on the most-measured
    medians. Deterministic: ties break toward the earlier candidate."""
    results = [CandidateStats(config=dict(c)) for c in candidates]
    alive = list(range(len(results)))
    trials = max(1, int(trials_per_round))
    for round_index in range(max(1, int(max_rounds))):
        if not alive:
            break
        measured = measure(
            [results[i].config for i in alive], trials, round_index
        )
        for i, m in zip(alive, measured):
            results[i].merge(m)
        if len(alive) <= min_survivors:
            break
        ranked = sorted(alive, key=lambda i: (-results[i].steps_per_sec, i))
        keep = max(min_survivors, math.ceil(len(alive) * survivor_frac))
        alive = sorted(ranked[:keep])
    return results


def select_winner(
    results: Sequence[CandidateStats],
    *,
    min_occupancy: Optional[float] = None,
    tolerance: Optional[float] = None,
    prefer: Optional[Callable[[Dict[str, Any]], Any]] = None,
) -> Optional[CandidateStats]:
    """Highest median steps/s among measured candidates meeting the
    occupancy floor — falling back to the unconstrained winner when none
    do (a floor must never select nothing). Candidates that paid a
    steady-state compile mid-trial are untrustworthy timings and lose to
    any clean candidate.

    ``tolerance`` + ``prefer`` select on a SECONDARY objective inside a
    throughput band: among candidates whose median steps/s is within
    ``tolerance`` (a fraction) of the best, the one maximizing
    ``prefer(config)`` wins, with throughput breaking preference ties.
    The policy group uses this — expressivity (rank) is worth a bounded
    throughput haircut, so the highest rank within the band wins rather
    than the outright-fastest rank-4 corner."""
    measured = [r for r in results if r.samples]
    if not measured:
        return None
    clean = [r for r in measured if r.steady_compiles == 0]
    pool = clean or measured
    if min_occupancy is not None:
        eligible = [
            r for r in pool if r.occupancy is not None and r.occupancy >= min_occupancy
        ]
        if eligible:
            pool = eligible
    best = max(pool, key=lambda r: r.steps_per_sec)
    if tolerance is None or prefer is None:
        return best
    floor = best.steps_per_sec * (1.0 - float(tolerance))
    near = [r for r in pool if r.steps_per_sec >= floor]
    return max(near, key=lambda r: (prefer(r.config), r.steps_per_sec))


@dataclass
class SearchOutcome:
    """Everything one group's search produced: ranked candidate stats
    (grid + refinement), the analytically-pruned configs with reasons,
    and the selected winner. ``cache_written`` is stamped by
    :func:`tune_group`: False when the winner was withheld from the cache
    (retrace-dirty timing, occupancy floor not met, or ``write_cache``
    off)."""

    results: List[CandidateStats]
    pruned: List[Tuple[Dict[str, Any], str]]
    winner: Optional[CandidateStats]
    cache_written: bool = False


def autotune_search(
    group: KnobGroup,
    measure: MeasureFn,
    *,
    cost_fn: Optional[Callable[[Dict[str, Any]], Optional[Dict[str, Any]]]] = None,
    hbm_budget_bytes: Optional[float] = None,
    flops_bound: Optional[float] = None,
    trials_per_round: int = 3,
    survivor_frac: float = 0.5,
    min_survivors: int = 2,
    max_rounds: int = 2,
    min_occupancy: Optional[float] = None,
    tolerance: Optional[float] = None,
    prefer: Optional[Callable[[Dict[str, Any]], Any]] = None,
    refine: bool = True,
) -> SearchOutcome:
    """The full (pure) search: grid → analytic prune → successive
    halving → winner → one neighborhood-refinement round around the
    winner (off-grid midpoints, themselves prune-checked) → final
    winner. ``measure``/``cost_fn`` carry all the impurity; everything
    here is deterministic given their outputs. ``tolerance``/``prefer``
    pass through to :func:`select_winner` (secondary-objective
    selection inside a throughput band)."""
    grid = candidate_grid(group)
    kept, pruned, costs = analytic_prune(
        grid, cost_fn, hbm_budget_bytes=hbm_budget_bytes, flops_bound=flops_bound
    )
    results = successive_halving(
        kept,
        measure,
        trials_per_round=trials_per_round,
        survivor_frac=survivor_frac,
        min_survivors=min_survivors,
        max_rounds=max_rounds,
    )
    for index, cost in costs.items():
        results[index].cost = cost
    winner = select_winner(
        results, min_occupancy=min_occupancy, tolerance=tolerance, prefer=prefer
    )
    if refine and winner is not None:
        measured_keys = {tuple(sorted(r.config.items())) for r in results}
        fresh = [
            c
            for c in neighborhood(group, winner.config)
            if tuple(sorted(c.items())) not in measured_keys
        ]
        kept2, pruned2, costs2 = analytic_prune(
            fresh,
            cost_fn,
            hbm_budget_bytes=hbm_budget_bytes,
            flops_bound=flops_bound,
        )
        pruned.extend(pruned2)
        if kept2:
            refined = successive_halving(
                kept2,
                measure,
                trials_per_round=trials_per_round,
                survivor_frac=1.0,  # no halving inside one refinement round
                min_survivors=len(kept2),
                max_rounds=1,
            )
            for index, cost in costs2.items():
                refined[index].cost = cost
            results = results + refined
            winner = select_winner(
                results,
                min_occupancy=min_occupancy,
                tolerance=tolerance,
                prefer=prefer,
            )
    return SearchOutcome(results=results, pruned=pruned, winner=winner)


# ---------------------------------------------------------------------------
# trial harnesses (the impure half: jax programs, clocks, telemetry)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TuneShape:
    """The workload shape a tuning run measures at (bench-compatible)."""

    env_name: str = "humanoid"
    popsize: int = 1024
    episode_length: int = 100
    hidden: Tuple[int, ...] = (64, 64)
    compute_dtype: Any = None  # e.g. jnp.bfloat16; None = float32
    num_episodes: int = 1

    def as_dict(self) -> Dict[str, Any]:
        return {
            "env": self.env_name,
            "popsize": self.popsize,
            "episode_length": self.episode_length,
        }


class _BespokeHarness:
    """Shared scaffolding of the bespoke-sim (device-program) harnesses:
    one env/policy/population built once, per-call PRNG keys derived by
    ``fold_in`` from a base key (never reused), interleaved timed trials
    under the retrace sentinel, telemetry decoded after the clock stops,
    and an ``autotune.trial`` tracer span per timed call."""

    group = ""  # knob-group / cache-entry name
    program = ""  # timing-ledger program name
    #: per-group winner floor (subclasses override; None = throughput only)
    default_min_occupancy: Optional[float] = None
    #: secondary-objective selection (select_winner's tolerance/prefer):
    #: None on throughput-only groups; the policy group trades a bounded
    #: throughput haircut for rank
    winner_tolerance: Optional[float] = None
    winner_prefer: Optional[Callable[[Dict[str, Any]], Any]] = None

    def __init__(self, shape: TuneShape, *, seed: int = 0):
        import jax
        from functools import partial

        from ..algorithms.functional import pgpe, pgpe_ask
        from ..envs import make_env
        from ..neuroevolution.net import FlatParamsPolicy, tanh_mlp
        from ..neuroevolution.net.runningnorm import RunningNorm

        self.shape = shape
        self.env = make_env(shape.env_name)
        self.policy = FlatParamsPolicy(
            tanh_mlp(self.env.observation_size, self.env.action_size, shape.hidden)
        )
        import jax.numpy as jnp

        state = pgpe(
            center_init=jnp.zeros(self.policy.parameter_count, dtype=jnp.float32),
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            objective_sense="max",
            stdev_init=0.1,
        )
        # one fixed population for every candidate and trial: candidates
        # compete on the SAME work list, so schedule quality is the only
        # difference being measured
        ask = jax.jit(partial(pgpe_ask, popsize=shape.popsize))
        self.values = ask(jax.random.key(seed), state)
        jax.block_until_ready(self.values)
        self.stats = RunningNorm(self.env.observation_size).stats
        self._base_key = jax.random.key(seed + 1)
        self._nonce = itertools.count()
        self._episodes_baseline: Optional[Dict[str, Any]] = None
        self._warmed_configs: set = set()

    # -- per-candidate program runners (overridden) -------------------------
    def run_once(self, config: Dict[str, Any], key, *, warmup: bool = False):
        raise NotImplementedError

    def tuned_config(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Map harness knob names to the cache entry's config keys."""
        return dict(config)

    def default_config(self) -> Optional[Dict[str, Any]]:
        """The built-in-default candidate — the anchor the relative HBM
        budget is derived from (the default is definitionally feasible)."""
        return None

    def knob_group(self) -> KnobGroup:
        raise NotImplementedError

    def cost(self, config: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        return None

    # -- the shared measurement machinery -----------------------------------
    def _next_key(self):
        import jax

        # fold_in with a fresh nonce per timed call: unique per-call keys,
        # no key ever consumed twice (the graftlint prng discipline)
        return jax.random.fold_in(self._base_key, next(self._nonce))

    def _timed_call(self, label: str, config: Dict[str, Any], runner):
        """One timed trial: sentinel around the call, clock stopped at
        ``block_until_ready``, telemetry decoded afterwards. Returns
        ``(steps_per_sec, telemetry, compiles)``."""
        import jax

        from ..analysis import track_compiles
        from . import EvalTelemetry

        key = self._next_key()
        with tracer.span("autotune.trial", "autotune", group=label, **config):
            with track_compiles() as compile_log:
                t0 = time.perf_counter()
                result = runner(key)
                jax.block_until_ready(result.scores)
                elapsed = time.perf_counter() - t0
        steps = int(result.total_steps)
        telemetry = (
            EvalTelemetry.from_array(result.telemetry)
            if result.telemetry is not None
            else None
        )
        return steps / elapsed if elapsed > 0 else 0.0, telemetry, compile_log.count

    def measure(
        self, configs: List[Dict[str, Any]], trials: int, round_index: int
    ) -> List[Dict[str, Any]]:
        """The real MeasureFn: warm every candidate once (compiles land
        outside every clock), then interleave candidates within each
        trial sweep — the CLAUDE.md ±20% rule — so drift hits all
        candidates alike."""
        for config in configs:
            # warm once per candidate PER SEARCH (not per round): a warmup
            # is a full untimed evaluation, and survivors of round 0 are
            # already compiled
            warm_key = tuple(sorted(config.items()))
            if warm_key in self._warmed_configs:
                continue
            self._warmed_configs.add(warm_key)
            self.run_once(config, self._next_key(), warmup=True)
        out = [
            {"samples": [], "occupancies": [], "steady_compiles": 0}
            for _ in configs
        ]
        for _ in range(trials):
            for i, config in enumerate(configs):
                sps, telemetry, compiles = self._timed_call(
                    self.group, config, lambda key, c=config: self.run_once(c, key)
                )
                out[i]["samples"].append(sps)
                out[i]["steady_compiles"] += compiles
                if telemetry is not None:
                    out[i]["occupancies"].append(telemetry.occupancy)
                    out[i]["refill_events"] = telemetry.refill_events
                    out[i]["queue_wait"] = telemetry.queue_wait
        return out

    def baseline(self, trials: int = 3) -> Dict[str, Any]:
        """Median steps/s of the monolithic ``episodes`` contract at the
        same shape — the denominator of ``refill_speedup`` /
        ``compaction_speedup`` (measured in the same process, same
        population)."""
        if self._episodes_baseline is not None:
            return self._episodes_baseline
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        def runner(key):
            return run_vectorized_rollout(
                self.env,
                self.policy,
                self.values,
                key,
                self.stats,
                eval_mode="episodes",
                num_episodes=self.shape.num_episodes,
                episode_length=self.shape.episode_length,
                compute_dtype=self.shape.compute_dtype,
            )

        import jax

        jax.block_until_ready(runner(self._next_key()).scores)  # warmup
        samples, occupancies = [], []
        for _ in range(max(1, trials)):
            sps, telemetry, _ = self._timed_call(
                "episodes", {"contract": "episodes"}, runner
            )
            samples.append(sps)
            if telemetry is not None:
                occupancies.append(telemetry.occupancy)
        self._episodes_baseline = {
            "steps_per_sec": _median(samples),
            "occupancy": _median(occupancies) if occupancies else None,
            "samples": samples,
        }
        return self._episodes_baseline


def _pow2_menu(values, lo: int, hi: int) -> Tuple[int, ...]:
    return tuple(sorted({int(v) for v in values if lo <= int(v) <= hi}))


class RefillHarness(_BespokeHarness):
    """Tunes the ``episodes_refill`` scheduler: lane width + refill
    period. The width menu brackets the engine's work/8 default with the
    fixed 64..512 rungs the r8 sweep used, so the search always measures
    the default it might replace."""

    group = "refill"
    program = "rollout.episodes_refill"
    #: the r8/acceptance bar: a refill schedule that starves lanes must
    #: not win on a lucky throughput run
    default_min_occupancy: Optional[float] = 0.9

    def __init__(
        self,
        shape: TuneShape,
        *,
        widths: Optional[Sequence[int]] = None,
        periods: Sequence[int] = (1,),
        seed: int = 0,
    ):
        super().__init__(shape, seed=seed)
        from ..neuroevolution.net.vecrl import _default_refill_width

        total_items = shape.popsize * shape.num_episodes
        if widths is None:
            base = _default_refill_width(total_items)
            widths = _pow2_menu(
                (64, 128, 256, 512, base // 2, base, base * 2),
                lo=8,
                hi=total_items,
            )
        self.widths = tuple(int(w) for w in widths)
        if not self.widths:
            raise ValueError(
                f"empty refill width menu for work-list size {total_items} "
                "(the default rungs all fall outside [8, work]); pass "
                "--widths explicitly"
            )
        self.periods = tuple(int(p) for p in periods)
        self._default_width = min(
            _default_refill_width(total_items), max(self.widths)
        )

    def default_config(self):
        return {
            "refill_width": self._default_width,
            "refill_period": self.periods[0],
        }

    def knob_group(self) -> KnobGroup:
        return KnobGroup(
            name=self.group,
            knobs=(
                KnobSpec("refill_width", self.widths),
                KnobSpec("refill_period", self.periods, refine=False),
            ),
        )

    def run_once(self, config, key, *, warmup: bool = False):
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        result = run_vectorized_rollout(
            self.env,
            self.policy,
            self.values,
            key,
            self.stats,
            eval_mode="episodes_refill",
            refill_width=int(config["refill_width"]),
            refill_period=int(config.get("refill_period", 1)),
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )
        if warmup:
            import jax

            jax.block_until_ready(result.scores)
        return result

    def cost(self, config):
        """PR 9 analytic cost of the candidate's compiled program (one
        AOT capture — outside every timed region; the compile_seconds
        figure lands in the timing record)."""
        import jax

        from .programs import ProgramLedger
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        led = ProgramLedger()
        record = led.capture(
            self.program,
            run_vectorized_rollout,
            self.env,
            self.policy,
            jax.ShapeDtypeStruct(self.values.shape, self.values.dtype),
            jax.random.key(0),
            self.stats,
            shape=dict(self.shape.as_dict(), **config),
            eval_mode="episodes_refill",
            refill_width=int(config["refill_width"]),
            refill_period=int(config.get("refill_period", 1)),
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )
        return {
            "peak_bytes": record.peak_bytes,
            "flops": record.flops,
            "compile_seconds": record.compile_seconds,
        }

    def tuned_config(self, config):
        return {
            "width": int(config["refill_width"]),
            "period": int(config.get("refill_period", 1)),
        }


class CompactHarness(_BespokeHarness):
    """Tunes the lane-compacting runner: host chunk size × width-menu
    floor (the grid ``scripts/tune_compact.py`` used to sweep — absorbed
    here as one knob group)."""

    group = "compact"
    program = "rollout.episodes_compact"
    #: compaction STRUCTURALLY runs below full occupancy (~0.5 at the
    #: bench shapes — r8/r11 measurements): the contract pads each chunk
    #: to its slowest survivor by design, so a refill-style 0.9 floor
    #: would make every winner unpersistable. Select on throughput, the
    #: original tune_compact criterion.
    default_min_occupancy: Optional[float] = None

    def __init__(
        self,
        shape: TuneShape,
        *,
        chunks: Sequence[int] = (10, 25, 50),
        min_widths: Sequence[int] = (128, 256, 512),
        seed: int = 0,
    ):
        super().__init__(shape, seed=seed)
        total = shape.popsize * shape.num_episodes
        self.chunks = tuple(int(c) for c in chunks)
        self.min_widths = tuple(w for w in (int(w) for w in min_widths) if w < total)
        if not self.min_widths:
            raise ValueError(
                f"no min_width candidate below the work-list size {total}; "
                "pass --min-widths values smaller than popsize*num_episodes"
            )

    def default_config(self):
        chunk = 25 if 25 in self.chunks else self.chunks[0]
        width = 256 if 256 in self.min_widths else self.min_widths[0]
        return {"chunk_size": chunk, "min_width": width}

    def knob_group(self) -> KnobGroup:
        return KnobGroup(
            name=self.group,
            knobs=(
                KnobSpec("chunk_size", self.chunks),
                KnobSpec("min_width", self.min_widths),
            ),
        )

    def run_once(self, config, key, *, warmup: bool = False):
        from ..neuroevolution.net.vecrl import run_vectorized_rollout_compacting

        # the warmup call (one per candidate — the base class dedups) runs
        # prewarm=True, compiling the candidate's whole width-descent chain
        # (the chunk step count is static in the jitted chunk program), so
        # timed calls stay compile-free
        result = run_vectorized_rollout_compacting(
            self.env,
            self.policy,
            self.values,
            key,
            self.stats,
            chunk_size=int(config["chunk_size"]),
            min_width=int(config["min_width"]),
            prewarm=warmup,
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )
        if warmup:
            import jax

            jax.block_until_ready(result.scores)
        return result

    def cost(self, config):
        """Cost of the full-width chunk program — the dominant compiled
        unit of the host-orchestrated contract (the width descent reruns
        the same program at narrower shapes)."""
        from .inventory import capture_compact_chunk
        from .programs import ProgramLedger

        led = ProgramLedger()
        record = capture_compact_chunk(
            led,
            self.env,
            self.policy,
            self.shape.popsize,
            self.shape.episode_length,
            chunk_size=int(config["chunk_size"]),
            compute_dtype=self.shape.compute_dtype,
            name=self.program + ".chunk",
            shape=dict(self.shape.as_dict(), **config),
        )
        return {
            "peak_bytes": record.peak_bytes,
            "flops": record.flops,
            "compile_seconds": record.compile_seconds,
        }

    def tuned_config(self, config):
        return {
            "chunk_size": int(config["chunk_size"]),
            "min_width": int(config["min_width"]),
        }


class PolicyHarness(_BespokeHarness):
    """Tunes the trunk-delta POLICY FORM knobs: delta rank × lane-block
    size (docs/policies.md). Unlike the schedule groups, each rank
    candidate evaluates its OWN factored population (same trunk, same
    base PRNG key) — rank changes the program being measured, not just
    its schedule — so the harness keeps one ``TrunkDeltaParamsBatch``
    per rank, built once. Selection is throughput-within-tolerance with
    rank as the preference: a higher rank buys expressivity (more
    sampling subspace per generation — the subspace-exhaustion guardrail
    bites later), so the HIGHEST rank within ``winner_tolerance`` of the
    fastest candidate wins rather than the outright-fastest low-rank
    corner."""

    group = "policy"
    program = "rollout.budget.trunk_delta"
    #: the budget contract keeps every lane active; throughput selection
    default_min_occupancy: Optional[float] = None
    #: the rank-preference band: a candidate within 10% of the fastest
    #: median is "as fast" on this box's ±20% timing noise
    winner_tolerance: Optional[float] = 0.1
    winner_prefer = staticmethod(lambda config: int(config.get("rank", 0)))

    def __init__(
        self,
        shape: TuneShape,
        *,
        ranks: Sequence[int] = (4, 16, 64),
        trunk_blocks: Sequence[int] = (0,),
        seed: int = 0,
    ):
        super().__init__(shape, seed=seed)
        self.ranks = tuple(sorted({int(r) for r in ranks if int(r) > 0}))
        if not self.ranks:
            raise ValueError("empty rank menu; pass --ranks with positive ints")
        # the blocked lane path requires popsize % block == 0 (vecrl's
        # trunk_block contract); 0 = unblocked is always valid
        self.trunk_blocks = tuple(
            sorted(
                {
                    int(b)
                    for b in trunk_blocks
                    if int(b) == 0
                    or (0 < int(b) < shape.popsize and shape.popsize % int(b) == 0)
                }
            )
        )
        if not self.trunk_blocks:
            self.trunk_blocks = (0,)
        self._rank_batches: Dict[int, Any] = {}
        self._seed = int(seed)

    def _params_for(self, rank: int):
        """The rank's trunk-delta population, built once per search: every
        candidate at this rank (and every trial) times the SAME batch."""
        rank = int(rank)
        if rank not in self._rank_batches:
            import jax
            import jax.numpy as jnp

            from ..algorithms.functional import pgpe, pgpe_ask_trunk_delta

            state = pgpe(
                center_init=jnp.zeros(
                    self.policy.parameter_count, dtype=jnp.float32
                ),
                center_learning_rate=0.1,
                stdev_learning_rate=0.1,
                objective_sense="max",
                stdev_init=0.1,
            )
            batch = pgpe_ask_trunk_delta(
                jax.random.key(self._seed),
                state,
                popsize=self.shape.popsize,
                rank=rank,
                policy=self.policy,
            )
            jax.block_until_ready(batch.coeffs)
            self._rank_batches[rank] = batch
        return self._rank_batches[rank]

    def default_config(self):
        return {"rank": self.ranks[0], "trunk_block": 0}

    def knob_group(self) -> KnobGroup:
        return KnobGroup(
            name=self.group,
            knobs=(
                # menu-only knobs: a refined off-grid rank would need a
                # fresh population + compile per midpoint, and block sizes
                # off the divisor menu violate the popsize % block contract
                KnobSpec("rank", self.ranks, refine=False),
                KnobSpec("trunk_block", self.trunk_blocks, refine=False),
            ),
        )

    def run_once(self, config, key, *, warmup: bool = False):
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        result = run_vectorized_rollout(
            self.env,
            self.policy,
            self._params_for(config["rank"]),
            key,
            self.stats,
            eval_mode="budget",
            trunk_block=int(config.get("trunk_block", 0)),
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )
        if warmup:
            import jax

            jax.block_until_ready(result.scores)
        return result

    def cost(self, config):
        """Analytic cost of the candidate's trunk-delta budget program
        (one AOT capture, outside every timed region)."""
        import jax

        from .programs import ProgramLedger, abstract_like
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        led = ProgramLedger()
        record = led.capture(
            self.program,
            run_vectorized_rollout,
            self.env,
            self.policy,
            abstract_like(self._params_for(config["rank"])),
            jax.random.key(0),
            self.stats,
            shape=dict(self.shape.as_dict(), **config),
            eval_mode="budget",
            trunk_block=int(config.get("trunk_block", 0)),
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )
        return {
            "peak_bytes": record.peak_bytes,
            "flops": record.flops,
            "compile_seconds": record.compile_seconds,
        }

    def baseline(self, trials: int = 3) -> Dict[str, Any]:
        """Median steps/s of the DENSE budget contract at the same shape —
        the policy group's speedup denominator is dense-vs-trunk-delta at
        the same contract, not a contract A/B."""
        if self._episodes_baseline is not None:
            return self._episodes_baseline
        from ..neuroevolution.net.vecrl import run_vectorized_rollout

        def runner(key):
            return run_vectorized_rollout(
                self.env,
                self.policy,
                self.values,
                key,
                self.stats,
                eval_mode="budget",
                num_episodes=self.shape.num_episodes,
                episode_length=self.shape.episode_length,
                compute_dtype=self.shape.compute_dtype,
            )

        import jax

        jax.block_until_ready(runner(self._next_key()).scores)  # warmup
        samples, occupancies = [], []
        for _ in range(max(1, trials)):
            sps, telemetry, _ = self._timed_call(
                "budget_dense", {"contract": "budget_dense"}, runner
            )
            samples.append(sps)
            if telemetry is not None:
                occupancies.append(telemetry.occupancy)
        self._episodes_baseline = {
            "steps_per_sec": _median(samples),
            "occupancy": _median(occupancies) if occupancies else None,
            "samples": samples,
        }
        return self._episodes_baseline

    def tuned_config(self, config):
        return {
            "rank": int(config["rank"]),
            "trunk_block": int(config.get("trunk_block", 0)),
        }


class SpanHarness(_BespokeHarness):
    """Tunes the fused-span length K (``parallel.make_training_span``):
    how many generations one donated device program scans before the
    host fetches results. Each K candidate is its OWN compiled program
    (lax.scan length is a static shape), so the span knob is menu-only —
    an off-grid midpoint would buy nothing but another compile. Every
    candidate keeps a persistent (state, stats) pair rebound after each
    call — the programs donate their search state, exactly like the
    consumers — and the budget contract keeps the per-generation work
    identical across trials, so steps/sec is the only moving part. The
    baseline is the SAME generation body dispatched from the host loop
    (``make_generation_step``, same mesh), making
    ``speedup_vs_baseline`` the span_speedup of docs/sharding.md."""

    group = "span"
    program = "gspmd.training_span"
    #: the budget contract keeps every lane active; throughput selection
    default_min_occupancy: Optional[float] = None

    def __init__(
        self,
        shape: TuneShape,
        *,
        spans: Sequence[int] = (1, 2, 4, 8, 16),
        seed: int = 0,
    ):
        super().__init__(shape, seed=seed)
        self.spans = tuple(sorted({int(s) for s in spans if int(s) >= 1}))
        if not self.spans:
            raise ValueError("empty span menu; pass --spans with ints >= 1")
        from ..parallel import default_mesh

        self._mesh = default_mesh(("pop",))
        self._programs: Dict[int, Any] = {}
        self._span_state: Dict[int, Any] = {}
        self._baseline_step = None
        self._baseline_state = None
        self._seed = int(seed)

    # -- program/state builders --------------------------------------------
    def _ask_tell(self):
        from functools import partial

        from ..algorithms.functional import pgpe_ask, pgpe_tell

        return partial(pgpe_ask, popsize=self.shape.popsize), pgpe_tell

    def _fresh_state(self):
        import jax.numpy as jnp

        from ..algorithms.functional import pgpe
        from ..neuroevolution.net.runningnorm import RunningNorm

        state = pgpe(
            center_init=jnp.zeros(
                self.policy.parameter_count, dtype=jnp.float32
            ),
            center_learning_rate=0.1,
            stdev_learning_rate=0.1,
            objective_sense="max",
            stdev_init=0.1,
        )
        return state, RunningNorm(self.env.observation_size).stats

    def _rollout_kwargs(self):
        return dict(
            eval_mode="budget",
            num_episodes=self.shape.num_episodes,
            episode_length=self.shape.episode_length,
            compute_dtype=self.shape.compute_dtype,
        )

    def _program_for(self, span: int):
        span = int(span)
        if span not in self._programs:
            from ..parallel import make_training_span

            ask, tell = self._ask_tell()
            self._programs[span] = make_training_span(
                self.env,
                self.policy,
                ask=ask,
                tell=tell,
                popsize=self.shape.popsize,
                span=span,
                mesh=self._mesh,
                **self._rollout_kwargs(),
            )
            self._span_state[span] = self._fresh_state()
        return self._programs[span]

    def default_config(self):
        return {"span": self.spans[0]}

    def knob_group(self) -> KnobGroup:
        return KnobGroup(
            name=self.group,
            # menu-only: each span length is a distinct compiled program
            knobs=(KnobSpec("span", self.spans, refine=False),),
        )

    def run_once(self, config, key, *, warmup: bool = False):
        import types

        import jax

        span = int(config["span"])
        fn = self._program_for(span)

        def call(k):
            state, stats = self._span_state[span]
            new_state, scores, new_stats, steps, _ = fn(
                state, jax.random.split(k, span), stats
            )
            self._span_state[span] = (new_state, new_stats)
            return scores, steps

        scores, steps = call(key)
        if warmup:
            # donated GSPMD programs reach the steady-state layout on the
            # SECOND call — run one more untimed so no compile can land
            # inside a timed trial (the bench A/B warms the same way)
            jax.block_until_ready(scores)
            scores, steps = call(self._next_key())
            jax.block_until_ready(scores)
        return types.SimpleNamespace(
            scores=scores, total_steps=steps.sum(), telemetry=None
        )

    def cost(self, config):
        """Analytic cost of the candidate's fused-span program (one AOT
        capture, outside every timed region) — the ISSUE's compile-time
        cost surface for long spans, plus the peak-HBM prune input."""
        import jax

        from .programs import ProgramLedger, abstract_like

        span = int(config["span"])
        from ..parallel import make_training_span

        ask, tell = self._ask_tell()
        fn = make_training_span(
            self.env,
            self.policy,
            ask=ask,
            tell=tell,
            popsize=self.shape.popsize,
            span=span,
            mesh=self._mesh,
            donate_state=False,  # AOT analysis only; nothing is consumed
            **self._rollout_kwargs(),
        )
        state, stats = self._fresh_state()
        led = ProgramLedger()
        record = led.capture(
            self.program,
            fn,
            abstract_like(state),
            jax.random.split(jax.random.key(0), span),
            abstract_like(stats),
            shape=dict(self.shape.as_dict(), span=span),
        )
        return {
            "peak_bytes": record.peak_bytes,
            "flops": record.flops,
            "compile_seconds": record.compile_seconds,
        }

    def baseline(self, trials: int = 3) -> Dict[str, Any]:
        """Median steps/s of the host loop: the SAME generation body
        (``make_generation_step``, same mesh, same contract) dispatched
        ``max(spans)`` times per sample from the host — the denominator
        that makes ``speedup_vs_baseline`` the span A/B headline."""
        if self._episodes_baseline is not None:
            return self._episodes_baseline
        import jax

        from ..parallel import make_generation_step

        if self._baseline_step is None:
            ask, tell = self._ask_tell()
            self._baseline_step = make_generation_step(
                self.env,
                self.policy,
                ask=ask,
                tell=tell,
                popsize=self.shape.popsize,
                mesh=self._mesh,
                **self._rollout_kwargs(),
            )
            self._baseline_state = self._fresh_state()
        gens = max(self.spans)

        def runner(key):
            import types

            state, stats = self._baseline_state
            steps_total = 0
            scores = None
            for g in range(gens):
                state, scores, stats, steps, _ = self._baseline_step(
                    state, jax.random.fold_in(key, g), stats
                )
                steps_total += int(steps)
            self._baseline_state = (state, stats)
            return types.SimpleNamespace(
                scores=scores, total_steps=steps_total, telemetry=None
            )

        # two untimed warmups: fresh layout, then steady-state donated layout
        jax.block_until_ready(runner(self._next_key()).scores)
        jax.block_until_ready(runner(self._next_key()).scores)
        samples = []
        for _ in range(max(1, trials)):
            sps, _, _ = self._timed_call(
                "span_hostloop", {"contract": "hostloop"}, runner
            )
            samples.append(sps)
        self._episodes_baseline = {
            "steps_per_sec": _median(samples),
            "occupancy": None,
            "samples": samples,
        }
        return self._episodes_baseline

    def tuned_config(self, config):
        return {"span": int(config["span"])}


class HostPipelineHarness:
    """Tunes the HOST-path knobs: the pipelined scheduler's lane-block
    count and (for MuJoCo backends) the physics thread-pool width. These
    are machine properties — "2 blocks when a second core exists" is the
    heuristic being replaced by a measured fact — so the cache entry is
    machine-scoped (shape ``{}``), and every `GymNE`/host-pipeline run on
    this machine inherits it."""

    group = "host_pipeline"
    program = "host_pipeline.rollout"
    #: host-path occupancy has no device-starvation meaning comparable to
    #: the refill contract's; select on throughput (no floor by default)
    default_min_occupancy: Optional[float] = None

    def __init__(
        self,
        env_id: Optional[str] = None,
        *,
        popsize: int = 64,
        num_envs: int = 16,
        episode_length: int = 200,
        hidden: Tuple[int, ...] = (64, 64),
        seed: int = 0,
    ):
        import gymnasium as gym
        import numpy as np

        from ..neuroevolution.net import FlatParamsPolicy, tanh_mlp

        if env_id is None:
            try:
                from ..envs.mujoco.mjvecenv import MjVecEnv  # noqa: F401

                env_id = "Hopper-v5"
            except ImportError:
                env_id = "CartPole-v1"
        self.env_id = env_id
        self.popsize = int(popsize)
        self.num_envs = int(num_envs)
        self.episode_length = int(episode_length)
        probe = gym.make(env_id)
        obs_dim = int(np.prod(probe.observation_space.shape))
        act_space = probe.action_space
        act_dim = (
            int(act_space.n)
            if hasattr(act_space, "n")
            else int(np.prod(act_space.shape))
        )
        probe.close()
        self.policy = FlatParamsPolicy(tanh_mlp(obs_dim, act_dim, hidden))
        rng = np.random.default_rng(seed)
        import jax.numpy as jnp

        self.params = jnp.asarray(
            rng.normal(size=(self.popsize, self.policy.parameter_count)),
            jnp.float32,
        )
        self._mujoco = self._mujoco_backend()
        self._warmed_splits: set = set()
        self._sync_baseline: Optional[Dict[str, Any]] = None

    def _mujoco_backend(self) -> bool:
        try:
            from ..envs.mujoco.mjvecenv import MjVecEnv

            import gymnasium as gym

            probe = MjVecEnv(lambda: gym.make(self.env_id), 1)
            probe.close()
            return True
        except Exception:  # graftlint: allow(swallow): backend availability probe; False IS the answer
            return False

    def default_config(self) -> Optional[Dict[str, Any]]:
        return None  # no analytic cost model on the host path; grid[0] anchors

    def knob_group(self) -> KnobGroup:
        import os

        blocks = tuple(b for b in (1, 2, 4) if b <= self.num_envs)
        knobs = [KnobSpec("num_blocks", blocks, refine=False)]
        if self._mujoco:
            cores = int(os.cpu_count() or 1)
            nthreads = tuple(sorted({1, 2, cores} & set(range(1, self.num_envs + 1))))
            knobs.append(KnobSpec("mj_nthread", nthreads, refine=False))
        return KnobGroup(name=self.group, knobs=tuple(knobs))

    def cost(self, config):
        return None  # host-orchestrated: no single XLA program to analyze

    def _fresh_vec(self, config):
        import gymnasium as gym

        if self._mujoco:
            from ..envs.mujoco.mjvecenv import MjVecEnv

            vec = MjVecEnv(
                lambda: gym.make(self.env_id),
                self.num_envs,
                nthread=config.get("mj_nthread"),
            )
        else:
            from ..neuroevolution.net.hostvecenv import SyncVectorEnv

            vec = SyncVectorEnv(lambda: gym.make(self.env_id), self.num_envs)
        vec.seed(range(1000, 1000 + self.num_envs))
        return vec

    def _run(self, config, *, episode_length: Optional[int] = None, mode="pipelined"):
        import numpy as np

        from ..neuroevolution.net.hostvecenv import run_host_pipelined_rollout

        vec = self._fresh_vec(config)
        try:
            t0 = time.perf_counter()
            result = run_host_pipelined_rollout(
                vec,
                self.policy,
                self.params,
                num_episodes=1,
                episode_length=(
                    self.episode_length if episode_length is None else episode_length
                ),
                mode=mode,
                num_blocks=config.get("num_blocks"),
                # the tuner must never measure through its own previous
                # output: the sync baseline (and any config with blocks
                # unset) gets the PRISTINE heuristic, not a cached entry
                use_tuned_cache=False,
                rng=np.random.default_rng(0),
            )
            elapsed = time.perf_counter() - t0
        finally:
            vec.close()
        return result["interactions"] / elapsed if elapsed else 0.0, result

    def _warm(self, config):
        """The gathered device forward is jitted per BLOCK WIDTH, so every
        distinct block split must compile OUTSIDE the timed region — a
        one-warmup-for-all approach would hand later candidates a mid-trial
        compile (and with one trial, a compile-contaminated median)."""
        split = (config.get("num_blocks"), config.get("mj_nthread"))
        if split not in self._warmed_splits:
            self._warmed_splits.add(split)
            self._run(config, episode_length=3)

    def measure(self, configs, trials, round_index):
        from ..analysis import track_compiles

        for config in configs:
            self._warm(config)
        out = [
            {"samples": [], "occupancies": [], "steady_compiles": 0}
            for _ in configs
        ]
        for _ in range(trials):
            for i, config in enumerate(configs):
                with tracer.span(
                    "autotune.trial", "autotune", group=self.group, **config
                ):
                    with track_compiles() as compile_log:
                        sps, result = self._run(config)
                out[i]["samples"].append(sps)
                out[i]["occupancies"].append(result["occupancy"])
                out[i]["steady_compiles"] += compile_log.count
        return out

    def baseline(self, trials: int = 3) -> Dict[str, Any]:
        """The sync-mode scheduler (same event order, no worker thread)
        at default blocks — the pipelined/sync A/B denominator."""
        if self._sync_baseline is not None:
            return self._sync_baseline
        samples = []
        self._warm({})
        for _ in range(max(1, trials)):
            with tracer.span("autotune.trial", "autotune", group="host_sync"):
                sps, _ = self._run({}, mode="sync")
            samples.append(sps)
        self._sync_baseline = {
            "steps_per_sec": _median(samples),
            "occupancy": None,
            "samples": samples,
        }
        return self._sync_baseline

    def tuned_config(self, config):
        out = {"num_blocks": int(config["num_blocks"])}
        if "mj_nthread" in config:
            out["mj_nthread"] = int(config["mj_nthread"])
        return out


# ---------------------------------------------------------------------------
# the tuning driver: search a harness, fill the ledger, persist the winner
# ---------------------------------------------------------------------------


def tune_group(
    harness,
    *,
    trials: int = 3,
    max_rounds: int = 2,
    survivor_frac: float = 0.5,
    min_occupancy="auto",
    hbm_budget_bytes: Optional[float] = None,
    hbm_budget_ratio: Optional[float] = 8.0,
    flops_bound: Optional[float] = None,
    refine: bool = True,
    ledger_out: Optional[TimingLedger] = None,
    cache_path=None,
    write_cache: bool = True,
) -> SearchOutcome:
    """Run one knob group end to end: derive the HBM budget from the
    DEFAULT candidate's analyzed peak (``hbm_budget_ratio`` — a
    guardrail against pathological grid corners, generous enough to keep
    every sane rung), search, land every candidate in the measured-timing
    ledger, and persist the winner to the tuned-config cache.

    ``min_occupancy="auto"`` takes the HARNESS's per-group floor
    (``default_min_occupancy``): 0.9 for refill, none for compact —
    whose contract structurally runs ~0.5 — and the host pipeline.
    Secondary-objective selection (``winner_tolerance`` /
    ``winner_prefer`` — the policy group's highest-rank-within-band
    rule) also comes from the harness."""
    if min_occupancy == "auto":
        min_occupancy = getattr(harness, "default_min_occupancy", None)
    tolerance = getattr(harness, "winner_tolerance", None)
    prefer = getattr(harness, "winner_prefer", None)
    led = ledger_out if ledger_out is not None else timings
    group = harness.knob_group()
    machine = machine_fingerprint()
    cost_cache: Dict[Tuple, Optional[Dict]] = {}

    def cost_fn(config):
        key = tuple(sorted(config.items()))
        if key not in cost_cache:
            try:
                cost_cache[key] = harness.cost(config)
            except Exception:  # graftlint: allow(swallow): cost analysis is advisory; None disables pruning for this config
                cost_cache[key] = None  # no analysis never prunes
        return cost_cache[key]

    budget = hbm_budget_bytes
    if budget is None and hbm_budget_ratio is not None:
        anchor = harness.default_config() or candidate_grid(group)[0]
        reference = cost_fn(anchor)
        if reference is not None and reference.get("peak_bytes") is not None:
            budget = float(reference["peak_bytes"]) * float(hbm_budget_ratio)

    outcome = autotune_search(
        group,
        harness.measure,
        cost_fn=cost_fn,
        hbm_budget_bytes=budget,
        flops_bound=flops_bound,
        trials_per_round=trials,
        survivor_frac=survivor_frac,
        max_rounds=max_rounds,
        min_occupancy=min_occupancy,
        tolerance=tolerance,
        prefer=prefer,
        refine=refine,
    )

    shape = harness.shape.as_dict() if hasattr(harness, "shape") else {}
    for stats in outcome.results:
        led.add(
            TimingRecord(
                program=harness.program,
                shape=shape,
                machine=machine,
                config=dict(stats.config),
                samples=tuple(stats.samples),
                occupancy=stats.occupancy,
                refill_events=stats.refill_events,
                queue_wait=stats.queue_wait,
                compile_seconds=(
                    None if stats.cost is None else stats.cost.get("compile_seconds")
                ),
                steady_compiles=stats.steady_compiles,
            )
        )
    for config, reason in outcome.pruned:
        led.add(
            TimingRecord(
                program=harness.program,
                shape=shape,
                machine=machine,
                config=dict(config),
                pruned=reason,
            )
        )

    # NEVER persist an untrustworthy winner: a steady-state compile inside
    # a timed trial means the medians are contaminated (the CLI additionally
    # exits nonzero on this), and a winner that only exists because NO
    # candidate met the occupancy floor (select_winner's unconstrained
    # fallback) is exactly the lucky-run wide rung the floor exists to
    # block — either one landing in the checked-in cache would be silently
    # applied by every consumer while the battery retries
    floor_met = outcome.winner is not None and (
        min_occupancy is None
        or (
            outcome.winner.occupancy is not None
            and outcome.winner.occupancy >= min_occupancy
        )
    )
    if (
        outcome.winner is not None
        and outcome.winner.steady_compiles == 0
        and floor_met
        and write_cache
    ):
        from .timings import save_tuned_entry

        baseline = harness.baseline(trials)
        speedup = None
        if baseline["steps_per_sec"]:
            speedup = outcome.winner.steps_per_sec / baseline["steps_per_sec"]
        cache_shape = _cache_shape(harness)
        entry = TunedEntry(
            group=harness.group,
            shape=cache_shape,
            machine=machine,
            config=harness.tuned_config(outcome.winner.config),
            evidence={
                "steps_per_sec": round(outcome.winner.steps_per_sec, 1),
                "occupancy": (
                    None
                    if outcome.winner.occupancy is None
                    else round(outcome.winner.occupancy, 4)
                ),
                "baseline_steps_per_sec": round(baseline["steps_per_sec"], 1),
                "speedup_vs_baseline": (
                    None if speedup is None else round(speedup, 3)
                ),
                "trials": len(outcome.winner.samples),
                "steady_compiles": outcome.winner.steady_compiles,
                "episode_length": getattr(
                    getattr(harness, "shape", None), "episode_length", None
                ),
                "tuned_at": time.strftime("%Y-%m-%d"),
            },
        )
        save_tuned_entry(entry, cache_path)
        outcome.cache_written = True
    return outcome


def _cache_shape(harness) -> Dict[str, Any]:
    """The cache key's shape dict: (env, popsize, policy parameter count,
    compute dtype) for the device-program groups — params/dtype because a
    width tuned for a 64x64-f32 policy says nothing about a 256x256-bf16
    one (different per-step FLOPs/HBM balance) — and machine-scoped
    (empty) for the host-pipeline group, whose knobs are host properties."""
    from .timings import canonical_env_label

    if isinstance(harness, HostPipelineHarness):
        return {}
    return {
        # canonicalized exactly like every consumer's lookup label — an
        # entry written under "Hopper-v5" would never match "hopper"
        "env": canonical_env_label(harness.shape.env_name),
        "popsize": harness.shape.popsize,
        # the FULL workload identity: episode length/count change the
        # work-list size and refill frequency, and params/dtype change the
        # per-step FLOPs/HBM balance — a schedule measured at one must not
        # be applied to another under a "cache" label
        "episode_length": harness.shape.episode_length,
        "num_episodes": harness.shape.num_episodes,
        "params": harness.policy.parameter_count,
        "dtype": dtype_label(harness.shape.compute_dtype),
        # the autotuner measures on the unsharded single-device program;
        # sharded consumers look up under their own mesh label and never
        # inherit these entries (parallel.mesh.mesh_label)
        "mesh": "none",
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _tpu_healthy() -> bool:
    """A killable-subprocess TPU probe (the axon plugin can hang FOREVER
    on first backend use when its tunnel is down — CLAUDE.md — which must
    not wedge a tuning run) that additionally asserts a NON-CPU platform:
    the plugin can also silently fall back to CPU, and a tuning run that
    believed it measured the chip would stamp the battery's .ok with
    CPU-measured entries (the false-fire mode tpu_watch.sh guards against
    the same way)."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; ds = jax.devices(); "
                "assert ds and ds[0].platform != 'cpu', ds; print(len(ds))",
            ],
            timeout=120,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _actual_backend() -> str:
    import jax

    return jax.default_backend()


def _setup_backend(force_cpu: bool) -> bool:
    import os
    import sys

    use_cpu = force_cpu or os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if not use_cpu and not _tpu_healthy():
        print("TPU backend unhealthy; falling back to CPU", file=sys.stderr)
        use_cpu = True
    if use_cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if use_cpu:
        jax.config.update("jax_platforms", "cpu")
    return use_cpu


def _shape_from_args(args, use_cpu: bool) -> TuneShape:
    """The tuning shape, honoring the same BENCH_* knobs with the same
    defaults as bench_common.bench_config — KEEP THE TWO IN SYNC: a cache
    hit requires exact (env, popsize, params, dtype) equality, so a
    default drifting here (or there) silently turns every bench lookup
    into a fallback. (Duplicated rather than imported: the package must
    not depend on the repo-root bench scripts.)"""
    import json as _json
    import os

    import jax.numpy as jnp

    popsize = args.popsize
    if popsize is None:
        popsize = int(os.environ.get("BENCH_POPSIZE", 1024 if use_cpu else 10_000))
    episode_length = args.episode_length
    if episode_length is None:
        episode_length = int(
            os.environ.get("BENCH_EPISODE_LENGTH", 100 if use_cpu else 200)
        )
    hidden_raw = args.hidden or os.environ.get("BENCH_HIDDEN", "64,64")
    hidden = tuple(int(h) for h in hidden_raw.split(",") if h)
    env_name = args.env or os.environ.get("BENCH_ENV", "humanoid")
    env_kwargs = _json.loads(os.environ.get("BENCH_ENV_ARGS", "{}"))
    if env_kwargs:
        raise SystemExit(
            "autotune keys the tuned-config cache by plain env name; "
            "BENCH_ENV_ARGS would make the entry ambiguous — unset it"
        )
    compute_dtype = (
        jnp.bfloat16 if os.environ.get("BENCH_BF16", "0") == "1" else None
    )
    return TuneShape(
        env_name=env_name,
        popsize=popsize,
        episode_length=episode_length,
        hidden=hidden,
        compute_dtype=compute_dtype,
    )


def _emit(payload: dict) -> None:
    import json as _json

    print(_json.dumps(payload), flush=True)


def main(argv=None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m evotorch_tpu.observability.autotune",
        description="Occupancy-driven autotuner: search the eval-schedule "
        "knobs at bench-compatible shapes, record measured timings, persist "
        "winners to the tuned-config cache (docs/observability.md).",
    )
    parser.add_argument(
        "--group",
        default="refill",
        help="comma list of knob groups: refill, compact, host_pipeline, "
        "policy, span",
    )
    parser.add_argument("--cpu", action="store_true",
                        help="force the 8-virtual-device CPU backend")
    parser.add_argument("--env", default=None, help="env name (BENCH_ENV)")
    parser.add_argument("--popsize", type=int, default=None)
    parser.add_argument("--episode-length", type=int, default=None)
    parser.add_argument("--hidden", default=None, help="comma list, e.g. 64,64")
    parser.add_argument("--trials", type=int, default=3,
                        help="timed trials per candidate per round (median "
                        "of >=3 — the CLAUDE.md variance rule)")
    parser.add_argument("--max-rounds", type=int, default=2,
                        help="successive-halving rounds")
    parser.add_argument("--min-occupancy", type=float, default=None,
                        help="occupancy floor on the winner (default: each "
                        "group's own floor — 0.9 for refill; none for "
                        "compact, whose contract structurally runs ~0.5, "
                        "and host_pipeline)")
    parser.add_argument("--widths", default=None,
                        help="refill width grid override (comma list)")
    parser.add_argument("--periods", default="1",
                        help="refill period grid (comma list)")
    parser.add_argument("--chunks", default="10,25,50",
                        help="compact chunk-size grid (comma list)")
    parser.add_argument("--min-widths", default="128,256,512",
                        help="compact width-menu-floor grid (comma list)")
    parser.add_argument("--ranks", default="4,16,64",
                        help="policy-group trunk-delta rank grid (comma list)")
    parser.add_argument("--trunk-blocks", default="0",
                        help="policy-group lane-block grid (comma list; 0 = "
                        "unblocked, others must divide the popsize)")
    parser.add_argument("--spans", default="1,2,4,8,16",
                        help="span-group fused-span length grid (comma list; "
                        "each K is its own compiled program)")
    parser.add_argument("--hbm-budget", type=float, default=None,
                        help="absolute peak-HBM prune budget in bytes")
    parser.add_argument("--hbm-budget-ratio", type=float, default=8.0,
                        help="prune budget as a multiple of the default "
                        "candidate's analyzed peak (None-able via 0)")
    parser.add_argument("--flops-bound", type=float, default=None,
                        help="absolute cost-model FLOPs prune bound")
    parser.add_argument("--no-refine", action="store_true",
                        help="skip the neighborhood-refinement round")
    parser.add_argument("--no-write-cache", action="store_true",
                        help="search + ledger only; don't touch "
                        "tuned_configs.json")
    parser.add_argument("--cache", default=None,
                        help="alternate tuned_configs.json path")
    parser.add_argument("--timings-out", default=None,
                        help="write the measured-timing ledger JSON here")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    use_cpu = _setup_backend(args.cpu)
    groups = [g.strip() for g in args.group.split(",") if g.strip()]
    unknown = set(groups) - {
        "refill", "compact", "host_pipeline", "policy", "span"
    }
    if unknown:
        parser.error(f"unknown group(s): {sorted(unknown)}")

    shape = _shape_from_args(args, use_cpu)
    ratio = args.hbm_budget_ratio if args.hbm_budget_ratio else None
    session = TimingLedger()
    rc = 0
    for group_name in groups:
        if group_name == "refill":
            widths = (
                [int(w) for w in args.widths.split(",") if w]
                if args.widths
                else None
            )
            periods = [int(p) for p in args.periods.split(",") if p]
            harness = RefillHarness(
                shape, widths=widths, periods=periods, seed=args.seed
            )
        elif group_name == "compact":
            harness = CompactHarness(
                shape,
                chunks=[int(c) for c in args.chunks.split(",") if c],
                min_widths=[int(w) for w in args.min_widths.split(",") if w],
                seed=args.seed,
            )
        elif group_name == "policy":
            harness = PolicyHarness(
                shape,
                ranks=[int(r) for r in args.ranks.split(",") if r],
                trunk_blocks=[int(b) for b in args.trunk_blocks.split(",") if b != ""],
                seed=args.seed,
            )
        elif group_name == "span":
            harness = SpanHarness(
                shape,
                spans=[int(s) for s in args.spans.split(",") if s],
                seed=args.seed,
            )
        else:
            harness = HostPipelineHarness(seed=args.seed)
        print(
            f"[autotune] group={group_name} shape={_cache_shape(harness)} "
            f"machine={machine_fingerprint()}",
            file=sys.stderr,
        )
        outcome = tune_group(
            harness,
            trials=args.trials,
            max_rounds=args.max_rounds,
            min_occupancy=(
                args.min_occupancy if args.min_occupancy is not None else "auto"
            ),
            hbm_budget_bytes=args.hbm_budget,
            hbm_budget_ratio=ratio,
            flops_bound=args.flops_bound,
            refine=not args.no_refine,
            ledger_out=session,
            cache_path=args.cache,
            write_cache=not args.no_write_cache,
        )
        for stats in outcome.results:
            _emit(
                {
                    "metric": "autotune_steps_per_sec",
                    "group": group_name,
                    "config": stats.config,
                    "steps_per_sec": round(stats.steps_per_sec, 1),
                    "occupancy": (
                        None
                        if stats.occupancy is None
                        else round(stats.occupancy, 4)
                    ),
                    "trials": len(stats.samples),
                    "steady_compiles": stats.steady_compiles,
                }
            )
        for config, reason in outcome.pruned:
            _emit(
                {
                    "metric": "autotune_pruned",
                    "group": group_name,
                    "config": config,
                    "reason": reason,
                }
            )
        if outcome.winner is None:
            _emit({"metric": "autotune_winner", "group": group_name,
                   "error": "no candidate produced a timing"})
            rc = 1
            continue
        baseline = harness.baseline(args.trials)
        speedup = (
            outcome.winner.steps_per_sec / baseline["steps_per_sec"]
            if baseline["steps_per_sec"]
            else None
        )
        _emit(
            {
                "metric": "autotune_winner",
                "group": group_name,
                "config": harness.tuned_config(outcome.winner.config),
                "steps_per_sec": round(outcome.winner.steps_per_sec, 1),
                "occupancy": (
                    None
                    if outcome.winner.occupancy is None
                    else round(outcome.winner.occupancy, 4)
                ),
                "baseline_steps_per_sec": round(baseline["steps_per_sec"], 1),
                "speedup_vs_baseline": (
                    None if speedup is None else round(speedup, 3)
                ),
                "steady_compiles": outcome.winner.steady_compiles,
                "cache_written": outcome.cache_written,
                # report the platform jax actually ran on, not the plan —
                # a mid-run silent CPU fallback must not be labeled "tpu"
                "backend": "cpu-fallback" if use_cpu else _actual_backend(),
            }
        )
        # steady-state compiles inside a timed trial invalidate the run's
        # claim to compile-free measurement — surfaced as a nonzero exit
        # so the battery marks the step failed instead of stamping .ok
        if outcome.winner.steady_compiles:
            rc = 1
    if args.timings_out:
        session.save(args.timings_out)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
