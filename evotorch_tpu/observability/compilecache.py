"""Persistent XLA compilation cache wiring.

jax can serialize compiled executables to disk and reload them in later
processes (``jax_compilation_cache_dir``).  For this repo's programs the
win is large: the flagship rollout program takes ~10 s to compile cold on
this box and ~2 s to deserialize warm, so every bench / battery / curve
process after the first skips most of its startup tax.

:func:`enable_persistent_cache` turns the cache on with thresholds
lowered to "cache everything" (the defaults skip entries that compiled in
under a second, which covers most of our CPU-mesh test programs), and
registers monitoring listeners so callers can report hit/miss provenance
(:func:`cache_stats`) — bench.py uses this for its ``compile_cache``
JSON keys, and the warm-start acceptance test asserts hits > 0 in the
second process.

The default cache directory lives next to the bench output dirs and is
gitignored: serialized executables are machine- and jax-version-specific
artifacts, not source.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax

# Sibling of bench_curves/ at the repo root; gitignored (machine-local).
DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "compile_cache",
)

_COUNTS: Dict[str, int] = {"hits": 0, "misses": 0}
_LISTENER_INSTALLED = False
_ENABLED_DIR: Optional[str] = None

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring
    except ImportError:  # pragma: no cover - jax internals moved
        return

    def _on_event(event: str, **kwargs) -> None:
        if event == _HIT_EVENT:
            _COUNTS["hits"] += 1
        elif event == _MISS_EVENT:
            _COUNTS["misses"] += 1

    monitoring.register_event_listener(_on_event)
    _LISTENER_INSTALLED = True


def enable_persistent_cache(
    cache_dir: Optional[str] = None, *, xla_caches: bool = True
) -> str:
    """Enable jax's persistent compilation cache rooted at ``cache_dir``.

    Thresholds are dropped to zero so even fast-compiling programs are
    cached — on a 1-core box the *second* process's wall clock is what we
    are buying, and deserialization is cheap at every size.  Returns the
    directory in use.  Idempotent; re-enabling with a different directory
    re-points the cache.
    """
    global _ENABLED_DIR
    from ..resilience.retry import retry_call

    path = os.path.abspath(cache_dir or os.environ.get("EVOTORCH_COMPILE_CACHE_DIR") or DEFAULT_CACHE_DIR)
    # the cache dir often lives on shared/network storage: creating it
    # retries with bounded backoff (and is fault-injectable at site
    # "compilecache.io"); jax itself degrades to uncached compiles when
    # later entry reads/writes fail, so setup is the only hard IO edge
    retry_call(os.makedirs, path, exist_ok=True, site="compilecache.io")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    if xla_caches:
        # ``xla_caches=False`` opts out (the test suite does): "all" embeds
        # extra machine-local cache paths into the hashed compile options, so
        # entries re-key whenever the directory moves, and the XLA-internal
        # autotuning caches buy nothing on the CPU backend anyway.
        try:
            # Also cache XLA-internal autotuning artifacts where supported.
            jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
        except Exception:  # graftlint: allow(swallow): older jax without the XLA-caches option; the main cache is already on
            pass
    _install_listener()
    _ENABLED_DIR = path
    return path


def cache_stats() -> Dict[str, object]:
    """Hit/miss counters since :func:`enable_persistent_cache` (this process)."""
    return {
        "enabled": _ENABLED_DIR is not None,
        "dir": _ENABLED_DIR,
        "hits": _COUNTS["hits"],
        "misses": _COUNTS["misses"],
    }


def reset_stats() -> None:
    _COUNTS["hits"] = 0
    _COUNTS["misses"] = 0
