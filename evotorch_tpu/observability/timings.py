"""Measured-timing ledger + the persisted tuned-config cache.

:mod:`~evotorch_tpu.observability.programs` accounts what a compiled
program *should* cost (XLA's cost model); this module is its RUNTIME
sibling: what a program *measured* on a concrete machine — median
steps/s, occupancy, compile wall-time — keyed per
``(program, shape, backend, device_kind, core_count)``. The autotuner
(:mod:`~evotorch_tpu.observability.autotune`) fills the ledger from
interleaved trials and persists each winner into the **tuned-config
cache**, ``observability/tuned_configs.json`` — the checked-in file the
eval stack consults at setup time so measured telemetry, not hand-picked
defaults, chooses the schedule (ROADMAP item 2; the Podracer discipline,
arXiv:2104.06272).

Three pieces:

- :func:`machine_fingerprint` — the ``(backend, device_kind,
  core_count)`` identity a measurement is only valid on. Timings do NOT
  transfer across fingerprints: a refill width tuned on the 1-core CPU
  fallback says nothing about the TPU, so both the ledger and the cache
  key on it.
- :class:`TimingLedger` / :class:`TimingRecord` — the process-wide
  measured-timing registry (module singleton :data:`timings`), mirroring
  :class:`~evotorch_tpu.observability.programs.ProgramLedger`'s shape.
- the tuned-config cache — :func:`load_tuned_cache` /
  :func:`lookup_tuned` / :func:`save_tuned_entry` over
  ``tuned_configs.json``, plus :func:`resolve_knobs`, the ONE precedence
  rule every consumer shares: **explicit knobs always override the
  cache; a cache hit overrides the built-in fallback** — and every
  consumer reports which branch fired as a ``tuned_config_source``
  provenance key (``"override"`` / ``"cache"`` / ``"fallback"``) so a
  bench line or status row always says where its schedule came from.

The file format is append-friendly JSON (one entry per
``(group, shape, machine)`` key, last write wins) and the checked-in
copy is seeded with the r8 CPU-box measurements (BENCH_NOTES.md r8: the
occupancy column proving the default refill width mistuned on this box).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _atomic_write_json(path: Path, payload: dict) -> None:
    """Durable atomic JSON write: temp file in the target directory +
    flush + fsync + ``os.replace``, retried on transient IO errors
    (``resilience.retry``, site ``timings.write`` — fault-injectable).
    Concurrent searches sharing one eval server can race the autotuner's
    read-modify-write; whatever interleaving loses the race, a reader
    only ever sees a COMPLETE old or new file, never a truncation."""
    from ..resilience.retry import retry_call

    def _write() -> None:
        tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if tmp.exists():
                tmp.unlink()

    retry_call(_write, site="timings.write")

__all__ = [
    "SOURCE_CACHE",
    "SOURCE_FALLBACK",
    "SOURCE_OVERRIDE",
    "TimingLedger",
    "TimingRecord",
    "TunedEntry",
    "canonical_env_label",
    "default_tuned_cache_path",
    "dtype_label",
    "load_tuned_cache",
    "lookup_tuned",
    "machine_fingerprint",
    "resolve_knobs",
    "save_tuned_entry",
    "timing_key",
    "timings",
]

#: tuned_config_source provenance values (the order is the precedence)
SOURCE_OVERRIDE = "override"  # an explicit knob was passed — cache not consulted
SOURCE_CACHE = "cache"  # the tuned-config cache had a matching entry
SOURCE_FALLBACK = "fallback"  # no knob, no entry: the built-in default


def machine_fingerprint() -> Dict[str, Any]:
    """The machine identity a measurement is valid on: jax backend,
    device kind, and host core count. Deliberately EXCLUDES the virtual
    device count (the pytest mesh's 8 virtual CPUs share one physical
    core — the thing that actually bounds throughput here)."""
    import os

    import jax

    return {
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "core_count": int(os.cpu_count() or 1),
    }


def dtype_label(compute_dtype) -> str:
    """The cache-key label of an engine ``compute_dtype`` knob (``None``
    is the f32 default). Part of the tuned-config shape key: a schedule
    tuned under bf16 compute says nothing about the f32 program."""
    if compute_dtype is None:
        return "float32"
    return getattr(compute_dtype, "__name__", str(compute_dtype))


def _fmt_dict(d: Dict[str, Any]) -> str:
    return ",".join(f"{k}={d[k]}" for k in sorted(d))


def timing_key(
    program: str, shape: Dict[str, Any], machine: Dict[str, Any]
) -> str:
    """The stable ledger/cache key:
    ``program@shape|backend=...,core_count=...,device_kind=...`` —
    human-readable, insensitive to dict order, and machine-scoped (the
    same program+shape measured on another box is a different row)."""
    parts = [program]
    if shape:
        parts.append("@" + _fmt_dict(shape))
    parts.append("|" + _fmt_dict(machine))
    return "".join(parts)


def _median(values: Sequence[float]) -> float:
    ordered = sorted(float(v) for v in values)
    n = len(ordered)
    if n == 0:
        return 0.0
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


@dataclass
class TimingRecord:
    """One measured configuration of one program on one machine.

    ``samples`` holds every timed trial's steps/s; the headline
    ``steps_per_sec`` is their MEDIAN (this box times ±20% run to run —
    CLAUDE.md — so single trials are never trusted). ``occupancy`` /
    ``refill_events`` / ``queue_wait`` come from the zero-sync device
    telemetry of the timed trials; ``compile_seconds`` from the program
    ledger's AOT capture; ``steady_compiles`` from the retrace sentinel
    over the timed region (anything but 0 invalidates the timing — it
    paid a mid-loop compile)."""

    program: str
    shape: Dict[str, Any] = field(default_factory=dict)
    machine: Dict[str, Any] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)
    samples: Tuple[float, ...] = ()
    occupancy: Optional[float] = None
    refill_events: Optional[int] = None
    queue_wait: Optional[int] = None
    compile_seconds: Optional[float] = None
    steady_compiles: int = 0
    pruned: Optional[str] = None  # analytic-pruning reason; None = timed

    @property
    def key(self) -> str:
        return timing_key(self.program, self.shape, self.machine)

    @property
    def steps_per_sec(self) -> float:
        return _median(self.samples)

    @property
    def timed(self) -> bool:
        return self.pruned is None and bool(self.samples)

    def to_json(self) -> dict:
        return {
            "key": self.key,
            "program": self.program,
            "shape": dict(self.shape),
            "machine": dict(self.machine),
            "config": dict(self.config),
            "samples": [round(float(s), 2) for s in self.samples],
            "steps_per_sec": round(self.steps_per_sec, 2),
            "occupancy": (
                None if self.occupancy is None else round(self.occupancy, 4)
            ),
            "refill_events": self.refill_events,
            "queue_wait": self.queue_wait,
            "compile_seconds": (
                None
                if self.compile_seconds is None
                else round(self.compile_seconds, 4)
            ),
            "steady_compiles": self.steady_compiles,
            "pruned": self.pruned,
        }

    @classmethod
    def from_json(cls, data: dict) -> "TimingRecord":
        return cls(
            program=data["program"],
            shape=dict(data.get("shape") or {}),
            machine=dict(data.get("machine") or {}),
            config=dict(data.get("config") or {}),
            samples=tuple(data.get("samples") or ()),
            occupancy=data.get("occupancy"),
            refill_events=data.get("refill_events"),
            queue_wait=data.get("queue_wait"),
            compile_seconds=data.get("compile_seconds"),
            steady_compiles=int(data.get("steady_compiles") or 0),
            pruned=data.get("pruned"),
        )


class TimingLedger:
    """Process-wide registry of measured timings — the runtime sibling of
    :class:`~evotorch_tpu.observability.programs.ProgramLedger`. Records
    append under ``(key, config)`` (one program+shape+machine holds MANY
    candidate configs — that is the whole point: the autotuner compares
    them); :meth:`best` ranks a key's timed configs by median steps/s."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: List[TimingRecord] = []

    def add(self, record: TimingRecord) -> TimingRecord:
        with self._lock:
            self._records.append(record)
        return record

    def records(
        self, program: Optional[str] = None, shape: Optional[Dict[str, Any]] = None
    ) -> List[TimingRecord]:
        with self._lock:
            out = list(self._records)
        if program is not None:
            out = [r for r in out if r.program == program]
        if shape is not None:
            out = [r for r in out if r.shape == shape]
        return out

    def best(
        self,
        program: str,
        shape: Optional[Dict[str, Any]] = None,
        *,
        min_occupancy: Optional[float] = None,
    ) -> Optional[TimingRecord]:
        """The highest-median-throughput TIMED record for a program (and
        optionally an exact shape), among candidates meeting
        ``min_occupancy`` — falling back to the unconstrained winner when
        none do (an occupancy floor must never select nothing)."""
        candidates = [r for r in self.records(program, shape) if r.timed]
        if not candidates:
            return None
        if min_occupancy is not None:
            eligible = [
                r
                for r in candidates
                if r.occupancy is not None and r.occupancy >= min_occupancy
            ]
            if eligible:
                candidates = eligible
        return max(candidates, key=lambda r: r.steps_per_sec)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def to_json(self) -> dict:
        return {"timings": [r.to_json() for r in self.records()]}

    def save(self, path) -> Path:
        path = Path(path)
        # atomic (temp + fsync + replace): a --timings-out dump killed
        # mid-write must not leave a truncated ledger
        _atomic_write_json(path, self.to_json())
        return path

    @classmethod
    def load(cls, path) -> "TimingLedger":
        led = cls()
        with open(path) as f:
            data = json.load(f)
        for entry in data.get("timings", []):
            led.add(TimingRecord.from_json(entry))
        return led


#: the process-wide measured-timing ledger the autotuner feeds
timings = TimingLedger()


# ---------------------------------------------------------------------------
# the tuned-config cache
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TunedEntry:
    """One persisted winner: the knob values to use for ``group`` at
    ``shape`` on ``machine``, with the measurement evidence that chose
    them (so a later reader can judge whether the entry is still
    credible)."""

    group: str  # knob group: "refill", "compact", "host_pipeline", "mj"
    shape: Dict[str, Any]
    machine: Dict[str, Any]
    config: Dict[str, Any]
    evidence: Dict[str, Any] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return timing_key(self.group, self.shape, self.machine)

    def to_json(self) -> dict:
        return {
            "key": self.key,
            "group": self.group,
            "shape": dict(self.shape),
            "machine": dict(self.machine),
            "config": dict(self.config),
            "evidence": dict(self.evidence),
        }

    @classmethod
    def from_json(cls, data: dict) -> "TunedEntry":
        return cls(
            group=data["group"],
            shape=dict(data.get("shape") or {}),
            machine=dict(data.get("machine") or {}),
            config=dict(data.get("config") or {}),
            evidence=dict(data.get("evidence") or {}),
        )


def default_tuned_cache_path() -> Path:
    """``EVOTORCH_TUNED_CACHE`` overrides the checked-in cache file —
    the hook tests and multi-checkout setups use to isolate tuning."""
    import os

    override = os.environ.get("EVOTORCH_TUNED_CACHE")
    if override:
        return Path(override)
    return Path(__file__).resolve().parent / "tuned_configs.json"


def canonical_env_label(env) -> str:
    """The env identity used in cache-entry shapes: the registry's OWN
    normalization for strings (``"Humanoid-v5"`` → ``"humanoid"``, via
    :func:`evotorch_tpu.envs.registry.canonical_env_key` — shared so the
    cache key and ``make_env`` resolution cannot drift), the class name
    lowercased for live instances (``Humanoid()`` → ``"humanoid"``) —
    so a problem built from either spelling hits the same entry."""
    # lazy: timings is a leaf module; envs imports at module scope would
    # cycle through the package __init__
    from ..envs.registry import canonical_env_key

    if not isinstance(env, str):
        # class names fold through the registry's alias map too:
        # Swimmer2D() must hit an entry tuned via the string "swimmer"
        return canonical_env_key(type(env).__name__)
    name = env
    if name.startswith("gym::"):
        name = name[len("gym::") :]
    return canonical_env_key(name)


_CACHE_LOCK = threading.Lock()
_CACHE: Optional[Dict[str, TunedEntry]] = None
_CACHE_PATH: Optional[Path] = None


def load_tuned_cache(path=None, *, force: bool = False) -> Dict[str, TunedEntry]:
    """The tuned-config cache as ``{key: TunedEntry}``. The DEFAULT path
    (``tuned_configs.json`` / ``EVOTORCH_TUNED_CACHE``) is memoized per
    process — eval setup consults it every construction, the file is
    checked in and small, and this process's own :func:`save_tuned_entry`
    calls refresh the memo; an external writer needs ``force=True`` (or a
    restart) to be seen. A path passed EXPLICITLY always reads the file
    fresh and never touches the memo."""
    global _CACHE, _CACHE_PATH
    target = Path(path) if path is not None else default_tuned_cache_path()
    memoizable = target == default_tuned_cache_path()
    with _CACHE_LOCK:
        if not force and memoizable and _CACHE is not None and _CACHE_PATH == target:
            return _CACHE
        entries: Dict[str, TunedEntry] = {}
        if target.exists():
            try:
                with open(target) as f:
                    data = json.load(f)
                for raw in data.get("entries", []):
                    entry = TunedEntry.from_json(raw)
                    entries[entry.key] = entry
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                # a corrupt cache must degrade to "no cache" (fallback
                # provenance), never break eval setup
                entries = {}
        if memoizable:
            _CACHE, _CACHE_PATH = entries, target
        return entries


def lookup_tuned(
    group: str,
    shape: Dict[str, Any],
    *,
    machine: Optional[Dict[str, Any]] = None,
    path=None,
) -> Optional[TunedEntry]:
    """The cache hit for ``(group, shape)`` on this machine (exact key
    match), or ``None``. A miss is normal — it just means the built-in
    fallback default applies (``tuned_config_source="fallback"``).

    Backward-compatible read of pre-mesh (version-1) caches: when the
    lookup shape says ``"mesh": "none"`` (an UNSHARDED evaluation) and the
    exact key misses, the lookup retries without the ``mesh`` field —
    legacy entries were all measured unsharded, so they keep serving
    unsharded consumers; a sharded lookup (any other mesh label) never
    falls back to them (a width tuned without a mesh says nothing about a
    sharded layout — ``parallel.mesh.mesh_label``)."""
    machine = machine if machine is not None else machine_fingerprint()
    cache = load_tuned_cache(path)
    entry = cache.get(timing_key(group, shape, machine))
    if entry is None and shape.get("mesh") == "none":
        legacy_shape = {k: v for k, v in shape.items() if k != "mesh"}
        entry = cache.get(timing_key(group, legacy_shape, machine))
    return entry


def save_tuned_entry(entry: TunedEntry, path=None) -> Path:
    """Persist one winner (last write per key wins) and refresh the
    in-process memo so the running process sees its own tuning. The write
    is ATOMIC AND DURABLE (per-pid temp file + fsync + rename, retried on
    transient IO errors): a battery step killed mid-write (the tpu_window
    timeout, a dropped tunnel) or concurrent searches racing the
    read-modify-write through a shared eval server must not leave a
    truncated checked-in cache that silently downgrades every consumer to
    fallback."""
    target = Path(path) if path is not None else default_tuned_cache_path()
    entries = dict(load_tuned_cache(target, force=True))
    entries[entry.key] = entry
    payload = {
        # version 2: entry shapes carry a "mesh" label (parallel.mesh
        # .mesh_label). Version-1 entries (no mesh key) remain readable —
        # lookup_tuned serves them to unsharded ("mesh": "none") consumers
        "version": 2,
        "entries": [entries[k].to_json() for k in sorted(entries)],
    }
    _atomic_write_json(target, payload)
    load_tuned_cache(target, force=True)
    return target


def resolve_knobs(
    explicit: Dict[str, Any],
    group: str,
    shape: Dict[str, Any],
    *,
    machine: Optional[Dict[str, Any]] = None,
    path=None,
    use_cache: bool = True,
) -> Tuple[Dict[str, Any], str]:
    """THE precedence rule, shared by every consumer: returns
    ``(config, tuned_config_source)``.

    - any explicit knob (a non-``None`` value in ``explicit``) wins and
      the cache is not consulted at all — ``"override"``;
    - else a cache hit supplies the tuned config — ``"cache"``;
    - else the empty config: the caller's built-in default applies —
      ``"fallback"`` (also the forced branch under ``use_cache=False``,
      e.g. ``BENCH_TUNED=0``)."""
    passed = {k: v for k, v in explicit.items() if v is not None}
    if passed:
        return passed, SOURCE_OVERRIDE
    if use_cache:
        entry = lookup_tuned(group, shape, machine=machine, path=path)
        if entry is not None:
            return dict(entry.config), SOURCE_CACHE
    return {}, SOURCE_FALLBACK
