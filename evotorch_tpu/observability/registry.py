"""Process-wide counter registry: compile/dispatch accounting, always on.

A :class:`CounterRegistry` is a thread-safe map of monotonically-increasing
integer counters. The module-level singleton :data:`counters` is the one the
framework feeds:

- ``compiles`` — every XLA trace+compile in the process, counted by the
  session-wide promotion of the retrace sentinel's compile counting
  (:func:`ensure_compile_counter`; see
  :mod:`evotorch_tpu.analysis.retrace_sentinel`). A warmed-up run
  incrementing this counter IS a steady-state retrace — the runtime form
  of graftlint's ``retrace`` checker.
- ``trace_spans`` — spans recorded by the host tracer
  (:mod:`~evotorch_tpu.observability.tracer`); 0 while tracing is off.
- ``telemetry_fetches`` — device->host decodes of the packed eval-telemetry
  vector (:meth:`~evotorch_tpu.observability.devicemetrics.EvalTelemetry.from_array`).
  Each fetch is one ~24-byte transfer of an already-materialized program
  output; this counter exists so "zero extra transfers" is auditable.

Beyond the integer counters, the registry carries two program-ledger
companions (PR 9, :mod:`~evotorch_tpu.observability.programs`):

- ``compile_seconds`` — a FLOAT accumulator of compile-pipeline wall time
  (trace + MLIR lowering + backend compile), fed by jax's monitoring
  duration events via :func:`ensure_compile_timer` — the wall-clock twin
  of the ``compiles`` count.
- ``peak_hbm_bytes`` — a max-gauge over every ledger-captured program's
  analyzed peak footprint (:meth:`CounterRegistry.observe_max`).

``SearchAlgorithm.step`` snapshots the registry around each generation and
publishes the per-step deltas as status keys (``compiles``, ``trace_spans``,
``telemetry_fetches``, ``compile_seconds``) plus the absolute
``peak_hbm_bytes`` gauge, so every logger sees them for free.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional

__all__ = [
    "CounterRegistry",
    "counters",
    "ensure_compile_counter",
    "ensure_compile_timer",
]


class CounterRegistry:
    """Thread-safe named meters: monotonically-increasing counters
    (:meth:`increment` int, :meth:`accumulate` float) and high-water-mark
    gauges (:meth:`observe_max`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = {}

    def increment(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def accumulate(self, name: str, value: float) -> None:
        """Float-valued increment (e.g. seconds); keeps the same snapshot /
        delta discipline as the integer counters."""
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + float(value)

    def observe_max(self, name: str, value: float) -> None:
        """High-water-mark gauge: the stored value only ever rises."""
        with self._lock:
            if value > self._counts.get(name, 0):
                self._counts[name] = value

    def get(self, name: str):
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self, names: Optional[Iterable[str]] = None) -> Dict[str, int]:
        """A point-in-time copy — pair two snapshots with :meth:`delta` to
        meter a code region."""
        with self._lock:
            if names is None:
                return dict(self._counts)
            return {n: self._counts.get(n, 0) for n in names}

    def delta(self, since: Dict[str, int]) -> Dict[str, int]:
        """Counter increases since a prior :meth:`snapshot` (only the keys of
        ``since`` are reported, so a snapshot doubles as a key filter)."""
        with self._lock:
            return {n: self._counts.get(n, 0) - v for n, v in since.items()}


#: the process-wide registry every subsystem feeds
counters = CounterRegistry()


_compile_sink = None
_compile_lock = threading.Lock()


class _CompileCounterSink:
    """A permanent retrace-sentinel sink feeding ``counters['compiles']``."""

    def record(self, name: str) -> None:
        counters.increment("compiles")


def ensure_compile_counter() -> None:
    """Promote the retrace sentinel's compile counting to session scope:
    every XLA compile from now on increments ``counters['compiles']``.

    Idempotent and cheap to call anywhere a hot loop starts (searchers call
    it on construction). Composes with test-scoped
    :func:`~evotorch_tpu.analysis.retrace_sentinel.track_compiles` blocks —
    the sentinel's sink list is shared and nestable."""
    global _compile_sink
    with _compile_lock:
        if _compile_sink is not None:
            return
        from ..analysis import retrace_sentinel

        _compile_sink = _CompileCounterSink()
        retrace_sentinel.register_sink(_compile_sink)


_timer_installed = False


def _on_duration_event(event: str, duration: float, **_kwargs) -> None:
    """jax.monitoring duration listener: accumulate the compile pipeline's
    wall time (trace + jaxpr->MLIR + backend compile all emit under the
    ``/jax/core/compile/`` prefix) into ``counters['compile_seconds']``."""
    if event.startswith("/jax/core/compile/"):
        counters.accumulate("compile_seconds", duration)


def ensure_compile_timer() -> None:
    """Session-scope compile WALL-TIME accounting — the duration twin of
    :func:`ensure_compile_counter`: from the first call on, every compile's
    trace/lower/backend-compile durations accumulate into
    ``counters['compile_seconds']`` via jax's monitoring events.

    Idempotent; a jax build without the monitoring API degrades to a no-op
    (the counter just stays 0.0)."""
    global _timer_installed
    with _compile_lock:
        if _timer_installed:
            return
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration_event)
        except Exception:  # graftlint: allow(swallow): older jax without the monitoring hook; timing column degrades to absent
            pass
        _timer_installed = True
