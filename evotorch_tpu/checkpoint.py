"""Checkpoint / resume.

The reference's checkpointing is pickle-based: every core object is
``Serializable`` and ``PicklingLogger`` periodically saves decision-making
state (SURVEY.md §5). The TPU build adds what the reference lacks — a
**mid-run algorithm-state resume API**: every functional algorithm state is a
pytree, so it round-trips losslessly through orbax.

- ``save_state`` / ``load_state``: orbax checkpoint of any pytree state
  (PGPEState, CMAESState, CollectedStats, optimizer states, ...).
- ``save_searcher`` / ``load_searcher``: pickle of a whole OO searcher
  (problem + distribution + optimizer + counters), reference-style.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax

__all__ = ["save_state", "load_state", "save_searcher", "load_searcher"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_state(path: str, state: Any):
    """Save a pytree state (functional algorithm/optimizer state) with orbax.
    Static dataclass fields ride along automatically (they are part of the
    treedef, which is reconstructed from the ``template`` at load time)."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    ckpt.save(path, state, force=True)
    ckpt.wait_until_finished()


def load_state(path: str, template: Any) -> Any:
    """Restore a pytree state saved by :func:`save_state`. ``template`` is a
    state of the same structure (e.g. a freshly initialized one) providing
    the treedef, static fields, and array shapes/dtypes."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
    restored = ckpt.restore(path, target)
    # graft restored leaves back into the template (preserving static fields)
    leaves, _ = jax.tree_util.tree_flatten(restored)
    _, treedef = jax.tree_util.tree_flatten(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_searcher(path: str, searcher) -> str:
    """Pickle a whole OO searcher (reference-style whole-object checkpoint).

    Crash-safe: the pickle goes to a sibling tmp file, is fsync'd, and is
    renamed into place — a crash mid-write leaves either the previous
    checkpoint or none, never a truncated pickle. (Durable multi-bundle
    checkpointing with retention and corruption fallback is
    ``resilience.RunCheckpointer``, which builds on this primitive.)
    """
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        pickle.dump(searcher, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_searcher(path: str):
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (pickle.UnpicklingError, EOFError, AttributeError) as exc:
        raise RuntimeError(
            f"checkpoint {path!r} is corrupt or truncated ({exc}); it likely "
            "predates the crash-safe writer — delete it, or resume from a "
            "resilience.RunCheckpointer bundle directory instead"
        ) from exc
