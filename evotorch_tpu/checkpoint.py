"""Checkpoint / resume.

The reference's checkpointing is pickle-based: every core object is
``Serializable`` and ``PicklingLogger`` periodically saves decision-making
state (SURVEY.md §5). The TPU build adds what the reference lacks — a
**mid-run algorithm-state resume API**: every functional algorithm state is a
pytree, so it round-trips losslessly through orbax.

- ``save_state`` / ``load_state``: orbax checkpoint of any pytree state
  (PGPEState, CMAESState, CollectedStats, optimizer states, ...).
- ``save_searcher`` / ``load_searcher``: pickle of a whole OO searcher
  (problem + distribution + optimizer + counters), reference-style.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax

__all__ = ["save_state", "load_state", "save_searcher", "load_searcher"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_state(path: str, state: Any):
    """Save a pytree state (functional algorithm/optimizer state) with orbax.
    Static dataclass fields ride along automatically (they are part of the
    treedef, which is reconstructed from the ``template`` at load time)."""
    path = os.path.abspath(path)
    ckpt = _checkpointer()
    ckpt.save(path, state, force=True)
    ckpt.wait_until_finished()


def load_state(path: str, template: Any) -> Any:
    """Restore a pytree state saved by :func:`save_state`. ``template`` is a
    state of the same structure (e.g. a freshly initialized one) providing
    the treedef, static fields, and array shapes/dtypes."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckpt = _checkpointer()
    target = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
    restored = ckpt.restore(path, target)
    # graft restored leaves back into the template (preserving static fields)
    leaves, _ = jax.tree_util.tree_flatten(restored)
    _, treedef = jax.tree_util.tree_flatten(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_searcher(path: str, searcher) -> str:
    """Pickle a whole OO searcher (reference-style whole-object checkpoint)."""
    with open(path, "wb") as f:
        pickle.dump(searcher, f)
    return path


def load_searcher(path: str):
    with open(path, "rb") as f:
        return pickle.load(f)
