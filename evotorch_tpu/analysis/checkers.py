"""The graftlint checkers — nine JAX/telemetry-specific static analyses.

=============  ==============================================================
checker        what it catches
=============  ==============================================================
``prng``       a PRNG key consumed by two sampling calls without an
               intervening ``split`` (including across loop iterations —
               keys threaded out of loops un-split)
``retrace``    ``jax.jit`` wrappers rebuilt per call: jit built inside a
               loop, jit over a fresh lambda/bound method inside a function
               (every call re-traces), f-strings passed to jitted callables
``host-sync``  ``.item()`` / ``float()`` / ``int()`` / ``np.asarray()`` on
               traced values inside jit/lax-traced functions, and
               per-iteration device syncs (``float(jax_helper(...))``) in
               host loops
``donation``   jitted state-in/state-out steps (first arg a state/carry
               pytree) lacking ``donate_argnums`` — the ask-tell hot loop
               then allocates a fresh state buffer every generation
``axis-name``  ``pmean``/``psum``/``axis_index``/``PartitionSpec`` string
               axis literals that match no declared mesh axis (typos silently
               crash late or, worse, silently de-shard)
``dtype``      float64/int64 leaks into the f32/bf16 compute path: x64 dtype
               references, ``dtype="float64"`` strings, np 64-bit constants
               materialized inside traced code
``timing``     ``time.*()`` measurement regions around calls to jitted
               callables with no ``block_until_ready()`` in the region —
               such timings measure async dispatch, not the computation
               (unsynced-timing bugs)
``swallow``    broad exception handlers (bare ``except``, ``except
               Exception``/``BaseException``) that neither log/report nor
               re-raise — silent degradation: the failure its author
               shrugged off becomes invisible at every later debugging
               session. Intentional swallows carry
               ``# graftlint: allow(swallow): reason``
``telemetry-schema``  hard-coded telemetry wire column indices (int literals
               subscripting ``*telemetry*``/``group_counts``/``lane_counts``
               arrays) outside ``observability/devicemetrics.py`` — the
               schema-versioned layout has ONE owner; everywhere else must
               index via its named constants or the decoded accessors
=============  ==============================================================

All checkers are pure-AST (no imports executed). Each returns
:class:`~evotorch_tpu.analysis.graftlint.Finding`\\ s whose ``detail`` field
is a stable signature component (see graftlint's baseline notes).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .graftlint import Finding, ModuleInfo, ProjectInfo, dotted_name

__all__ = ["CHECKERS"]


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

#: jax.random functions that CONSUME a key (first positional argument)
_SAMPLERS = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical", "cauchy",
    "chisquare", "choice", "dirichlet", "double_sided_maxwell", "exponential",
    "f", "gamma", "generalized_normal", "geometric", "gumbel", "laplace",
    "loggamma", "logistic", "maxwell", "multivariate_normal", "normal",
    "orthogonal", "pareto", "permutation", "poisson", "rademacher", "randint",
    "rayleigh", "t", "triangular", "truncated_normal", "uniform", "wald",
    "weibull_min",
}

#: jax.random functions that DERIVE fresh keys (do not invalidate the parent
#: for further derivation; assigning their result rebinds targets as fresh)
_DERIVERS = {"split", "fold_in", "key", "PRNGKey", "clone", "wrap_key_data"}

_TRACED_COMBINATORS = {
    "jax.jit",
    "jax.vmap",
    "jax.pmap",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.scan",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.checkpoint",
    "jax.remat",
    "jax.grad",
    "jax.value_and_grad",
}

_COLLECTIVES = {
    "jax.lax.pmean",
    "jax.lax.psum",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.axis_index",
    "jax.lax.all_gather",
    "jax.lax.ppermute",
    "jax.lax.psum_scatter",
    "jax.lax.all_to_all",
    "jax.lax.pshuffle",
}

_STATE_PARAM_RE = re.compile(r"^(new_)?(state|carry|opt_state|optimizer_state)$|^\w+_(state|carry)$")


def _is_jit_call(mod: ModuleInfo, node: ast.Call) -> bool:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    canon = mod.canon(node.func)
    if canon == "jax.jit":
        return True
    if canon == "functools.partial" and node.args:
        return mod.canon(node.args[0]) == "jax.jit"
    return False


def _jit_kwargs(mod: ModuleInfo, node: ast.Call) -> Dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in node.keywords if kw.arg}


def _jit_decoration(mod: ModuleInfo, fn: ast.AST) -> Optional[ast.Call]:
    """The jit decorator Call of a FunctionDef, if any (``@jax.jit`` bare
    decorators are returned as a synthetic empty-kwargs marker)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call) and _is_jit_call(mod, dec):
            return dec
        if mod.canon(dec) == "jax.jit":
            return ast.Call(func=dec, args=[], keywords=[])
    return None


def _static_param_names(mod: ModuleInfo, fn: ast.AST, jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names pinned static by the jit decoration/wrapping —
    ``int()``/``float()`` on those is host math on static config, not a sync."""
    if jit_call is None or not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return set()
    names: Set[str] = set()
    params = [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
            for elt in elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.add(elt.value)
        elif kw.arg == "static_argnums":
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
            for elt in elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    if 0 <= elt.value < len(params):
                        names.add(params[elt.value])
    return names


def _resolve_local_def(mod: ModuleInfo, scope: ast.AST, name: str) -> Optional[ast.AST]:
    """A FunctionDef named ``name`` visible from ``scope`` (nearest enclosing
    scope first, then module level)."""
    cur: Optional[ast.AST] = scope
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
            body = cur.body
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt.name == name:
                    return stmt
        cur = getattr(cur, "_gl_parent", None)
    return None


def _collect_traced(mod: ModuleInfo) -> Dict[ast.AST, Set[str]]:
    """Function/lambda nodes whose bodies run under trace, mapped to their
    static parameter names. Sources of truth:

    - defs decorated with ``jax.jit`` / ``partial(jax.jit, ...)``;
    - lambdas / local defs passed (by name or inline) to jit/vmap/shard_map
      or the ``lax`` control-flow combinators;
    - defs nested inside an already-traced def.

    Memoized per module (host-sync and dtype both need it).
    """
    cached = getattr(mod, "_gl_traced_cache", None)
    if cached is not None:
        return cached
    traced: Dict[ast.AST, Set[str]] = {}
    mod._gl_traced_cache = traced  # type: ignore[attr-defined]

    def mark(fn: ast.AST, statics: Set[str]):
        if fn in traced:
            traced[fn] |= statics
        else:
            traced[fn] = set(statics)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            dec = _jit_decoration(mod, node)
            if dec is not None:
                mark(node, _static_param_names(mod, node, dec))
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canon(node.func) or ""
        is_jit = _is_jit_call(mod, node)
        if canon not in _TRACED_COMBINATORS and not is_jit:
            continue
        statics: Set[str] = set()
        candidates = list(node.args)
        if canon == "functools.partial":
            candidates = candidates[1:]  # skip the jax.jit argument itself
        for arg in candidates:
            target: Optional[ast.AST] = None
            if isinstance(arg, ast.Lambda):
                target = arg
            elif isinstance(arg, ast.Name):
                target = _resolve_local_def(mod, mod.enclosing_function(node) or mod.tree, arg.id)
            elif isinstance(arg, ast.Call) and mod.canon(arg.func) == "functools.partial" and arg.args:
                inner = arg.args[0]
                if isinstance(inner, ast.Name):
                    target = _resolve_local_def(
                        mod, mod.enclosing_function(node) or mod.tree, inner.id
                    )
            if target is not None:
                if is_jit and isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    statics = _static_param_names(mod, target, node)
                mark(target, statics)
    # nested defs inside traced defs trace too
    frontier = list(traced)
    while frontier:
        fn = frontier.pop()
        for sub in ast.walk(fn):
            if sub is not fn and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if sub not in traced:
                    mark(sub, set())
                    frontier.append(sub)
    return traced


def _in_traced(mod: ModuleInfo, traced: Dict[ast.AST, Set[str]], node: ast.AST):
    """(traced_fn, statics) for the innermost traced function containing
    ``node``, else (None, empty). Statics accumulate from enclosing traced
    scopes (a closure over a static name is still static)."""
    statics: Set[str] = set()
    hit: Optional[ast.AST] = None
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        if cur in traced:
            if hit is None:
                hit = cur
            statics |= traced[cur]
        cur = getattr(cur, "_gl_parent", None)
    return hit, statics


# ---------------------------------------------------------------------------
# (a) PRNG discipline
# ---------------------------------------------------------------------------


class _PrngScope:
    """Linear abstract interpretation of one function body: key names go
    fresh -> consumed; a second consumption without an intervening
    split/fold_in is a finding. Branches are analyzed separately (a branch
    ending in return/raise does not leak its consumption), loops are walked
    twice to expose cross-iteration reuse."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.findings: List[Finding] = []
        self._reported: Set[int] = set()

    # -- expression side -----------------------------------------------------
    def _consumptions(self, expr: ast.AST):
        """(node, key_name) for each jax.random sampler call consuming a bare
        Name key inside ``expr`` (nested lambdas/defs handled separately)."""
        out = []
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate scope (walk still descends; filter below)
            if not isinstance(node, ast.Call):
                continue
            canon = self.mod.canon(node.func) or ""
            if not canon.startswith("jax.random."):
                continue
            fname = canon.rsplit(".", 1)[-1]
            if fname in _SAMPLERS and node.args and isinstance(node.args[0], ast.Name):
                # skip if this call sits inside a nested function scope
                inner = self.mod.enclosing_function(node)
                outer = self.mod.enclosing_function(expr) if not isinstance(
                    expr, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ) else expr
                if inner is not None and inner is not outer and not isinstance(expr, ast.Module):
                    continue
                out.append((node, node.args[0].id))
        return out

    def _derivation_call(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            canon = self.mod.canon(expr.func) or ""
            if canon.startswith("jax.random.") and canon.rsplit(".", 1)[-1] in _DERIVERS:
                return canon
        return None

    # -- statement side ------------------------------------------------------
    def consume(self, state: Dict[str, str], node: ast.AST, name: str, in_second_loop_pass: bool):
        status = state.get(name)
        if status == "consumed":
            if id(node) in self._reported:
                return
            self._reported.add(id(node))
            if in_second_loop_pass:
                msg = (
                    f"PRNG key `{name}` is consumed again on the next loop iteration "
                    "without a jax.random.split — every iteration draws the same stream"
                )
                detail = f"loop-reuse:{name}"
            else:
                msg = (
                    f"PRNG key `{name}` is consumed by a second sampling call without an "
                    "intervening jax.random.split — the draws are identical/correlated"
                )
                detail = f"reuse:{name}"
            self.findings.append(self.mod.finding("prng", node, msg, detail))
        else:
            state[name] = "consumed"

    def eval_expr(self, state: Dict[str, str], expr: ast.AST, second_pass: bool):
        for node, name in self._consumptions(expr):
            self.consume(state, node, name, second_pass)

    def assign_targets(self, state: Dict[str, str], targets, value: ast.AST):
        derivation = self._derivation_call(value)
        names: List[str] = []
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(e.id for e in tgt.elts if isinstance(e, ast.Name))
        if derivation is not None:
            for n in names:
                state[n] = "fresh"
        elif isinstance(value, ast.Name) and value.id in state and len(names) == 1:
            state[names[0]] = state[value.id]
        else:
            for n in names:
                state.pop(n, None)

    def walk_block(self, stmts, state: Dict[str, str], second_pass: bool = False) -> bool:
        """Returns True if the block terminates (return/raise/continue/break)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.Return, ast.Raise)):
                if isinstance(stmt, ast.Return) and stmt.value is not None:
                    self.eval_expr(state, stmt.value, second_pass)
                return True
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return True
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.run_function(stmt)
                continue
            if isinstance(stmt, ast.Assign):
                self.eval_expr(state, stmt.value, second_pass)
                self.assign_targets(state, stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self.eval_expr(state, stmt.value, second_pass)
                self.assign_targets(state, [stmt.target], stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                self.eval_expr(state, stmt.value, second_pass)
            elif isinstance(stmt, ast.Expr):
                self.eval_expr(state, stmt.value, second_pass)
            elif isinstance(stmt, ast.If):
                self.eval_expr(state, stmt.test, second_pass)
                s_body = dict(state)
                s_else = dict(state)
                t_body = self.walk_block(stmt.body, s_body, second_pass)
                t_else = self.walk_block(stmt.orelse, s_else, second_pass)
                if t_body and t_else:
                    pass  # both paths leave; keep pre-state
                elif t_body:
                    state.clear()
                    state.update(s_else)
                elif t_else:
                    state.clear()
                    state.update(s_body)
                else:
                    merged = dict(s_else)
                    for k, v in s_body.items():
                        if v == "consumed" or merged.get(k) == "consumed":
                            merged[k] = "consumed"
                        else:
                            merged[k] = v
                    state.clear()
                    state.update(merged)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.eval_expr(state, stmt.iter, second_pass)
                self.assign_targets(state, [stmt.target], stmt.iter)
                terminated = self.walk_block(stmt.body, state, second_pass)
                if not terminated:
                    # second walk: anything still consumed from iteration one
                    # that gets consumed again is cross-iteration reuse. The
                    # loop target is re-assigned by the iteration protocol, so
                    # re-freshen it first (`for k in jax.random.split(key, n)`
                    # hands a NEW key to every iteration)
                    self.assign_targets(state, [stmt.target], stmt.iter)
                    self.walk_block(stmt.body, state, second_pass=True)
                self.walk_block(stmt.orelse, state, second_pass)
            elif isinstance(stmt, ast.While):
                self.eval_expr(state, stmt.test, second_pass)
                terminated = self.walk_block(stmt.body, state, second_pass)
                if not terminated:
                    self.walk_block(stmt.body, state, second_pass=True)
                self.walk_block(stmt.orelse, state, second_pass)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self.eval_expr(state, item.context_expr, second_pass)
                self.walk_block(stmt.body, state, second_pass)
            elif isinstance(stmt, ast.Try):
                self.walk_block(stmt.body, state, second_pass)
                for handler in stmt.handlers:
                    self.walk_block(handler.body, dict(state), second_pass)
                self.walk_block(stmt.orelse, state, second_pass)
                self.walk_block(stmt.finalbody, state, second_pass)
        return False

    def run_function(self, fn: ast.AST):
        state: Dict[str, str] = {}
        args = getattr(fn, "args", None)
        if args is not None:
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                if re.search(r"(^|_)(key|keys|rng)s?($|_)", a.arg) or a.arg.endswith("_key"):
                    state[a.arg] = "fresh"
        body = fn.body if isinstance(fn.body, list) else [ast.Return(value=fn.body)]
        self.walk_block(body, state)


def check_prng(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    scope = _PrngScope(mod)
    # module level (scripts) + every function, each as its own scope
    scope.walk_block(
        [s for s in mod.tree.body if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))],
        {},
    )
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _PrngScope(mod)
            inner._reported = scope._reported  # share dedupe across scopes
            inner.run_function(node)
            scope.findings.extend(inner.findings)
        elif isinstance(node, ast.Lambda):
            inner = _PrngScope(mod)
            inner._reported = scope._reported
            inner.run_function(node)
            scope.findings.extend(inner.findings)
    return scope.findings


# ---------------------------------------------------------------------------
# (b) retrace hazards
# ---------------------------------------------------------------------------


_MEMO_DECORATORS = {"functools.lru_cache", "functools.cache"}


def _result_is_cached(mod: ModuleInfo, jit_call: ast.Call, fn: ast.AST) -> bool:
    """True for the sanctioned builder pattern: the jit result is stored into
    a subscript (``cache[key] = fn``, directly or via a name) somewhere in
    the enclosing function, or the enclosing function is decorated with
    ``functools.lru_cache``/``functools.cache`` (matched canonically — a
    decorator merely *named* like a cache does not memoize)."""
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if (mod.canon(target) or "") in _MEMO_DECORATORS:
                return True
    parent = getattr(jit_call, "_gl_parent", None)
    assigned: Optional[str] = None
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Subscript):
            return True  # `cache[key] = jax.jit(...)` directly
        if isinstance(tgt, ast.Name):
            assigned = tgt.id
    if assigned is None or fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == assigned
                ):
                    return True
    return False


def check_retrace(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []
    jitted_names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_call(mod, node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jitted_names.add(tgt.id)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _is_jit_call(mod, node):
            continue
        # decorators are definition-time, not call-time: skip
        parent = getattr(node, "_gl_parent", None)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) and node in parent.decorator_list:
            continue
        fn = mod.enclosing_function(node)
        loops = mod.enclosing_loops(node)
        wrapped = node.args[0] if node.args else None
        if mod.canon(node.func) == "functools.partial" and len(node.args) >= 2:
            wrapped = node.args[1]
        wrapped_desc = None
        if isinstance(wrapped, ast.Lambda):
            wrapped_desc = "a fresh lambda"
        elif isinstance(wrapped, ast.Attribute):
            wrapped_desc = f"the bound method `{dotted_name(wrapped)}`"
        elif (
            isinstance(wrapped, ast.Call)
            and (mod.canon(wrapped.func) or "") in ("jax.vmap", "jax.pmap")
            and wrapped.args
            and isinstance(wrapped.args[0], (ast.Attribute, ast.Lambda))
        ):
            inner_name = dotted_name(wrapped.args[0]) or "<lambda>"
            wrapped_desc = f"a fresh vmap wrapper over `{inner_name}`"
        if loops:
            if _result_is_cached(mod, node, fn):
                continue  # cache-filling warm-up loop: one jit per cache key
            findings.append(
                mod.finding(
                    "retrace",
                    node,
                    "jax.jit called inside a loop: the wrapper (and its trace cache) is "
                    "rebuilt every iteration — hoist the jit out of the loop",
                    "jit-in-loop",
                )
            )
        elif wrapped_desc is not None and fn is not None and not _result_is_cached(mod, node, fn):
            findings.append(
                mod.finding(
                    "retrace",
                    node,
                    f"jax.jit over {wrapped_desc} inside a function: every call of the "
                    "enclosing function rebuilds the wrapper and re-traces — hoist it to "
                    "module scope, jit a named function, or cache the wrapper",
                    f"jit-fresh-callee:{wrapped_desc.split('`')[-2] if '`' in wrapped_desc else 'lambda'}",
                )
            )

    # f-string / str(...) arguments handed to a known-jitted callable: the
    # value becomes (or collides with) a static arg and re-traces per call
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
            continue
        if node.func.id not in jitted_names:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.JoinedStr) or (
                isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id in ("str", "repr")
            ):
                findings.append(
                    mod.finding(
                        "retrace",
                        arg,
                        f"f-string/str() argument to jitted `{node.func.id}`: a fresh "
                        "string per call re-traces on every invocation",
                        f"str-arg:{node.func.id}",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# (c) host-sync hazards
# ---------------------------------------------------------------------------


def check_host_sync(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []
    traced = _collect_traced(mod)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn, statics = _in_traced(mod, traced, node)

        # .item() — a device->host scalar sync wherever it runs under trace
        if (
            fn is not None
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            findings.append(
                mod.finding(
                    "host-sync",
                    node,
                    ".item() inside traced code forces a device->host sync (and fails "
                    "under jit) — keep the value on device",
                    "item",
                )
            )
            continue

        canon = mod.canon(node.func) or ""

        # np.asarray / np.array under trace: silently materializes the traced
        # value on host (ConcretizationError under jit, a sync under eager)
        if fn is not None and canon in ("numpy.asarray", "numpy.array"):
            findings.append(
                mod.finding(
                    "host-sync",
                    node,
                    f"{dotted_name(node.func)}() inside traced code pulls the value to "
                    "host — use jnp, or move the conversion outside the traced function",
                    "np-asarray",
                )
            )
            continue

        # float()/int()/bool() on non-static values under trace
        if (
            fn is not None
            and isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
        ):
            arg = node.args[0]
            if isinstance(arg, ast.Constant):
                continue
            if isinstance(arg, ast.Name) and arg.id in statics:
                continue
            # int(len(...)) / int(x.shape[i]) / int(x.ndim) are static shape
            # math, not value syncs
            if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name) and arg.func.id == "len":
                continue
            if isinstance(arg, ast.Subscript) and isinstance(arg.value, ast.Attribute) and arg.value.attr == "shape":
                continue
            if isinstance(arg, ast.Attribute) and arg.attr in ("ndim", "size"):
                continue
            findings.append(
                mod.finding(
                    "host-sync",
                    node,
                    f"{node.func.id}() on a traced value inside traced code — a "
                    "concretization/host-sync hazard; mark the argument static or keep "
                    "the math in jnp",
                    f"{node.func.id}-in-trace",
                )
            )
            continue

        # host-loop mode: float(helper(...)) / int(helper(...)) where helper
        # is a project function implemented in jax — a device round-trip per
        # loop iteration
        if (
            fn is None
            and isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int")
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Call)
            and mod.enclosing_loops(node)
        ):
            callee = node.args[0].func
            callee_name = callee.id if isinstance(callee, ast.Name) else None
            if callee_name and project.func_uses_jax.get(callee_name):
                findings.append(
                    mod.finding(
                        "host-sync",
                        node,
                        f"{node.func.id}({callee_name}(...)) inside a host loop: "
                        "dispatches a device computation and syncs its result every "
                        "iteration — compute it on host or batch it",
                        f"loop-sync:{callee_name}",
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# (d) donation opportunities
# ---------------------------------------------------------------------------


def _first_param_of(mod: ModuleInfo, project: ProjectInfo, scope: ast.AST, target: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Lambda):
        args = target.args
        params = list(args.posonlyargs) + list(args.args)
        return params[0].arg if params else None
    if isinstance(target, ast.Name):
        name = mod.name_aliases.get(target.id, target.id)
        local = _resolve_local_def(mod, scope, name)
        if local is not None:
            params = list(local.args.posonlyargs) + list(local.args.args)
            return params[0].arg if params else None
        # imported / aliased project function
        canon = mod.aliases.get(name, name)
        short = canon.rsplit(".", 1)[-1]
        return project.func_first_param.get(short)
    return None


def check_donation(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []

    def has_donation(kwargs: Dict[str, ast.AST]) -> bool:
        return "donate_argnums" in kwargs or "donate_argnames" in kwargs

    def statics_cover_first(kwargs: Dict[str, ast.AST]) -> bool:
        node = kwargs.get("static_argnums")
        if node is None:
            return False
        elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
        return any(isinstance(e, ast.Constant) and e.value == 0 for e in elts)

    # decorator form
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            dec = _jit_decoration(mod, node)
            if dec is None:
                continue
            kwargs = _jit_kwargs(mod, dec)
            params = list(node.args.posonlyargs) + list(node.args.args)
            first = params[0].arg if params else None
            if (
                first
                and _STATE_PARAM_RE.match(first)
                and not has_donation(kwargs)
                and not statics_cover_first(kwargs)
            ):
                findings.append(
                    mod.finding(
                        "donation",
                        node,
                        f"jitted `{node.name}` takes the state pytree `{first}` first but "
                        "does not donate it (donate_argnums=(0,)): the hot loop allocates "
                        "a fresh state buffer every call instead of updating in place",
                        f"undonated-state:{node.name}",
                    )
                )
        if not isinstance(node, ast.Call) or not _is_jit_call(mod, node):
            continue
        parent = getattr(node, "_gl_parent", None)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) and node in parent.decorator_list:
            continue
        kwargs = _jit_kwargs(mod, node)
        if has_donation(kwargs) or statics_cover_first(kwargs):
            continue
        wrapped = node.args[0] if node.args else None
        if mod.canon(node.func) == "functools.partial" and len(node.args) >= 2:
            wrapped = node.args[1]
        if wrapped is None:
            continue
        scope = mod.enclosing_function(node) or mod.tree
        first = _first_param_of(mod, project, scope, wrapped)
        if first and _STATE_PARAM_RE.match(first):
            wrapped_name = dotted_name(wrapped) or "<lambda>"
            findings.append(
                mod.finding(
                    "donation",
                    node,
                    f"jax.jit({wrapped_name}) wraps a step whose first arg `{first}` is a "
                    "state pytree but does not donate it (donate_argnums=(0,)): each call "
                    "allocates a fresh state instead of reusing the buffers",
                    f"undonated-state:{wrapped_name}",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# (e) sharding / axis-name hygiene
# ---------------------------------------------------------------------------


def check_axis_names(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []
    declared = project.axis_names
    if not declared:
        return findings

    def check_literal(node: ast.AST, context: str):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value not in declared:
                findings.append(
                    mod.finding(
                        "axis-name",
                        node,
                        f"axis name {node.value!r} in {context} matches no declared mesh "
                        f"axis (declared: {sorted(declared)}) — typo'd collectives fail "
                        "late or silently de-shard",
                        f"unknown-axis:{node.value}",
                    )
                )

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = mod.canon(node.func) or ""
        if canon in _COLLECTIVES:
            if len(node.args) >= 2:
                check_literal(node.args[1], canon.rsplit(".", 1)[-1])
            elif len(node.args) == 1 and canon.endswith("axis_index"):
                check_literal(node.args[0], "axis_index")
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    check_literal(kw.value, canon.rsplit(".", 1)[-1])
        elif canon.endswith("PartitionSpec") or canon == "jax.sharding.PartitionSpec":
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)):
                    for elt in arg.elts:
                        check_literal(elt, "PartitionSpec")
                else:
                    check_literal(arg, "PartitionSpec")
        else:
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    check_literal(kw.value, f"{canon or 'call'}(axis_name=...)")
    return findings


# ---------------------------------------------------------------------------
# (f) dtype leaks
# ---------------------------------------------------------------------------


def check_dtype(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []
    traced = _collect_traced(mod)

    for node in ast.walk(mod.tree):
        canon = mod.canon(node) if isinstance(node, (ast.Attribute,)) else None
        if canon in ("jax.numpy.float64", "jax.numpy.int64"):
            findings.append(
                mod.finding(
                    "dtype",
                    node,
                    f"{dotted_name(node)} reference: x64 dtypes re-promote the f32/bf16 "
                    "compute path (and require jax_enable_x64) — use 32-bit dtypes",
                    f"x64:{canon.rsplit('.', 1)[-1]}",
                )
            )
        if isinstance(node, ast.Attribute):
            canon_np = mod.canon(node)
            if canon_np in ("numpy.float64", "numpy.int64"):
                fn, _ = _in_traced(mod, traced, node)
                if fn is not None:
                    findings.append(
                        mod.finding(
                            "dtype",
                            node,
                            f"{dotted_name(node)} inside traced code: a strong-typed "
                            "64-bit numpy constant re-promotes bf16/f32 carries — use a "
                            "python scalar or an explicit 32-bit dtype",
                            f"np-x64:{canon_np.rsplit('.', 1)[-1]}",
                        )
                    )
        if isinstance(node, ast.Call):
            canon_call = mod.canon(node.func) or ""
            if canon_call.startswith(("jax.numpy.", "jax.")):
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("float64", "int64")
                    ):
                        findings.append(
                            mod.finding(
                                "dtype",
                                kw.value,
                                f"dtype={kw.value.value!r} on a jnp call: x64 dtypes "
                                "re-promote the f32/bf16 compute path",
                                f"dtype-str:{kw.value.value}",
                            )
                        )
            if canon_call == "jax.config.update" and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) and arg0.value == "jax_enable_x64":
                    findings.append(
                        mod.finding(
                            "dtype",
                            node,
                            "jax_enable_x64 flips every default dtype in the process to "
                            "64-bit — the bf16/f32 compute-path contract breaks globally",
                            "enable-x64",
                        )
                    )
    return findings


# ---------------------------------------------------------------------------
# (g) unsynced timing
# ---------------------------------------------------------------------------

#: wall-clock sources a benchmark region starts/ends with
_TIME_FUNCS = {
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.process_time",
}


def _collect_jitted_names(mod: ModuleInfo) -> Set[str]:
    """Names that are jitted callables in this module: ``x = jax.jit(...)``
    bindings and ``@jax.jit``-decorated defs."""
    jitted: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_call(mod, node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jitted.add(tgt.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decoration(mod, node) is not None:
                jitted.add(node.name)
    return jitted


def _contains_block_until_ready(mod: ModuleInfo, root: ast.AST) -> bool:
    for node in ast.walk(root):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "block_until_ready":
                return True
    return False


def check_timing(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    """jax dispatch is asynchronous: ``t0 = time.perf_counter(); jitted(...);
    dt = time.perf_counter() - t0`` measures how fast the host *enqueued* the
    work, not how long it ran. Flag every timing region (two or more
    ``time.*()`` reads in one scope) that contains calls to known-jitted
    callables but no ``block_until_ready`` — neither directly in the region
    nor inside a locally-defined helper the region calls."""
    findings: List[Finding] = []
    jitted = _collect_jitted_names(mod)
    if not jitted:
        return findings

    scopes: List[ast.AST] = [mod.tree]
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node)

    for scope in scopes:
        owner = scope if scope is not mod.tree else None
        time_calls = []
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if mod.enclosing_function(node) is not owner:
                continue  # nested defs are their own timing scopes
            if (mod.canon(node.func) or "") in _TIME_FUNCS:
                time_calls.append(node)
        if len(time_calls) < 2:
            continue
        first = min(c.lineno for c in time_calls)
        last = max(c.lineno for c in time_calls)

        region_jitted: List[str] = []
        synced = False
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            # only calls the region itself EXECUTES count — a nested def
            # merely *defined* between the clock reads neither dispatches
            # nor syncs until it is called (same owner filter as the
            # time-call scan above)
            if mod.enclosing_function(node) is not owner:
                continue
            if not (first <= getattr(node, "lineno", 0) <= last):
                continue
            if isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
                synced = True
                break
            if isinstance(node.func, ast.Name):
                name = mod.name_aliases.get(node.func.id, node.func.id)
                if name in jitted:
                    region_jitted.append(name)
                    continue
                # a locally-defined helper CALLED in the region contributes
                # what its body does: a block inside counts as the region's
                # sync (`once()` patterns), a jitted dispatch inside counts
                # as region jitted activity
                local = _resolve_local_def(mod, scope, name)
                if local is None:
                    continue
                if _contains_block_until_ready(mod, local):
                    synced = True
                    break
                for sub in ast.walk(local):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and mod.name_aliases.get(sub.func.id, sub.func.id) in jitted
                    ):
                        region_jitted.append(name)
                        break
        if synced or not region_jitted:
            continue
        callee = sorted(set(region_jitted))[0]
        findings.append(
            mod.finding(
                "timing",
                time_calls[-1],
                f"time.*() measurement around jitted `{callee}` with no "
                "block_until_ready() in the region: async dispatch makes this "
                "measure enqueue time, not compute time — block on the result "
                "before reading the clock",
                f"unsynced-timing:{callee}",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# (h) silently swallowed exceptions
# ---------------------------------------------------------------------------


#: broad exception classes whose handlers must not be silent
_SWALLOW_BROAD = {"Exception", "BaseException"}

#: a call whose final attribute is one of these counts as reporting the
#: failure: stdlib logging/warnings/print, traceback capture, and the
#: registry counters (a counted degradation is observable, not silent)
_SWALLOW_REPORTERS = {
    "print", "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log", "format_exc", "print_exc", "increment", "accumulate",
    "observe_max", "instant", "fail", "skip",
}


def _swallow_broad_handler(mod: ModuleInfo, handler: ast.ExceptHandler) -> Optional[str]:
    """The label to report for a broad handler, or None for a narrow one."""
    t = handler.type
    if t is None:
        return "bare except"
    types = list(t.elts) if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = mod.canon(node) or dotted_name(node) or ""
        if name.rpartition(".")[2] in _SWALLOW_BROAD:
            return f"except {name.rpartition('.')[2]}"
    return None


def _swallow_handler_reports(mod: ModuleInfo, handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                name = mod.canon(node.func) or dotted_name(node.func) or ""
                if name.rpartition(".")[2] in _SWALLOW_REPORTERS:
                    return True
                if "logg" in name.lower():  # logger.*/logging.* helpers
                    return True
    return False


def check_swallow(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        label = _swallow_broad_handler(mod, node)
        if label is None or _swallow_handler_reports(mod, node):
            continue
        findings.append(
            mod.finding(
                "swallow",
                node,
                f"{label} neither logs, counts, nor re-raises — the failure "
                "degrades silently; report it, re-raise, or annotate "
                "`# graftlint: allow(swallow): reason`",
                f"swallow:{label}",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# (i) telemetry wire-schema literals
# ---------------------------------------------------------------------------

#: the single module allowed to spell raw telemetry column indices — it OWNS
#: the wire schema (TELEMETRY_SCHEMA_VERSION and the column-layout constants)
_TELEMETRY_SCHEMA_OWNER = "evotorch_tpu/observability/devicemetrics.py"

#: bare names that carry the raw int32 telemetry wire even without
#: "telemetry" in their spelling (the decoded per-group/per-lane matrices)
_TELEMETRY_WIRE_NAMES = {"group_counts", "lane_counts"}


def _telemetry_wire_base(node: ast.Subscript) -> Optional[str]:
    """Dotted name of the subscripted expression when it looks like a raw
    telemetry wire array; unwraps chained subscripts (``telemetry[g][15]``)."""
    base: ast.AST = node.value
    while isinstance(base, ast.Subscript):
        base = base.value
    name = dotted_name(base)
    if name is None:
        return None
    if "telemetry" in name.lower():
        return name
    if name.rpartition(".")[2] in _TELEMETRY_WIRE_NAMES:
        return name
    return None


def check_telemetry_schema(mod: ModuleInfo, project: ProjectInfo) -> List[Finding]:
    """The telemetry matrix layout is versioned (schema v1 ``(6,)`` through
    v4 ``(G, 20)``); a hard-coded column index outside devicemetrics.py is a
    latent decode bug — it silently reads the wrong counter the next time a
    column is inserted. Index through the named layout constants / decoded
    :class:`GroupTelemetry` fields instead."""
    if mod.path == _TELEMETRY_SCHEMA_OWNER:
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Subscript):
            continue
        base = _telemetry_wire_base(node)
        if base is None:
            continue
        literals = sorted(
            {
                n.value
                for n in ast.walk(node.slice)
                if isinstance(n, ast.Constant)
                and isinstance(n.value, int)
                and not isinstance(n.value, bool)
            }
        )
        if not literals:
            continue
        lits = ",".join(str(v) for v in literals)
        findings.append(
            mod.finding(
                "telemetry-schema",
                node,
                f"hard-coded telemetry column index [{lits}] on `{base}`: the "
                "wire layout is schema-versioned and owned by "
                "observability/devicemetrics.py — index via its named layout "
                "constants or the decoded GroupTelemetry accessors, or "
                "annotate `# graftlint: allow(telemetry-schema): reason`",
                f"telemetry-index:{base}:[{lits}]",
            )
        )
    return findings


CHECKERS = {
    "prng": check_prng,
    "retrace": check_retrace,
    "host-sync": check_host_sync,
    "donation": check_donation,
    "axis-name": check_axis_names,
    "dtype": check_dtype,
    "timing": check_timing,
    "swallow": check_swallow,
    "telemetry-schema": check_telemetry_schema,
}
