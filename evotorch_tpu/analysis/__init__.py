"""Static + runtime correctness guardrails for the compiled hot paths.

- :mod:`~evotorch_tpu.analysis.graftlint` / ``checkers`` — the ``graftlint``
  AST lint suite (PRNG discipline, retrace hazards, host-sync hazards,
  donation opportunities, sharding/axis-name hygiene, dtype leaks). Run it
  with ``python -m evotorch_tpu.analysis`` (or ``scripts/lint.sh``); findings
  not in ``analysis/baseline.json`` fail the fast tier via
  ``tests/test_lint.py``.
- :mod:`~evotorch_tpu.analysis.retrace_sentinel` — a runtime compile counter
  (over ``jax.log_compiles``) asserting steady-state compile counts around
  the eval contracts and ask-tell loops.

See ``docs/static_analysis.md`` for the checker catalog and the baseline
workflow.
"""

from .graftlint import (  # noqa: F401
    Finding,
    apply_baseline,
    default_baseline_path,
    default_targets,
    lint_sources,
    load_baseline,
    run_lint,
    save_baseline,
)
from .retrace_sentinel import (  # noqa: F401
    CompileLog,
    RetraceError,
    assert_compiles,
    track_compiles,
)

__all__ = [
    "Finding",
    "run_lint",
    "lint_sources",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
    "default_targets",
    "default_baseline_path",
    "CompileLog",
    "RetraceError",
    "track_compiles",
    "assert_compiles",
]
