"""Runtime retrace sentinel: count XLA compilations over a code region.

The static side (:mod:`evotorch_tpu.analysis.checkers`) catches retrace
*hazards*; this is the runtime ground truth. jax's pxla emits exactly one
``"Compiling <name> with global shapes ..."`` log record per actual
trace+compile (executable-cache misses; persistent-compilation-cache hits
still log, which is correct — a dispatch-cache miss IS a retrace, the
persistent cache only makes it cheaper). The record is logged at DEBUG
level unconditionally (``jax.log_compiles`` merely promotes it to
WARNING), so the sentinel needs no jax config at all: one counting handler
on the emitting logger, with the logger level pinned to DEBUG. A canary
test (``tests/test_retrace_sentinel.py``) guards against the log format
drifting out from under us on a jax upgrade.

The handler is installed ONCE per process and fans records out to a
registry of active sinks, which makes compile counting **nestable and
thread-safe**: overlapping :func:`track_compiles` blocks each see every
compile (sink scope is the whole process — XLA compiles on whichever
thread dispatches first, so per-thread scoping would undercount), and a
permanent sink can promote the counting to session scope — that is how the
always-on observability registry's ``compiles`` counter works
(:func:`evotorch_tpu.observability.registry.ensure_compile_counter`).

Usage::

    with track_compiles() as log:
        step(state, key)
    assert log.count == 0            # steady state: nothing recompiled

    with assert_compiles(0):         # raises RetraceError otherwise
        for _ in range(3):
            state, scores = step(state, key)

Tests wrap the four eval contracts (budget / episodes / episodes_compact /
episodes_refill) and the jitted PGPE/SNES ask-tell steps with this, so any
change that starts retracing in steady state fails the fast tier.
"""

from __future__ import annotations

import contextlib
import logging
import re
import threading
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = [
    "CompileLog",
    "RetraceError",
    "track_compiles",
    "assert_compiles",
    "register_sink",
    "unregister_sink",
]

# the logger that emits exactly one "Compiling <name> with global shapes"
# record per trace+lower (jax 0.4.x: jax/_src/interpreters/pxla.py)
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_RE = re.compile(r"^Compiling (\S+) with global shapes")
# siblings jax.log_compiles turns chatty when a CALLER enabled it; quiet=True
# keeps them off the console while a tracking block is active
_NOISY_LOGGERS = ("jax._src.dispatch", "jax._src.compiler")


class RetraceError(AssertionError):
    """Raised by :func:`assert_compiles` when a region compiled more than its
    budget — a steady-state retrace."""


@dataclass(eq=False)
class CompileLog:
    """Names of the programs compiled while tracking was active.

    ``eq=False``: logs are registry entries, and registry membership is by
    IDENTITY — value equality (two logs that happened to observe the same
    records) once made ``unregister_sink`` remove the wrong sink (see its
    docstring)."""

    names: List[str] = field(default_factory=list)

    def record(self, name: str) -> None:
        """Sink protocol: called once per observed compile (any thread;
        ``list.append`` is atomic under the GIL)."""
        self.names.append(name)

    @property
    def count(self) -> int:
        return len(self.names)

    def count_matching(self, substring: str) -> int:
        return sum(1 for n in self.names if substring in n)


# ---------------------------------------------------------------------------
# the shared dispatch handler + sink registry
# ---------------------------------------------------------------------------

_LOCK = threading.RLock()
_SINKS: List = []  # objects with .record(name); mutated under _LOCK
_INSTALLED = False
_QUIET_DEPTH = 0
_QUIET_SAVED: Optional[list] = None
_QUIET_NULL = logging.NullHandler()


class _DispatchHandler(logging.Handler):
    """The one handler on the pxla logger: matches compile records and fans
    them out to every registered sink."""

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.match(record.getMessage())
        if m is None:
            return
        name = m.group(1)
        with _LOCK:
            sinks = list(_SINKS)
        for sink in sinks:
            sink.record(name)


def _ensure_installed() -> None:
    """Install the dispatch handler once: the pxla logger is pinned to DEBUG
    so the per-compile record (DEBUG-level without ``jax.log_compiles``)
    always reaches the handler, and propagation is turned off so the
    records feed the counter instead of the console — once the sentinel is
    in use, the sentinel owns this logger (``jax.log_compiles`` console
    chatter from it is intentionally absorbed; the counting is the
    observable)."""
    global _INSTALLED
    with _LOCK:
        if _INSTALLED:
            return
        logger = logging.getLogger(_PXLA_LOGGER)
        logger.addHandler(_DispatchHandler())
        if logger.level == logging.NOTSET or logger.level > logging.DEBUG:
            logger.setLevel(logging.DEBUG)
        logger.propagate = False
        _INSTALLED = True


def register_sink(sink) -> None:
    """Add a permanent sink (an object with ``record(name: str)``) that sees
    every subsequent compile — the session-wide promotion of
    :class:`CompileLog`. Thread-safe; compose freely with
    :func:`track_compiles` blocks."""
    _ensure_installed()
    with _LOCK:
        _SINKS.append(sink)


def unregister_sink(sink) -> None:
    """Remove a sink by IDENTITY, never equality: ``list.remove`` removes
    the first ``==`` element, and two value-equal sinks (e.g. nested
    ``CompileLog``s that observed the same records — the common case for
    overlapping blocks) would make one block's exit silently unregister
    the OTHER block's sink, which then misses every later compile."""
    with _LOCK:
        for i, registered in enumerate(_SINKS):
            if registered is sink:
                del _SINKS[i]
                return


def _push_quiet() -> None:
    """Refcounted console silencing of the SIBLING loggers (the pxla logger
    itself is owned outright by the handler install): while any quiet
    tracking block is active, a caller-enabled ``jax.log_compiles`` cannot
    spray dispatch/compiler chatter. A NullHandler keeps the handler-less
    siblings off ``logging.lastResort``."""
    global _QUIET_DEPTH, _QUIET_SAVED
    with _LOCK:
        if _QUIET_DEPTH == 0:
            saved = []
            for name in _NOISY_LOGGERS:
                lg = logging.getLogger(name)
                saved.append((lg, lg.propagate))
                lg.propagate = False
                lg.addHandler(_QUIET_NULL)
            _QUIET_SAVED = saved
        _QUIET_DEPTH += 1


def _pop_quiet() -> None:
    global _QUIET_DEPTH, _QUIET_SAVED
    with _LOCK:
        _QUIET_DEPTH -= 1
        if _QUIET_DEPTH == 0 and _QUIET_SAVED is not None:
            for lg, propagate in _QUIET_SAVED:
                lg.propagate = propagate
                lg.removeHandler(_QUIET_NULL)
            _QUIET_SAVED = None


@contextlib.contextmanager
def track_compiles(*, quiet: bool = True):
    """Context manager yielding a :class:`CompileLog` that records every XLA
    compilation inside the block. Nestable (every active block sees every
    compile) and thread-safe (the sink registry is shared and locked; sink
    scope is the process, not the thread). ``quiet=True`` (default) keeps
    any caller-enabled log_compiles chatter off the console while
    tracking."""
    log = CompileLog()
    register_sink(log)
    if quiet:
        _push_quiet()
    try:
        yield log
    finally:
        if quiet:
            _pop_quiet()
        unregister_sink(log)


@contextlib.contextmanager
def assert_compiles(
    at_most: int = 0, *, match: Optional[str] = None, quiet: bool = True
):
    """Assert the block compiles at most ``at_most`` programs (optionally
    only counting program names containing ``match``); raises
    :class:`RetraceError` listing the offending programs otherwise.

    ``assert_compiles(0)`` around a warmed-up hot loop is the steady-state
    contract: the executables are cached, nothing re-traces."""
    with track_compiles(quiet=quiet) as log:
        yield log
    names = log.names if match is None else [n for n in log.names if match in n]
    if len(names) > at_most:
        raise RetraceError(
            f"expected at most {at_most} compilation(s)"
            + (f" matching {match!r}" if match else "")
            + f", observed {len(names)}: {names}"
        )
