"""Runtime retrace sentinel: count XLA compilations over a code region.

The static side (:mod:`evotorch_tpu.analysis.checkers`) catches retrace
*hazards*; this is the runtime ground truth. It rides on ``jax.log_compiles``:
jax logs one ``"Compiling <name> with global shapes ..."`` record per actual
trace+compile (executable-cache misses; persistent-compilation-cache hits
still log, which is correct — a dispatch-cache miss IS a retrace, the
persistent cache only makes it cheaper). We attach a counting handler to the
emitting logger, so the sentinel needs no private jax APIs beyond the logger
name, and a canary test (``tests/test_retrace_sentinel.py``) guards against
the log format drifting out from under us on a jax upgrade.

Usage::

    with track_compiles() as log:
        step(state, key)
    assert log.count == 0            # steady state: nothing recompiled

    with assert_compiles(0):         # raises RetraceError otherwise
        for _ in range(3):
            state, scores = step(state, key)

Tests wrap the four eval contracts (budget / episodes / episodes_compact /
episodes_refill) and the jitted PGPE/SNES ask-tell steps with this, so any
change that starts retracing in steady state fails the fast tier.
"""

from __future__ import annotations

import contextlib
import logging
import re
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = ["CompileLog", "RetraceError", "track_compiles", "assert_compiles"]

# the logger that emits exactly one "Compiling <name> with global shapes"
# record per trace+lower (jax 0.4.x: jax/_src/interpreters/pxla.py)
_PXLA_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_RE = re.compile(r"^Compiling (\S+) with global shapes")
# siblings that log_compiles also turns chatty; silenced under quiet=True
_NOISY_LOGGERS = ("jax._src.dispatch", "jax._src.compiler")


class RetraceError(AssertionError):
    """Raised by :func:`assert_compiles` when a region compiled more than its
    budget — a steady-state retrace."""


@dataclass
class CompileLog:
    """Names of the programs compiled while tracking was active."""

    names: List[str] = field(default_factory=list)

    @property
    def count(self) -> int:
        return len(self.names)

    def count_matching(self, substring: str) -> int:
        return sum(1 for n in self.names if substring in n)


class _CountingHandler(logging.Handler):
    def __init__(self, log: CompileLog):
        super().__init__(level=logging.DEBUG)
        self._log = log

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.match(record.getMessage())
        if m:
            self._log.names.append(m.group(1))


@contextlib.contextmanager
def track_compiles(*, quiet: bool = True):
    """Context manager yielding a :class:`CompileLog` that records every XLA
    compilation inside the block. ``quiet=True`` (default) keeps the
    log_compiles chatter off the console while tracking."""
    import jax

    log = CompileLog()
    handler = _CountingHandler(log)
    logger = logging.getLogger(_PXLA_LOGGER)
    old_level = logger.level
    old_propagate = logger.propagate
    noisy = [logging.getLogger(n) for n in _NOISY_LOGGERS]
    old_noisy = [lg.propagate for lg in noisy]
    # a NullHandler as well as propagate=False: a handler-less, non-
    # propagating logger falls through to logging.lastResort (stderr)
    null = logging.NullHandler()
    logger.addHandler(handler)
    logger.setLevel(logging.DEBUG)
    if quiet:
        logger.propagate = False
        for lg in noisy:
            lg.propagate = False
            lg.addHandler(null)
    try:
        with jax.log_compiles():
            yield log
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)
        logger.propagate = old_propagate
        for lg, prop in zip(noisy, old_noisy):
            lg.propagate = prop
            if quiet:
                lg.removeHandler(null)


@contextlib.contextmanager
def assert_compiles(
    at_most: int = 0, *, match: Optional[str] = None, quiet: bool = True
):
    """Assert the block compiles at most ``at_most`` programs (optionally
    only counting program names containing ``match``); raises
    :class:`RetraceError` listing the offending programs otherwise.

    ``assert_compiles(0)`` around a warmed-up hot loop is the steady-state
    contract: the executables are cached, nothing re-traces."""
    with track_compiles(quiet=quiet) as log:
        yield log
    names = log.names if match is None else [n for n in log.names if match in n]
    if len(names) > at_most:
        raise RetraceError(
            f"expected at most {at_most} compilation(s)"
            + (f" matching {match!r}" if match else "")
            + f", observed {len(names)}: {names}"
        )
