"""graftlint — AST-based JAX correctness/performance lint for this repo.

The framework's value proposition is that evolution runs as *compiled XLA
programs* (functional ask-tell states, jitted distribution math, one
``lax.while_loop`` rollout), so its worst bugs are the ones Python never
raises: silent retraces that turn a flagship step into a recompile storm,
PRNG key reuse that correlates "independent" samples, host-device syncs
hiding in hot loops, dtype/axis-name drift across ``shard_map`` boundaries.
This module is the machinery: finding/ baseline bookkeeping, module parsing
(import-alias resolution, symbol tables), and the runner. The checkers
themselves live in :mod:`evotorch_tpu.analysis.checkers`; the runtime
counterpart (compile counting) in
:mod:`evotorch_tpu.analysis.retrace_sentinel`.

Pure stdlib (``ast``/``json``) — linting never imports jax, so it runs in
milliseconds per file and cannot hang on an unhealthy TPU tunnel.

Baselines: a finding's :attr:`Finding.signature` deliberately excludes the
line number, so unrelated edits moving code around do not churn
``baseline.json``; matching is multiset-aware (two identical-signature
findings need two baseline entries).

Scoped exemptions: a ``# graftlint: allow(<checker>): <reason>`` comment on
(or immediately above) the offending line suppresses that checker there —
the in-code alternative to a baseline entry for *intentional* violations
(e.g. the host pipeline's swap-point syncs). The reason is mandatory: a
reasonless allow is itself reported as a ``lint-allow`` finding.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "ModuleInfo",
    "ProjectInfo",
    "run_lint",
    "lint_sources",
    "load_baseline",
    "save_baseline",
    "apply_baseline",
    "default_targets",
    "default_baseline_path",
    "repo_root",
    "scoped_allows",
]


# ---------------------------------------------------------------------------
# findings + baseline
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``detail`` is the stable, line-independent part of
    the identity (typically the offending symbol/pattern), so baselines
    survive unrelated line drift."""

    checker: str
    path: str  # repo-relative posix path
    line: int
    symbol: str  # enclosing function qualname, or "<module>"
    message: str
    detail: str = ""

    @property
    def signature(self) -> str:
        return f"{self.path}::{self.checker}::{self.symbol}::{self.detail}"

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.symbol}: {self.message}"

    def to_json(self) -> dict:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "detail": self.detail,
            "signature": self.signature,
        }


def load_baseline(path) -> List[dict]:
    """Baseline file: ``{"findings": [{"signature": ..., "reason": ...}]}``."""
    data = json.loads(Path(path).read_text())
    return list(data.get("findings", []))


def save_baseline(path, findings: Sequence[Finding], *, reasons: Optional[dict] = None):
    reasons = reasons or {}
    entries = [
        {
            "signature": f.signature,
            "reason": reasons.get(f.signature, ""),
            # message kept for human readers only; matching is by signature
            "message": f.message,
        }
        for f in sorted(findings, key=lambda f: (f.path, f.checker, f.line))
    ]
    Path(path).write_text(json.dumps({"findings": entries}, indent=2) + "\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Sequence[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Split findings into (new, stale-baseline-entries). Multiset matching:
    each baseline entry absorbs at most one finding with its signature."""
    budget = Counter(e["signature"] for e in baseline)
    new: List[Finding] = []
    for f in findings:
        if budget.get(f.signature, 0) > 0:
            budget[f.signature] -= 1
        else:
            new.append(f)
    stale_sigs = Counter()
    for sig, n in budget.items():
        if n > 0:
            stale_sigs[sig] = n
    stale = []
    seen: Counter = Counter()
    for e in baseline:
        sig = e["signature"]
        if seen[sig] < stale_sigs.get(sig, 0):
            stale.append(e)
            seen[sig] += 1
    return new, stale


# ---------------------------------------------------------------------------
# scoped allow-comments
# ---------------------------------------------------------------------------

#: `# graftlint: allow(checker[, checker...])` with an optional `: reason`
_ALLOW_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*([a-z0-9_\-\s,]+?)\s*\)\s*(?::\s*(\S.*))?$"
)


def scoped_allows(path: str, source: str) -> Tuple[Dict[int, set], List[Finding]]:
    """Parse ``# graftlint: allow(...)`` comments (real COMMENT tokens only —
    allow-syntax inside string literals is inert). Returns
    ``({line: {checker, ...}}, reasonless-allow findings)``. A trailing allow
    covers its own line; a standalone allow-comment line covers the next
    line — never both, so one allow cannot silently wave through an
    adjacent, unrelated violation."""
    allows: Dict[int, set] = {}
    problems: List[Finding] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return allows, problems  # unparsable source is reported elsewhere
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ALLOW_RE.search(tok.string)
        if m is None:
            continue
        lineno = tok.start[0]
        checkers = {c.strip() for c in m.group(1).split(",") if c.strip()}
        if not m.group(2):
            problems.append(
                Finding(
                    checker="lint-allow",
                    path=path,
                    line=lineno,
                    symbol="<comment>",
                    message=(
                        "graftlint allow-comment without a reason — write"
                        " `# graftlint: allow(<checker>): <why this is"
                        " intentional>`"
                    ),
                    detail="missing-reason",
                )
            )
            continue
        trailing = bool(tok.line[: tok.start[1]].strip())  # code before the '#'
        covered = lineno if trailing else lineno + 1
        allows.setdefault(covered, set()).update(checkers)
    return allows, problems


def _apply_scoped_allows(
    findings: List[Finding], allows_by_path: Dict[str, Dict[int, set]]
) -> List[Finding]:
    kept = []
    for f in findings:
        allowed = allows_by_path.get(f.path, {}).get(f.line, ())
        if f.checker not in allowed:
            kept.append(f)
    return kept


# ---------------------------------------------------------------------------
# module / project models
# ---------------------------------------------------------------------------


def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._gl_parent = node  # type: ignore[attr-defined]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class ModuleInfo:
    path: str  # repo-relative posix
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    # top-level function defs (incl. simple `x = y` aliases of them)
    defs: Dict[str, ast.AST] = field(default_factory=dict)
    name_aliases: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, source: str) -> "ModuleInfo":
        tree = ast.parse(source, filename=path)
        _attach_parents(tree)
        info = cls(path=path, tree=tree)
        info._collect_imports()
        info._collect_defs()
        return info

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def _collect_defs(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node
        # simple name aliases are collected module-WIDE (bench drivers pick
        # their ask/tell implementations inside main()); first binding wins
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                # `tell = pgpe_tell` / `ask, tell = pgpe_ask, pgpe_tell` /
                # chained `a = b = pgpe_tell`
                for target in node.targets:
                    if (
                        isinstance(target, ast.Tuple)
                        and isinstance(node.value, ast.Tuple)
                        and len(target.elts) == len(node.value.elts)
                    ):
                        pairs = zip(target.elts, node.value.elts)
                    else:
                        pairs = [(target, node.value)]
                    for tgt, val in pairs:
                        if isinstance(tgt, ast.Name) and isinstance(val, ast.Name):
                            self.name_aliases.setdefault(tgt.id, val.id)

    def canon(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, with the leading
        segment expanded through this module's import aliases
        (``jnp.asarray`` -> ``jax.numpy.asarray``)."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        expanded = self.aliases.get(head, head)
        return f"{expanded}.{rest}" if rest else expanded

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = getattr(node, "_gl_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = getattr(cur, "_gl_parent", None)
        return None

    def symbol_for(self, node: ast.AST) -> str:
        names = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                names.append("<lambda>")
            cur = getattr(cur, "_gl_parent", None)
        return ".".join(reversed(names)) if names else "<module>"

    def enclosing_loops(self, node: ast.AST) -> List[ast.AST]:
        """Loops strictly containing ``node``, innermost-first, stopping at
        the enclosing function boundary."""
        loops = []
        cur = getattr(node, "_gl_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While)):
                loops.append(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
            cur = getattr(cur, "_gl_parent", None)
        return loops

    def finding(self, checker: str, node: ast.AST, message: str, detail: str) -> Finding:
        return Finding(
            checker=checker,
            path=self.path,
            line=getattr(node, "lineno", 0),
            symbol=self.symbol_for(node),
            message=message,
            detail=detail,
        )


@dataclass
class ProjectInfo:
    modules: List[ModuleInfo] = field(default_factory=list)
    #: mesh axis names declared anywhere (Mesh(..., axis_names=...),
    #: make_mesh({...}) keys, default_mesh((...)), `axis_name="..."` defaults)
    axis_names: set = field(default_factory=set)
    #: module-level function name -> first positional parameter name
    func_first_param: Dict[str, str] = field(default_factory=dict)
    #: module-level function name -> body contains jax/jnp operations
    func_uses_jax: Dict[str, bool] = field(default_factory=dict)

    @classmethod
    def build(cls, modules: Iterable[ModuleInfo]) -> "ProjectInfo":
        project = cls(modules=list(modules))
        for mod in project.modules:
            project._collect_symbols(mod)
            project._collect_axis_names(mod)
        return project

    def _collect_symbols(self, mod: ModuleInfo) -> None:
        for name, node in mod.defs.items():
            args = node.args
            params = list(args.posonlyargs) + list(args.args)
            if params and params[0].arg not in ("self", "cls"):
                self.func_first_param.setdefault(name, params[0].arg)
            uses = False
            for sub in ast.walk(node):
                canon = mod.canon(sub) if isinstance(sub, (ast.Name, ast.Attribute)) else None
                if canon and (canon == "jax" or canon.startswith(("jax.", "jax_"))):
                    uses = True
                    break
            if uses:
                self.func_uses_jax[name] = True

    def _collect_axis_names(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                canon = mod.canon(node.func) or ""
                tail = canon.rsplit(".", 1)[-1]
                if tail == "Mesh":
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            self._add_str_elts(kw.value)
                    if len(node.args) >= 2:
                        self._add_str_elts(node.args[1])
                elif tail == "make_mesh" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Dict):
                        for k in arg.keys:
                            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                                self.axis_names.add(k.value)
                elif tail == "default_mesh" and node.args:
                    self._add_str_elts(node.args[0])
            elif isinstance(node, ast.Assign):
                # a module-level `MESH_AXES = ("pop", "model")` declaration
                # (parallel/mesh.py) is the canonical axis registry: every
                # name it lists is a known axis, so new axes are introduced
                # by declaration, not by growing the lint baseline
                for target in node.targets:
                    if isinstance(target, ast.Name) and target.id == "MESH_AXES":
                        self._add_str_elts(node.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                params = list(args.posonlyargs) + list(args.args)
                defaults = list(args.defaults)
                # align defaults to the tail of params
                pairs = list(zip(params[len(params) - len(defaults):], defaults))
                pairs += [
                    (p, d) for p, d in zip(args.kwonlyargs, args.kw_defaults) if d is not None
                ]
                for param, default in pairs:
                    if param.arg in ("axis_name", "axis_names"):
                        self._add_str_elts(default)

    def _add_str_elts(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    self.axis_names.add(elt.value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            self.axis_names.add(node.value)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def repo_root() -> Path:
    """The repository root, assuming the canonical layout
    ``<root>/evotorch_tpu/analysis/graftlint.py``."""
    return Path(__file__).resolve().parents[2]


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def default_targets(root: Optional[Path] = None) -> List[Path]:
    """The gated lint surface: the package, the bench drivers, the examples,
    the dryrun entry and the python scripts."""
    root = Path(root) if root is not None else repo_root()
    targets = [root / "evotorch_tpu", root / "examples"]
    targets += sorted(root.glob("bench*.py"))
    entry = root / "__graft_entry__.py"
    if entry.exists():
        targets.append(entry)
    targets += sorted((root / "scripts").glob("*.py"))
    return [t for t in targets if t.exists()]


def _iter_py_files(targets: Iterable[Path]) -> Iterable[Path]:
    for target in targets:
        target = Path(target)
        if target.is_dir():
            for p in sorted(target.rglob("*.py")):
                if "__pycache__" not in p.parts:
                    yield p
        elif target.suffix == ".py":
            yield target


def lint_sources(
    sources: Dict[str, str], *, checkers: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Lint in-memory sources ``{relpath: source}`` — the unit-test entry
    point (the file runner below funnels through this)."""
    from . import checkers as checker_mod

    modules = []
    findings: List[Finding] = []
    allows_by_path: Dict[str, Dict[int, set]] = {}
    for path, src in sources.items():
        allows, allow_problems = scoped_allows(path, src)
        allows_by_path[path] = allows
        findings.extend(allow_problems)
        try:
            modules.append(ModuleInfo.parse(path, src))
        except SyntaxError as e:
            findings.append(
                Finding(
                    checker="parse",
                    path=path,
                    line=e.lineno or 0,
                    symbol="<module>",
                    message=f"syntax error: {e.msg}",
                    detail="syntax-error",
                )
            )
    project = ProjectInfo.build(modules)
    for mod in project.modules:
        for name, check in checker_mod.CHECKERS.items():
            if checkers is not None and name not in checkers:
                continue
            findings.extend(check(mod, project))
    findings = _apply_scoped_allows(findings, allows_by_path)
    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings


def run_lint(
    targets: Optional[Sequence[Path]] = None,
    *,
    root: Optional[Path] = None,
    checkers: Optional[Sequence[str]] = None,
) -> List[Finding]:
    root = Path(root) if root is not None else repo_root()
    paths = list(targets) if targets else default_targets(root)
    sources: Dict[str, str] = {}
    for p in _iter_py_files(paths):
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = p.as_posix()
        sources[rel] = p.read_text()
    return lint_sources(sources, checkers=checkers)
