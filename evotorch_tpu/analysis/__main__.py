"""``python -m evotorch_tpu.analysis`` — run graftlint over the repo.

Exit status: 0 when every finding is baselined (and no baseline entry is
stale), 1 otherwise. ``--write-baseline`` regenerates ``baseline.json`` from
the current findings (use when grandfathering; burning the baseline down is
the intended direction).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .graftlint import (
    apply_baseline,
    default_baseline_path,
    default_targets,
    load_baseline,
    run_lint,
    save_baseline,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m evotorch_tpu.analysis",
        description="graftlint: JAX correctness/performance static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the gated repo surface)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file (default: {default_baseline_path()})",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    parser.add_argument(
        "--checkers", type=str, default=None,
        help="comma-separated subset of checkers to run",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    checkers = args.checkers.split(",") if args.checkers else None
    findings = run_lint(args.paths or None, checkers=checkers)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        if args.paths or checkers:
            # a restricted run sees only part of the linted surface; writing
            # it out would erase every baseline entry (and reason) outside
            # that scope
            print(
                "--write-baseline requires a full run (no explicit paths, "
                "no --checkers): a partial rewrite would drop the rest of "
                "the baseline",
                file=sys.stderr,
            )
            return 2
        reasons = {}
        if baseline_path.exists():
            reasons = {
                e["signature"]: e.get("reason", "")
                for e in load_baseline(baseline_path)
            }
        save_baseline(baseline_path, findings, reasons=reasons)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    if args.no_baseline or not baseline_path.exists():
        new, stale = list(findings), []
    else:
        new, stale = apply_baseline(findings, load_baseline(baseline_path))
        if args.paths or checkers:
            # a restricted run cannot see the whole baselined surface, so
            # "stale" would be meaningless — only the full default run
            # enforces baseline hygiene
            stale = []

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in new],
                    "baselined": len(findings) - len(new),
                    "stale_baseline": [e["signature"] for e in stale],
                }
            )
        )
    else:
        for f in new:
            print(f.format())
        for e in stale:
            print(f"STALE baseline entry (no longer found — remove it): {e['signature']}")
        n_base = len(findings) - len(new)
        print(
            f"graftlint: {len(new)} finding(s)"
            + (f", {n_base} baselined" if n_base else "")
            + (f", {len(stale)} stale baseline entr(y/ies)" if stale else ""),
            file=sys.stderr,
        )
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
