"""Sharded ES-gradient estimation: the TPU form of the reference's
distributed mode.

Reference behavior (``core.py:2762-3073`` + ``gaussian.py:199-272``): each Ray
actor samples its own sub-population from the (broadcast) distribution,
evaluates it, ranks *locally*, computes local gradients, and the main process
averages the per-actor gradients weighted by sub-population size. Here the
same dataflow is one SPMD program: each mesh shard samples ``popsize/shards``
solutions with a device-unique key, evaluates and ranks locally, computes
local gradients, and a ``pmean`` over the population axis produces the
(equal-weight, since shards are equal-sized) average on every device.
"""

from __future__ import annotations

from typing import Callable, Optional, Type

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..tools.lowrank import dense_values
from ..tools.ranking import rank
from .mesh import default_mesh

__all__ = ["make_sharded_grad_estimator"]


def make_sharded_grad_estimator(
    distribution_class: Type,
    fitness_func: Callable,
    *,
    objective_sense: str,
    ranking_method: str = "centered",
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
    with_aux: bool = False,
    lowrank_rank: Optional[int] = None,
) -> Callable:
    """Build ``g(key, num_solutions, parameters) -> grads`` where the
    sample/evaluate/rank/grad pipeline runs sharded over the mesh and the
    returned gradient dict is the pmean across shards (replicated on all
    devices).

    ``num_solutions`` is the *global* population size and must be divisible by
    the mesh axis size (and the local size must be even for symmetric
    distributions).

    With ``with_aux=True`` the estimator returns ``(grads, aux)`` where
    ``aux["mean_eval"]`` is the population-mean fitness (the pmean of the
    shard-local means — what the reference's main process reconstructs from
    the per-actor ``mean_eval`` entries, ``gaussian.py:246-272``).

    With ``lowrank_rank`` each shard samples its own factored (low-rank)
    sub-population — per-shard basis, the analog of per-actor independent
    sampling — and computes its gradients from the factors in O(L * rank);
    only the fitness evaluation materializes the dense shard-local matrix
    (plain fitness functions consume dense rows)."""
    if mesh is None:
        mesh = default_mesh((axis_name,))
    n_shards = mesh.shape[axis_name]
    higher_is_better = {"max": True, "min": False}[objective_sense]

    # one jitted shard_map program per (local popsize, static params): repeated
    # calls must hit JAX's dispatch cache instead of retracing every generation
    compiled: dict = {}

    def _build(local_popsize: int, static_items: tuple):
        static_params = dict(static_items)

        def local(key, array_params):
            parameters = {**array_params, **static_params}
            my_key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
            if lowrank_rank is not None:
                samples = distribution_class._sample_lowrank(
                    my_key, parameters, local_popsize, lowrank_rank
                )
                fitnesses = fitness_func(dense_values(samples))
            else:
                samples = distribution_class._sample(my_key, parameters, local_popsize)
                fitnesses = fitness_func(samples)
            weights = rank(fitnesses, ranking_method, higher_is_better=higher_is_better)
            grads = distribution_class._compute_gradients(
                parameters, samples, weights, ranking_method
            )
            out = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name), grads
            )
            if with_aux:
                aux = {"mean_eval": jax.lax.pmean(jnp.mean(fitnesses), axis_name)}
                if lowrank_rank is not None:
                    # each shard's basis rides out stacked along the pop axis
                    # (shard i's rows at [i*L:(i+1)*L]) so the caller can run
                    # the subspace-exhaustion diagnostic on a representative
                    # per-shard basis without an extra collective
                    aux["basis"] = samples.basis
                return out, aux
            return out

        aux_specs = {"mean_eval": P()}
        if lowrank_rank is not None:
            aux_specs["basis"] = P(axis_name)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=mesh,
                in_specs=(P(), P()),
                out_specs=(P(), aux_specs) if with_aux else P(),
                check_vma=False,
            )
        )

    def estimator(key, num_solutions: int, parameters: dict):
        num_solutions = int(num_solutions)
        if num_solutions % n_shards != 0:
            raise ValueError(
                f"num_solutions={num_solutions} must be divisible by the mesh axis size {n_shards}"
            )
        local_popsize = num_solutions // n_shards

        # strings ("divide_mu_grad_by", ...) and structural floats
        # ("parenthood_ratio") are not JAX types: close over them statically
        static_params = {
            k: v
            for k, v in parameters.items()
            if isinstance(v, str) or k == "parenthood_ratio"
        }
        array_params = {k: v for k, v in parameters.items() if k not in static_params}

        cache_key = (local_popsize, tuple(sorted(static_params.items())))
        fn = compiled.get(cache_key)
        if fn is None:
            fn = compiled[cache_key] = _build(local_popsize, cache_key[1])
        return fn(key, array_params)

    return estimator
