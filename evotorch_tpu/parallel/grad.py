"""Sharded ES-gradient estimation: the TPU form of the reference's
distributed mode.

Default GSPMD: the sample/evaluate/rank/grad pipeline is written ONCE as the
global program — sample the full population, rank GLOBALLY, compute the
gradients — with the sample matrix pinned to the mesh's population layout;
XLA partitions the math and inserts the reductions. Global ranking is the
reference's SINGLE-PROCESS semantics (``gaussian.py:199-272`` without the
actor split), so the estimate is exactly what a one-device run computes, at
any mesh shape and ANY population size (no divisibility constraint — GSPMD
handles uneven layouts).

``use_shard_map=True`` / ``EVOTORCH_SHARD_MAP=1`` keeps the pre-GSPMD
explicit form, which reproduces the reference's DISTRIBUTED-mode semantics
(``core.py:2762-3073``): each shard samples its own sub-population with a
device-unique key, ranks *locally*, computes local gradients, and a ``pmean``
averages them — per-actor local ranking is a semantic, not just a layout
(rank weights depend on the cohort), which is why the knob preserves it.
"""

from __future__ import annotations

from typing import Callable, Optional, Type

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tools.lowrank import dense_values
from ..tools.ranking import rank
from .evaluate import _use_shard_map, population_spec
from .mesh import default_mesh

__all__ = ["make_sharded_grad_estimator"]


def make_sharded_grad_estimator(
    distribution_class: Type,
    fitness_func: Callable,
    *,
    objective_sense: str,
    ranking_method: str = "centered",
    mesh: Optional[Mesh] = None,
    axis_name: str = "pop",
    with_aux: bool = False,
    lowrank_rank: Optional[int] = None,
    use_shard_map: Optional[bool] = None,
) -> Callable:
    """Build ``g(key, num_solutions, parameters) -> grads`` where the
    sample/evaluate/rank/grad pipeline runs sharded over the mesh and the
    returned gradient dict is replicated on all devices.

    Default GSPMD (global ranking = the reference's single-process
    semantics): ``num_solutions`` may be ANY size. Under the
    ``use_shard_map`` compat knob (the reference's distributed per-actor
    local-ranking semantics) it must be divisible by the mesh axis size (and
    the local size even for symmetric distributions).

    With ``with_aux=True`` the estimator returns ``(grads, aux)`` where
    ``aux["mean_eval"]`` is the population-mean fitness (what the
    reference's main process reconstructs from the per-actor ``mean_eval``
    entries, ``gaussian.py:246-272``).

    With ``lowrank_rank`` the population is sampled in factored (low-rank)
    form and the gradients come from the factors in O(L * rank); only the
    fitness evaluation materializes the dense matrix (plain fitness
    functions consume dense rows). Under the compat knob each shard samples
    its own basis (per-actor independent sampling)."""
    if mesh is None:
        mesh = default_mesh((axis_name,))
    higher_is_better = {"max": True, "min": False}[objective_sense]
    legacy = _use_shard_map(use_shard_map)
    n_shards = mesh.shape[axis_name] if legacy else None
    pop_sharding = NamedSharding(mesh, population_spec(mesh))

    # one jitted program per (popsize, static params): repeated calls must
    # hit JAX's dispatch cache instead of retracing every generation
    compiled: dict = {}

    def _build_global(num_solutions: int, static_items: tuple):
        static_params = dict(static_items)

        def fn(key, array_params):
            parameters = {**array_params, **static_params}
            if lowrank_rank is not None:
                samples = distribution_class._sample_lowrank(
                    key, parameters, num_solutions, lowrank_rank
                )
                samples = samples._replace(
                    coeffs=jax.lax.with_sharding_constraint(
                        samples.coeffs, pop_sharding
                    )
                )
                fitnesses = fitness_func(dense_values(samples))
            else:
                samples = distribution_class._sample(key, parameters, num_solutions)
                samples = jax.lax.with_sharding_constraint(samples, pop_sharding)
                fitnesses = fitness_func(samples)
            weights = rank(fitnesses, ranking_method, higher_is_better=higher_is_better)
            grads = distribution_class._compute_gradients(
                parameters, samples, weights, ranking_method
            )
            if with_aux:
                aux = {"mean_eval": jnp.mean(fitnesses)}
                if lowrank_rank is not None:
                    # the global basis, for the caller's subspace-exhaustion
                    # diagnostic (basis_capture)
                    aux["basis"] = samples.basis
                return grads, aux
            return grads

        return jax.jit(fn)

    def _build_shard_map(local_popsize: int, static_items: tuple):
        static_params = dict(static_items)

        def local(key, array_params):
            parameters = {**array_params, **static_params}
            my_key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
            if lowrank_rank is not None:
                samples = distribution_class._sample_lowrank(
                    my_key, parameters, local_popsize, lowrank_rank
                )
                fitnesses = fitness_func(dense_values(samples))
            else:
                samples = distribution_class._sample(my_key, parameters, local_popsize)
                fitnesses = fitness_func(samples)
            weights = rank(fitnesses, ranking_method, higher_is_better=higher_is_better)
            grads = distribution_class._compute_gradients(
                parameters, samples, weights, ranking_method
            )
            out = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axis_name), grads
            )
            if with_aux:
                aux = {"mean_eval": jax.lax.pmean(jnp.mean(fitnesses), axis_name)}
                if lowrank_rank is not None:
                    # each shard's basis rides out stacked along the pop axis
                    # (shard i's rows at [i*L:(i+1)*L]) so the caller can run
                    # the subspace-exhaustion diagnostic on a representative
                    # per-shard basis without an extra collective
                    aux["basis"] = samples.basis
                return out, aux
            return out

        aux_specs = {"mean_eval": P()}
        if lowrank_rank is not None:
            aux_specs["basis"] = P(axis_name)
        return jax.jit(
            jax.shard_map(
                local,
                mesh=mesh,
                in_specs=(P(), P()),
                out_specs=(P(), aux_specs) if with_aux else P(),
                check_vma=False,
            )
        )

    def estimator(key, num_solutions: int, parameters: dict):
        num_solutions = int(num_solutions)
        if legacy:
            if num_solutions % n_shards != 0:
                raise ValueError(
                    f"num_solutions={num_solutions} must be divisible by the mesh axis size {n_shards}"
                )
            build_size = num_solutions // n_shards
        else:
            build_size = num_solutions

        # strings ("divide_mu_grad_by", ...) and structural floats
        # ("parenthood_ratio") are not JAX types: close over them statically
        static_params = {
            k: v
            for k, v in parameters.items()
            if isinstance(v, str) or k == "parenthood_ratio"
        }
        array_params = {k: v for k, v in parameters.items() if k not in static_params}

        cache_key = (build_size, tuple(sorted(static_params.items())))
        fn = compiled.get(cache_key)
        if fn is None:
            builder = _build_shard_map if legacy else _build_global
            fn = compiled[cache_key] = builder(build_size, cache_key[1])
        return fn(key, array_params)

    return estimator
