"""Host-side parallel evaluation: a multiprocessing actor pool.

The TPU mesh path (``parallel/evaluate.py``) covers jax-traceable
objectives; this module covers the reference's other use class — fanning an
*arbitrary Python* fitness function (or a ``GymNE`` rollout) across worker
processes (reference ``core.py:115-270`` ``EvaluationActor``,
``core.py:1977-2052`` ``_parallelize`` + ``ActorPool``, ``core.py:2583-2600``
``map_unordered`` scatter-back). Ray is replaced by ``multiprocessing``
("spawn" start method: forking a process after JAX initialized its backend is
unsafe), and the reference's main<->actor sync protocol
(``core.py:2239-2332``) maps onto the same four Problem hooks it defines:
``_make_sync_data_for_actors`` / ``_use_sync_data_from_main`` /
``_make_sync_data_for_main`` / ``_use_sync_data_from_actors``.

Workers force the CPU jax backend: host-side rollouts are numpy/gym work, and
a worker must never contend for the (single-client) TPU.

Actor-side evaluation composes with the in-process schedulers unchanged: a
``GymNE(num_envs=k)`` clone inside a worker drives its lanes with the
pipelined host scheduler (``net.hostvecenv.run_host_pipelined_rollout`` —
Sebulba overlap + batch-wide lane refill over each worker's piece), and the
obs-norm delta-sync protocol is untouched — the worker still reports exactly
the statistics its lanes consumed, whatever order the scheduler collected
them in (the delta is a sum, so scheduling does not change what merges home).
"""

from __future__ import annotations

import multiprocessing as mp
import pickle
import time
import traceback
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..observability.tracer import span

__all__ = ["HostEvaluatorPool"]

_STARTUP_TIMEOUT = 300.0

_MAIN_GUARD_HINT = (
    "HostEvaluatorPool was constructed inside a child process. This happens "
    "when a script using num_actors is not wrapped in an "
    "`if __name__ == '__main__':` guard: the 'spawn' start method re-imports "
    "the main module in each worker, which would recursively spawn pools. "
    "Wrap the script body in the guard (standard Python multiprocessing "
    "requirement)."
)


def _worker_main(problem_bytes: bytes, seed: int, task_q, result_q):
    # force the CPU backend BEFORE any jax device use: the axon PJRT plugin
    # pins jax_platforms at interpreter startup and the TPU is single-client
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp

    try:
        problem = pickle.loads(problem_bytes)
        problem._num_actors_requested = None  # workers never spawn sub-pools
        problem._is_main = False
        problem.manual_seed(seed)
    except Exception:
        result_q.put(("fatal", -1, traceback.format_exc()))
        return
    result_q.put(("ready", -1, None))

    from ..core import SolutionBatch

    while True:
        msg = task_q.get()
        if msg is None:
            return
        kind, idx, values, sync = msg
        try:
            if sync is not None:
                problem._use_sync_data_from_main(sync)
            if isinstance(values, np.ndarray):
                values = jnp.asarray(values)
            batch = SolutionBatch(problem, len(values), values=values)
            problem.evaluate(batch)
            result_q.put(
                ("ok", idx, np.asarray(batch.evals), problem._make_sync_data_for_main())
            )
        except Exception:
            result_q.put(("error", idx, traceback.format_exc()))


class HostEvaluatorPool:
    """N worker processes, each holding a pickled clone of the Problem
    (exactly the reference's ``EvaluationActor`` arrangement,
    ``core.py:115-270``); tasks are pulled from a shared queue, giving the
    same dynamic load balancing as ``ActorPool.map_unordered``."""

    def __init__(
        self,
        problem,
        num_workers: int,
        *,
        seeds: Optional[Sequence[int]] = None,
        timeout: Optional[float] = 1800.0,
    ):
        if mp.current_process().name != "MainProcess":
            raise RuntimeError(_MAIN_GUARD_HINT)
        self._num_workers = int(num_workers)
        if self._num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        # inactivity cap: if no piece result arrives for `timeout` seconds the
        # round fails instead of blocking forever on a HUNG (not dead) worker
        # (VERDICT r2 weak #7 — the reference inherits Ray's liveness
        # machinery; this is ours). Progress resets the clock only per PIECE,
        # so the default is generous: a single piece must be able to run a
        # full slow host rollout. None disables, relying on worker-death
        # detection alone.
        self._timeout = timeout
        ctx = mp.get_context("spawn")
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        problem_bytes = pickle.dumps(problem)
        if seeds is None:
            seeds = [None] * self._num_workers
        self._procs = []
        for i in range(self._num_workers):
            seed = seeds[i] if seeds[i] is not None else i
            p = ctx.Process(
                target=_worker_main,
                args=(problem_bytes, int(seed), self._task_q, self._result_q),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        self._await_ready()

    def _await_ready(self):
        """Block until every worker finished bootstrapping (unpickled its
        problem clone), failing fast — with the child traceback — if any died
        on the way (e.g. an unpicklable objective, or a script missing its
        ``__main__`` guard)."""
        ready = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT
        while ready < self._num_workers:
            try:
                msg = self._result_q.get(timeout=1.0)
            except Exception:
                if time.monotonic() > deadline:
                    self.shutdown()
                    raise RuntimeError("host evaluation workers timed out during startup")
                if not all(p.is_alive() for p in self._procs):
                    self.shutdown()
                    raise RuntimeError(
                        "a host evaluation worker died during startup. "
                        + _MAIN_GUARD_HINT
                    )
                continue
            status, _, payload = msg
            if status == "fatal":
                self.shutdown()
                raise RuntimeError(f"host evaluation worker failed to start:\n{payload}")
            if status == "ready":
                ready += 1

    @property
    def num_workers(self) -> int:
        return self._num_workers

    @property
    def worker_pids(self) -> List[int]:
        return [p.pid for p in self._procs]

    def is_alive(self) -> bool:
        return any(p.is_alive() for p in self._procs)

    def evaluate_pieces(
        self, pieces_values: Sequence, sync_data: Optional[dict]
    ) -> Tuple[List[np.ndarray], List[dict]]:
        """Evaluate the value arrays of each piece; returns per-piece eval
        matrices (in piece order) and the unordered list of per-worker sync
        payloads (one per piece). Any failure shuts the pool down, so stale
        in-flight results can never bleed into a later round."""
        try:
            return self._evaluate_pieces(pieces_values, sync_data)
        except Exception:
            self.shutdown()
            raise

    def _evaluate_pieces(self, pieces_values, sync_data):
        # prepare ALL transport payloads before enqueuing anything: a
        # conversion error must not leave orphan tasks in flight
        import jax

        transport = []
        with span("hostpool.dispatch", "hostpool", pieces=len(pieces_values)):
            for values in pieces_values:
                if isinstance(values, jax.Array):  # jax array -> numpy for pickling
                    values = np.asarray(values)
                transport.append(values)  # ObjectArray and ndarray both pickle
            n = len(transport)
            for i, v in enumerate(transport):
                self._task_q.put(("eval", i, v, sync_data))
        evals: List[Optional[np.ndarray]] = [None] * n
        sync_back: List[dict] = []
        received = 0
        deadline = None if self._timeout is None else time.monotonic() + self._timeout
        # the actor-sync window: the main process blocks here gathering the
        # per-piece results + obs-stat deltas from the worker processes
        with span("hostpool.sync", "hostpool", pieces=n):
            while received < n:
                try:
                    msg = self._result_q.get(timeout=1.0)
                except Exception as e:
                    if not all(p.is_alive() for p in self._procs):
                        raise RuntimeError(
                            "a host evaluation worker died mid-evaluation"
                        ) from e
                    if deadline is not None and time.monotonic() > deadline:
                        raise RuntimeError("host evaluation pool timed out") from e
                    continue
                status, idx, *payload = msg
                if status != "ok":
                    raise RuntimeError(f"host evaluation worker failed:\n{payload[-1]}")
                evals[idx] = payload[0]
                sync_back.append(payload[1])
                received += 1
                if deadline is not None:
                    deadline = time.monotonic() + self._timeout  # progress resets it
        return evals, sync_back

    def shutdown(self):
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        self._procs = []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
