"""Host-side parallel evaluation: a multiprocessing actor pool.

The TPU mesh path (``parallel/evaluate.py``) covers jax-traceable
objectives; this module covers the reference's other use class — fanning an
*arbitrary Python* fitness function (or a ``GymNE`` rollout) across worker
processes (reference ``core.py:115-270`` ``EvaluationActor``,
``core.py:1977-2052`` ``_parallelize`` + ``ActorPool``, ``core.py:2583-2600``
``map_unordered`` scatter-back). Ray is replaced by ``multiprocessing``
("spawn" start method: forking a process after JAX initialized its backend is
unsafe), and the reference's main<->actor sync protocol
(``core.py:2239-2332``) maps onto the same four Problem hooks it defines:
``_make_sync_data_for_actors`` / ``_use_sync_data_from_main`` /
``_make_sync_data_for_main`` / ``_use_sync_data_from_actors``.

Workers force the CPU jax backend: host-side rollouts are numpy/gym work, and
a worker must never contend for the (single-client) TPU.

Actor-side evaluation composes with the in-process schedulers unchanged: a
``GymNE(num_envs=k)`` clone inside a worker drives its lanes with the
pipelined host scheduler (``net.hostvecenv.run_host_pipelined_rollout`` —
Sebulba overlap + batch-wide lane refill over each worker's piece), and the
obs-norm delta-sync protocol is untouched — the worker still reports exactly
the statistics its lanes consumed, whatever order the scheduler collected
them in (the delta is a sum, so scheduling does not change what merges home).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import signal
import time
import traceback
from collections import deque
from multiprocessing.connection import wait as _conn_wait
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..observability.tracer import span
from ..resilience.faults import fault_point

__all__ = ["HostEvaluatorPool"]

_STARTUP_TIMEOUT = 300.0

_MAIN_GUARD_HINT = (
    "HostEvaluatorPool was constructed inside a child process. This happens "
    "when a script using num_actors is not wrapped in an "
    "`if __name__ == '__main__':` guard: the 'spawn' start method re-imports "
    "the main module in each worker, which would recursively spawn pools. "
    "Wrap the script body in the guard (standard Python multiprocessing "
    "requirement)."
)


def _worker_main(problem_bytes: bytes, seed: int, conn):
    # force the CPU backend BEFORE any jax device use: the axon PJRT plugin
    # pins jax_platforms at interpreter startup and the TPU is single-client
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # graftlint: allow(swallow): platform may be pre-pinned; the worker only must never touch the TPU
        pass
    try:
        problem = pickle.loads(problem_bytes)
        problem._num_actors_requested = None  # workers never spawn sub-pools
        problem._is_main = False
        problem.manual_seed(seed)
    except Exception:
        conn.send(("fatal", -1, traceback.format_exc()))
        return
    conn.send(("ready", -1, None))

    from ..core import SolutionBatch

    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):  # the main process went away
            return
        if msg is None:
            return
        kind, idx, values, sync = msg
        try:
            if sync is not None:
                problem._use_sync_data_from_main(sync)
            # hand the numpy values straight to SolutionBatch: it asarray()s
            # with the problem dtype, and numpy into a jitted eval dispatch
            # is ~3x cheaper than a jnp.asarray round trip first (r7)
            batch = SolutionBatch(problem, len(values), values=values)
            problem.evaluate(batch)
            result = (
                "ok", idx, np.asarray(batch.evals), problem._make_sync_data_for_main()
            )
        except Exception:
            result = ("error", idx, traceback.format_exc())
        try:
            conn.send(result)
        except (EOFError, OSError):  # the main process went away
            return


class HostEvaluatorPool:
    """N worker processes, each holding a pickled clone of the Problem
    (exactly the reference's ``EvaluationActor`` arrangement,
    ``core.py:115-270``); pieces are handed out one at a time over
    per-worker pipes (a pull scheduler: each finished piece fetches the
    next), giving the same dynamic load balancing as
    ``ActorPool.map_unordered``. Per-worker pipes instead of shared queues
    is a fault-tolerance decision, not a style one: an ``mp.Queue`` reader
    holds the queue's shared lock WHILE blocked in ``get()``, so a worker
    SIGKILL'd at the wrong moment (OOM killer, fault injection) leaves the
    lock held forever and deadlocks every sibling — with pipes, a death can
    only sever the dead worker's own channel, which the respawn path
    discards along with the corpse (docs/resilience.md)."""

    def __init__(
        self,
        problem,
        num_workers: int,
        *,
        seeds: Optional[Sequence[int]] = None,
        timeout: Optional[float] = 1800.0,
    ):
        if mp.current_process().name != "MainProcess":
            raise RuntimeError(_MAIN_GUARD_HINT)
        self._num_workers = int(num_workers)
        if self._num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        # inactivity cap: if no piece result arrives for `timeout` seconds the
        # round fails instead of blocking forever on a HUNG (not dead) worker
        # (VERDICT r2 weak #7 — the reference inherits Ray's liveness
        # machinery; this is ours). Progress resets the clock only per PIECE,
        # so the default is generous: a single piece must be able to run a
        # full slow host rollout. None disables, relying on worker-death
        # detection alone.
        self._timeout = timeout
        self._ctx = mp.get_context("spawn")
        # kept for respawn-and-redispatch: a dead worker is replaced by a
        # fresh clone built from the same pickled problem + the same seed,
        # so a respawned worker is behaviorally the worker it replaces
        self._problem_bytes = pickle.dumps(problem)
        if seeds is None:
            seeds = [None] * self._num_workers
        self._seeds = [
            int(seeds[i]) if seeds[i] is not None else i
            for i in range(self._num_workers)
        ]
        # lifetime respawn cap: tolerate transient deaths, but a worker that
        # keeps dying (a deterministically-crashing objective) must
        # eventually fail the round instead of thrashing forever
        self._respawn_budget = 2 * self._num_workers
        self._procs = []
        self._conns = []
        for seed in self._seeds:
            proc, conn = self._spawn(seed)
            self._procs.append(proc)
            self._conns.append(conn)
        self._await_ready()

    def _spawn(self, seed: int):
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        p = self._ctx.Process(
            target=_worker_main,
            args=(self._problem_bytes, int(seed), child_conn),
            daemon=True,
        )
        p.start()
        # close the parent's copy of the child end so a dead worker's pipe
        # EOFs instead of blocking (EOF is the death signal the sync loop
        # reads)
        child_conn.close()
        return p, parent_conn

    def _worker_index(self, conn) -> int:
        for i, c in enumerate(self._conns):
            if c is conn:
                return i
        raise KeyError("connection does not belong to this pool")

    def _respawn_dead(self, pending, inflight, evals, broken=()) -> int:
        """Replace every dead worker with a same-seed clone on a FRESH pipe
        and put its unfinished piece back on the pending queue; returns how
        many were respawned (0 = everyone is alive). ``broken`` lists worker
        indices whose pipe already failed — their process is reaped here
        even if it has not fully exited yet."""
        from ..observability.registry import counters

        respawned = 0
        for wi, proc in enumerate(self._procs):
            if proc.is_alive() and wi not in broken:
                continue
            if proc.is_alive():  # severed pipe but lingering process
                os.kill(proc.pid, signal.SIGKILL)
                proc.join(timeout=10)
            counters.increment("hostpool.worker_deaths")
            if self._respawn_budget <= 0:
                raise RuntimeError(
                    "a host evaluation worker died mid-evaluation and the "
                    f"respawn budget ({2 * self._num_workers}) is exhausted — "
                    "the objective is likely crashing deterministically"
                )
            self._respawn_budget -= 1
            # the piece that died with the worker goes back to the front of
            # the queue; duplicates (a piece the worker finished but whose
            # result was torn mid-send) resolve first-wins in the sync loop
            piece = inflight[wi]
            inflight[wi] = None
            if piece is not None and evals[piece] is None:
                counters.increment("hostpool.redispatched_pieces")
                pending.appendleft(piece)
            try:
                self._conns[wi].close()  # the corpse's pipe end
            except Exception:  # graftlint: allow(swallow): already-severed pipe; closing is best-effort fd hygiene
                pass
            with span("hostpool.respawn", "hostpool", worker=wi, exitcode=proc.exitcode):
                self._procs[wi], self._conns[wi] = self._spawn(self._seeds[wi])
            counters.increment("hostpool.respawns")
            respawned += 1
        return respawned

    def _await_ready(self):
        """Block until every worker finished bootstrapping (unpickled its
        problem clone), failing fast — with the child traceback — if any died
        on the way (e.g. an unpicklable objective, or a script missing its
        ``__main__`` guard)."""
        ready: set = set()
        deadline = time.monotonic() + _STARTUP_TIMEOUT
        while len(ready) < self._num_workers:
            if time.monotonic() > deadline:
                self.shutdown()
                raise RuntimeError("host evaluation workers timed out during startup")
            waiting = [c for i, c in enumerate(self._conns) if i not in ready]
            for conn in _conn_wait(waiting, timeout=1.0):
                wi = self._worker_index(conn)
                try:
                    msg = conn.recv()
                except Exception:
                    self.shutdown()
                    raise RuntimeError(
                        "a host evaluation worker died during startup. "
                        + _MAIN_GUARD_HINT
                    )
                status, _, payload = msg
                if status == "fatal":
                    self.shutdown()
                    raise RuntimeError(
                        f"host evaluation worker failed to start:\n{payload}"
                    )
                if status == "ready":
                    ready.add(wi)

    @property
    def num_workers(self) -> int:
        return self._num_workers

    @property
    def worker_pids(self) -> List[int]:
        return [p.pid for p in self._procs]

    def is_alive(self) -> bool:
        return any(p.is_alive() for p in self._procs)

    def evaluate_pieces(
        self, pieces_values: Sequence, sync_data: Optional[dict]
    ) -> Tuple[List[np.ndarray], List[dict]]:
        """Evaluate the value arrays of each piece; returns per-piece eval
        matrices (in piece order) and the unordered list of per-worker sync
        payloads (one per piece). Any failure shuts the pool down, so stale
        in-flight results can never bleed into a later round."""
        try:
            return self._evaluate_pieces(pieces_values, sync_data)
        except Exception:
            self.shutdown()
            raise

    def _evaluate_pieces(self, pieces_values, sync_data):
        # prepare ALL transport payloads before dispatching anything: a
        # conversion error must not leave orphan tasks in flight
        import jax

        transport = []
        for values in pieces_values:
            if isinstance(values, jax.Array):  # jax array -> numpy for pickling
                values = np.asarray(values)
            transport.append(values)  # ObjectArray and ndarray both pickle
        n = len(transport)
        evals: List[Optional[np.ndarray]] = [None] * n
        sync_back: List[dict] = []
        pending = deque(range(n))
        inflight: List[Optional[int]] = [None] * self._num_workers

        def dispatch(wi: int) -> None:
            # hand the next pending piece to worker `wi`; a send that fails
            # (the worker just died) puts the piece back, and the death
            # sweep below respawns the worker and re-dispatches to the clone
            if inflight[wi] is not None or not pending:
                return
            i = pending.popleft()
            try:
                self._conns[wi].send(("eval", i, transport[i], sync_data))
            except (OSError, ValueError):
                pending.appendleft(i)
            else:
                inflight[wi] = i

        with span("hostpool.dispatch", "hostpool", pieces=n):
            for wi in range(self._num_workers):
                dispatch(wi)
        # deterministic worker-death injection (docs/resilience.md):
        # EVOTORCH_FAULTS="hostpool.worker:kill@R[:W]" SIGKILLs worker W at
        # the R-th round, exercising the respawn-and-redispatch path below
        rule = fault_point("hostpool.worker")
        if rule is not None and rule.kind == "kill" and self._procs:
            victim = self._procs[int(rule.float_arg(0)) % len(self._procs)]
            os.kill(victim.pid, signal.SIGKILL)
        received = 0
        deadline = None if self._timeout is None else time.monotonic() + self._timeout
        # the actor-sync window: the main process blocks here gathering the
        # per-piece results + obs-stat deltas from the worker processes
        with span("hostpool.sync", "hostpool", pieces=n):
            while received < n:
                try:
                    readable = _conn_wait(list(self._conns), timeout=1.0)
                except OSError:
                    readable = []
                broken: List[int] = []
                results = []
                for conn in readable:
                    wi = self._worker_index(conn)
                    try:
                        results.append((wi, conn.recv()))
                    except Exception:  # graftlint: allow(swallow): EOF/torn message = worker death; _respawn_dead counts it in hostpool.worker_deaths
                        # the worker is gone, and only ITS channel dies with
                        # it (per-worker pipes exist exactly so a death can
                        # poison nothing shared)
                        broken.append(wi)
                if broken or not all(p.is_alive() for p in self._procs):
                    # respawn same-seed clones on fresh pipes, re-queue their
                    # in-flight pieces, and hand the clones work immediately
                    # (the task waits in the pipe buffer while they boot)
                    self._respawn_dead(pending, inflight, evals, broken)
                    for wi in range(self._num_workers):
                        dispatch(wi)
                    if deadline is not None:
                        deadline = time.monotonic() + self._timeout
                for wi, msg in results:
                    status, idx, *payload = msg
                    if status == "ready":  # a respawned worker finished booting
                        dispatch(wi)
                        continue
                    if status != "ok":
                        raise RuntimeError(
                            f"host evaluation worker failed:\n{payload[-1]}"
                        )
                    if inflight[wi] == idx:
                        inflight[wi] = None
                    if evals[idx] is None:  # duplicate after redispatch loses
                        evals[idx] = payload[0]
                        sync_back.append(payload[1])
                        received += 1
                        if deadline is not None:
                            deadline = time.monotonic() + self._timeout
                    dispatch(wi)
                if (
                    not readable
                    and deadline is not None
                    and time.monotonic() > deadline
                ):
                    raise RuntimeError("host evaluation pool timed out")
        return evals, sync_back

    def shutdown(self):
        for conn in self._conns:
            try:
                conn.send(None)
            except Exception:  # graftlint: allow(swallow): pipe may already be severed during teardown; shutdown is best-effort
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for conn in self._conns:
            try:
                conn.close()
            except Exception:  # graftlint: allow(swallow): pipe may already be severed during teardown; shutdown is best-effort
                pass
        self._procs = []
        self._conns = []

    def __del__(self):
        try:
            self.shutdown()
        except Exception:  # graftlint: allow(swallow): destructor during interpreter teardown must never raise
            pass
