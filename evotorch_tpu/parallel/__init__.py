"""Parallel execution layer (L3): SPMD over the TPU device mesh.

This module replaces the reference's entire Ray actor layer
(``core.py:115-356`` ``EvaluationActor``, ``core.py:1977-2052``
``Problem._parallelize`` + ``ActorPool``, ``core.py:2762-3073`` distributed
gradient sampling, and the main<->actor sync protocol ``core.py:2239-2332``)
with XLA collectives over a ``jax.sharding.Mesh``:

- population evaluation  -> ``shard_map`` over the population axis
  (one program, population rows sharded across devices via ICI);
- ES-gradient estimation -> local sample/evaluate/rank/grad per shard,
  then ``pmean`` (this *is* the reference's weighted average of per-actor
  gradients, ``gaussian.py:246-271``, expressed as a collective);
- obs-norm stat merging  -> ``psum`` of (count, sum, sumsq) — see
  ``neuroevolution.net.runningnorm``;
- multi-host             -> ``jax.distributed.initialize`` over DCN.

For objectives that are *not* jax-traceable (arbitrary Python fitness
functions, classic gym rollouts), ``hostpool.HostEvaluatorPool`` provides the
reference's actor-pool behavior with plain worker processes.
"""

from .mesh import default_mesh, device_count, make_mesh
from .evaluate import (
    make_sharded_evaluator,
    make_sharded_rollout_evaluator,
    shard_population,
)
from .grad import make_sharded_grad_estimator
from .hostpool import HostEvaluatorPool
from .distributed import init_distributed

__all__ = [
    "default_mesh",
    "device_count",
    "make_mesh",
    "make_sharded_evaluator",
    "make_sharded_rollout_evaluator",
    "shard_population",
    "make_sharded_grad_estimator",
    "HostEvaluatorPool",
    "init_distributed",
]
