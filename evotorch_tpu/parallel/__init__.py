"""Parallel execution layer (L3): SPMD over the TPU device mesh.

This module replaces the reference's entire Ray actor layer
(``core.py:115-356`` ``EvaluationActor``, ``core.py:1977-2052``
``Problem._parallelize`` + ``ActorPool``, ``core.py:2762-3073`` distributed
gradient sampling, and the main<->actor sync protocol ``core.py:2239-2332``)
with GSPMD over a ``jax.sharding.Mesh`` (``docs/sharding.md``):

- population evaluation  -> the GLOBAL program jitted once, population rows
  pinned to the mesh with ``NamedSharding`` / ``with_sharding_constraint``;
  XLA's SPMD partitioner inserts the collectives (the explicit
  ``shard_map`` + ``psum`` form survives behind ``EVOTORCH_SHARD_MAP=1``);
- whole generations      -> ``make_generation_step``: ask -> rollout -> tell
  as ONE donated-buffer program (steady-state HBM = one generation's live
  set, verified by the program ledger);
- ES-gradient estimation -> global sample/rank/grad under GSPMD (the
  reference's single-process semantics at any popsize; the compat knob keeps
  the per-actor local-ranking form, ``gaussian.py:246-271``);
- obs-norm stat merging  -> the global program's cohort IS the mesh-global
  population — see ``neuroevolution.net.runningnorm``;
- multi-host             -> ``jax.distributed.initialize`` over DCN +
  ``dryrun_multihost`` (the 2-process CPU proof in tests/test_multihost.py).

For objectives that are *not* jax-traceable (arbitrary Python fitness
functions, classic gym rollouts), ``hostpool.HostEvaluatorPool`` provides the
reference's actor-pool behavior with plain worker processes.
"""

from .mesh import (
    MESH_AXES,
    default_mesh,
    device_count,
    make_mesh,
    mesh_label,
    parse_mesh_shape,
)
from .evaluate import (
    make_generation_step,
    make_sharded_evaluator,
    make_sharded_rollout_evaluator,
    make_training_span,
    population_spec,
    shard_population,
)
from .grad import make_sharded_grad_estimator
from .hostpool import HostEvaluatorPool
from .distributed import dryrun_multihost, init_distributed

__all__ = [
    "MESH_AXES",
    "default_mesh",
    "device_count",
    "make_mesh",
    "mesh_label",
    "parse_mesh_shape",
    "make_generation_step",
    "make_sharded_evaluator",
    "make_sharded_rollout_evaluator",
    "make_training_span",
    "population_spec",
    "shard_population",
    "make_sharded_grad_estimator",
    "HostEvaluatorPool",
    "init_distributed",
    "dryrun_multihost",
]
